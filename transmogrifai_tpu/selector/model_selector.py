"""ModelSelector: the AutoML sweep.

Parity: reference ``core/.../stages/impl/selector/ModelSelector.scala:72-264``
— an Estimator of (label RealNN, features OPVector) -> Prediction that:
splits data (Splitter/Balancer/Cutter), runs the validator over every
(estimator, param-grid) candidate, refits the winner on the prepared
training data, evaluates train + holdout with every evaluator, and emits a
``ModelSelectorSummary``; the fitted stage is a ``SelectedModel`` wrapping
the winning PredictionModel.

TPU-first (SURVEY §2.7 P3): each candidate family trains its whole
hyperparameter grid AND the whole k-fold CV axis as one stacked vmapped
program (``grid_fit_arrays_folds``) — validation scoring and metrics batch
over [k, G]; the (fold x grid) work units shard 2-D over the mesh (rows on
"data", candidates on "model"). Tree families (RF/GBT) stack too (round
8): the grid groups by compiled-program shape and each depth-group's
whole k folds x L lanes batch trains as ONE program over the dataset-level
bin codes (``tree_stack_scores``), with the HBM guard splitting too-wide
groups into lane chunks. Round 9 collapses the remaining host syncs: the
sweep DISPATCHES every family's stacked program first, holding each
``[k, G]`` metric batch as a device future, then SETTLES them all behind
a single ``jax.block_until_ready`` — families overlap on device and the
entire sweep costs ONE blocking host sync (asserted end-to-end via
``SweepCounters.sweep_host_syncs``) — and the winner refit rides the same
machinery: a G=1 full-data program warm-started from the retained stacked
fold parameters (linear/GLM/MLP; trees reuse the dataset-level bin codes
bitwise) with donated init buffers, checkpointed under a shape-keyed
refit entry. Custom subclasses that override the per-fold trainers,
multiclass scoring, and batches that would not fit HBM at even one lane
fall back to a sequential per-fold loop (compile once, run k times). No
thread pool, no executor dispatch. See PERF.md "Sweep execution model"
and docs/SWEEP.md.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu.evaluators.base import EvaluatorBase
from transmogrifai_tpu.models.base import PredictionModel, Predictor
from transmogrifai_tpu.selector.splitters import DataSplitter
from transmogrifai_tpu.selector.validator import OpCrossValidation
from transmogrifai_tpu.stages.base import Estimator
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["ModelSelector", "SelectedModel", "ModelSelectorSummary",
           "ModelEvaluation"]


@dataclass
class ModelEvaluation:
    model_name: str
    model_uid: str
    model_type: str
    params: dict
    metric_values: dict


@dataclass
class ModelSelectorSummary:
    validation_type: str
    validation_metric: str
    best_model_uid: str
    best_model_name: str
    best_model_type: str
    best_params: dict
    validation_results: list[ModelEvaluation] = field(default_factory=list)
    train_evaluation: dict = field(default_factory=dict)
    holdout_evaluation: dict = field(default_factory=dict)
    data_prep_results: dict = field(default_factory=dict)
    wall_time_s: float = 0.0
    #: candidates that failed or were skipped during the sweep (reference
    #: maxWait/failed-future semantics): [{"modelName":, "reason":}]
    failures: list = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "validationType": self.validation_type,
            "validationMetric": self.validation_metric,
            "bestModelUID": self.best_model_uid,
            "bestModelName": self.best_model_name,
            "bestModelType": self.best_model_type,
            "bestModelParams": _jsonable(self.best_params),
            "validationResults": [
                {"modelName": r.model_name, "modelUID": r.model_uid,
                 "modelType": r.model_type, "modelParams": _jsonable(r.params),
                 "metricValues": _jsonable(r.metric_values)}
                for r in self.validation_results],
            "trainEvaluation": _jsonable(self.train_evaluation),
            "holdoutEvaluation": _jsonable(self.holdout_evaluation),
            "dataPrepResults": _jsonable(self.data_prep_results),
            "wallTimeSeconds": self.wall_time_s,
            "failures": _jsonable(self.failures),
        }

    @staticmethod
    def from_json(d: dict) -> "ModelSelectorSummary":
        return ModelSelectorSummary(
            validation_type=d.get("validationType", ""),
            validation_metric=d.get("validationMetric", ""),
            best_model_uid=d.get("bestModelUID", ""),
            best_model_name=d.get("bestModelName", ""),
            best_model_type=d.get("bestModelType", ""),
            best_params=d.get("bestModelParams", {}),
            validation_results=[
                ModelEvaluation(
                    model_name=r.get("modelName", ""),
                    model_uid=r.get("modelUID", ""),
                    model_type=r.get("modelType", ""),
                    params=r.get("modelParams", {}),
                    metric_values=r.get("metricValues", {}))
                for r in d.get("validationResults", [])],
            train_evaluation=d.get("trainEvaluation", {}),
            holdout_evaluation=d.get("holdoutEvaluation", {}),
            data_prep_results=d.get("dataPrepResults", {}),
            wall_time_s=d.get("wallTimeSeconds", 0.0),
            failures=d.get("failures", []),
        )


class _FoldStackFallback(Exception):
    """Internal: a family opted into the stacked path but produced no
    batched fold scores (e.g. multiclass margins) — reroute it through the
    per-fold loop instead of recording a failure."""


def _jsonable(x: Any) -> Any:
    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, np.integer):
        return int(x)
    if isinstance(x, (float, np.floating)):
        # NaN/inf (diverged candidates) would serialize as bare NaN tokens —
        # invalid strict JSON for non-Python manifest consumers
        f = float(x)
        return f if np.isfinite(f) else None
    if isinstance(x, np.ndarray):
        return _jsonable(x.tolist())
    return x


class SelectedModel(PredictionModel):
    """The fitted winner; delegates to the wrapped PredictionModel."""

    def __init__(self, model: Optional[PredictionModel] = None,
                 summary: Optional[ModelSelectorSummary] = None,
                 uid: Optional[str] = None):
        self.model = model
        self.summary = summary
        super().__init__(uid=uid)

    def device_params(self):
        return self.model.device_params()

    def quantize_device_params(self, precision):
        return self.model.quantize_device_params(precision)

    def device_apply(self, params, col):
        return self.model.device_apply(params, col)

    def transform_row(self, *values):
        return self.model.transform_row(*values)

    def config(self):
        return {"model_class": type(self.model).__name__,
                "model_module": type(self.model).__module__,
                "model_config": self.model.config(),
                "summary": self.summary.to_json() if self.summary else None}

    @classmethod
    def from_config(cls, config, uid=None):
        import importlib
        from transmogrifai_tpu.stages.base import STAGE_REGISTRY
        name = config["model_class"]
        if name not in STAGE_REGISTRY:
            # the registry fills on import: try the recorded module first,
            # then every model family shipped in-package (covers manifests
            # whose recorded module was since renamed)
            candidates = ([config["model_module"]]
                          if config.get("model_module") else [])
            candidates += ["transmogrifai_tpu.models.linear",
                           "transmogrifai_tpu.models.trees",
                           "transmogrifai_tpu.models.extras"]
            for mod in candidates:
                try:
                    importlib.import_module(mod)
                except ImportError:
                    continue
                if name in STAGE_REGISTRY:
                    break
            else:
                raise KeyError(
                    f"Unknown model class {name!r}: not found after "
                    f"importing {candidates}; import its module first")
        model_cls = STAGE_REGISTRY[name]
        model = model_cls.from_config(config.get("model_config") or {})
        summary = None
        if config.get("summary"):
            summary = ModelSelectorSummary.from_json(config["summary"])
        return cls(model=model, summary=summary, uid=uid)

    def fitted_state(self):
        return self.model.fitted_state()

    def set_fitted_state(self, state):
        self.model.set_fitted_state(state)


class ModelSelector(Estimator):
    in_types = (ft.RealNN, ft.OPVector)
    out_type = ft.Prediction

    def __init__(self,
                 models_and_grids: Sequence[tuple[Predictor, Sequence[dict]]],
                 validator: Optional[OpCrossValidation] = None,
                 splitter: Optional[DataSplitter] = None,
                 evaluators: Sequence[EvaluatorBase] = (),
                 validation_metric: Optional[str] = None,
                 max_wait_s: Optional[float] = 3600.0,
                 checkpoint_dir: Optional[str] = None,
                 uid: Optional[str] = None):
        if not models_and_grids:
            raise ValueError("ModelSelector needs at least one candidate model")
        self.models_and_grids = [(m, list(g) or [{}]) for m, g in models_and_grids]
        self.validator = validator or OpCrossValidation()
        self.splitter = splitter
        self.evaluators = list(evaluators)
        if not self.evaluators:
            raise ValueError("ModelSelector needs at least one evaluator")
        self.validation_metric = validation_metric or \
            self.evaluators[0].default_metric
        #: sweep wall-clock budget (reference OpValidator.scala:108 maxWait):
        #: once exceeded, remaining candidate families are skipped and
        #: recorded as failures — provided at least one candidate scored
        self.max_wait_s = max_wait_s
        #: restartable sweep (SURVEY §5 failure-detection aux): completed
        #: metric batches persist to ``checkpoint_dir/sweep.json`` — one
        #: per-family key with per-fold value vectors on the fold-stacked
        #: fast path, one (fold, family) key per fold on the fallback loop;
        #: a re-run after a crash skips them (either key layout resumes
        #: under either path).
        #: The file carries a fingerprint of the sweep CONFIG (families,
        #: grids, metric, validator) and entries key on the fold's training
        #: shape — a different configuration ignores the stale file. Point
        #: each distinct dataset at its own directory: same-shaped different
        #: DATA cannot be distinguished from a restart.
        self.checkpoint_dir = checkpoint_dir
        #: degradation-ladder rungs taken this sweep (utils/resources.py):
        #: [{"site", "rung", ...shape}] — persisted into ``sweep.json`` so
        #: a checkpoint records WHICH shapes ran degraded, and a resumed
        #: run's operator can see why replayed values exist at a rung
        self._sweep_degradations: list[dict] = []
        super().__init__(uid=uid)

    # -- sweep checkpointing -------------------------------------------------
    def _ckpt_fingerprint(self) -> str:
        import hashlib
        import json
        spec = {
            "metric": self.validation_metric,
            "validator": type(self.validator).__name__,
            "validator_cfg": {
                k: v for k, v in sorted(vars(self.validator).items())
                if isinstance(v, (int, float, str, bool))},
            "families": [[type(est).__name__, grid]
                         for est, grid in self.models_and_grids],
        }
        return hashlib.sha256(
            json.dumps(spec, sort_keys=True, default=str).encode()
        ).hexdigest()[:16]

    def _ckpt_path(self) -> Optional[str]:
        if not self.checkpoint_dir:
            return None
        import os

        from transmogrifai_tpu.utils.durable import ensure_checkpoint_dir
        if not ensure_checkpoint_dir(self.checkpoint_dir,
                                     "sweep checkpoint"):
            return None
        return os.path.join(self.checkpoint_dir, "sweep.json")

    def _ckpt_load(self) -> dict:
        path = self._ckpt_path()
        if path is None:
            return {}
        import json
        import os
        if not os.path.exists(path):
            return {}
        try:
            with open(path) as fh:
                raw = json.load(fh)
            if raw.get("fingerprint") != self._ckpt_fingerprint():
                return {}  # different sweep config: stale checkpoint
            return {k: [float("nan") if v is None else float(v)
                        for v in vals]
                    for k, vals in raw["entries"].items()}
        except Exception as e:  # noqa: BLE001 — malformed/truncated file
            # must cost a fresh sweep, never a crashed run — but silently
            # eating it would hide real corruption from operators
            import warnings
            warnings.warn(
                f"sweep checkpoint: unreadable state at {path!r} "
                f"({type(e).__name__}: {e}); starting the sweep fresh",
                RuntimeWarning)
            return {}

    def _ckpt_save(self, done: dict) -> None:
        """Best-effort, atomic (``utils.durable``): a checkpoint write
        failure must never fail a sweep whose training succeeded."""
        path = self._ckpt_path()
        if path is None:
            return
        from transmogrifai_tpu.utils.durable import (
            atomic_json_dump, best_effort_checkpoint_write,
        )

        def write() -> None:
            clean = {k: [v if np.isfinite(v) else None for v in vals]
                     for k, vals in done.items()}
            atomic_json_dump({"fingerprint": self._ckpt_fingerprint(),
                              "entries": clean,
                              "degradations":
                                  list(self._sweep_degradations)},
                             path, allow_nan=False)

        best_effort_checkpoint_write(
            write, "sweep checkpoint write failed; continuing without "
                   "checkpointing")

    def _degrade(self, site: str, rung: str,
                 error: Optional[BaseException] = None, **shape) -> None:
        """Take one degradation-ladder rung (utils/resources.py): count +
        flight-recorder event + warning, and append to the sweep's rung
        log so the next checkpoint write records it."""
        from transmogrifai_tpu.utils.resources import record_degradation
        record_degradation(site, rung, error=error, **shape)
        self._sweep_degradations.append({"site": site, "rung": rung,
                                         **shape})

    @staticmethod
    def _oom_ladder(err: BaseException) -> bool:
        """True when ``err`` is an allocation failure AND the ladder is
        on — the condition under which a failing unit retries one rung
        down instead of recording a candidate failure."""
        from transmogrifai_tpu.utils.resources import (
            is_resource_exhausted, ladder_enabled,
        )
        return ladder_enabled() and is_resource_exhausted(err)

    # -- shared pieces -------------------------------------------------------
    def _split_prepare(self, n: int, y) -> tuple[np.ndarray, np.ndarray,
                                                 np.ndarray, dict]:
        """(train_idx, holdout_idx, train weights, prep summary)."""
        if self.splitter is not None:
            # pull the label to host only when the splitter actually needs it
            y_np = np.asarray(y) if getattr(self.splitter, "requires_label",
                                            True) else None
            train_idx, holdout_idx = self.splitter.split_indices(n, y_np)
            train_idx, w_train = self.splitter.prepare_indices(
                train_idx, y_np)
            prep = {self.splitter.summary.splitter:
                    self.splitter.summary.detail} \
                if self.splitter.summary else {}
            return train_idx, holdout_idx, w_train, prep
        return (np.arange(n), np.zeros(0, dtype=np.int64),
                np.ones(n, dtype=np.float32), {})

    # -- sweep ---------------------------------------------------------------
    def _family_name(self, ci: int) -> str:
        return f"{type(self.models_and_grids[ci][0]).__name__}_{ci}"

    @staticmethod
    def _stacking_default(env_var: str) -> bool:
        """Shared gating policy for both stacked fast paths: the env var
        forces either way (A/B reruns, parity checks); otherwise ON where
        the win lives — accelerator backends and active meshes (the
        saving is k-or-k x L fewer dispatches + host syncs, which a
        tunneled TPU pays in round trips) — and OFF on plain
        single-device CPU, where the microbenches measure the batched
        programs at ~0.9x the per-fold loop (the CPU default only flips
        if an artifact measures >= 1.0x)."""
        import os
        env = os.environ.get(env_var)
        if env is not None:
            return env != "0"
        from transmogrifai_tpu.parallel import mesh as pmesh
        if pmesh.current_mesh() is not None:
            return True
        import jax
        return jax.default_backend() != "cpu"

    @classmethod
    def _stacked_enabled(cls) -> bool:
        """Linear fold-stacked gating (benchmarks/FOLD_STACKED_SWEEP.json
        measures CPU at ~0.9x -> default OFF there)."""
        return cls._stacking_default("TRANSMOGRIFAI_SWEEP_STACKED")

    @classmethod
    def _tree_stacked_enabled(cls) -> bool:
        """Tree fold x grid-stacked gating
        (benchmarks/TREE_STACKED_SWEEP.json measures CPU at 0.93x ->
        default OFF there; a tree depth-group on the fast path costs one
        dispatch + ONE host sync instead of k x L of each)."""
        return cls._stacking_default("TRANSMOGRIFAI_TREE_STACKED")

    @staticmethod
    def _async_enabled() -> bool:
        """One-sync overlapped dispatch gating (round 9): default ON.
        With it, every stacked family's/depth-group's metric batch is
        held as a DEVICE FUTURE at dispatch and the whole sweep settles
        behind a single ``jax.block_until_ready`` — families overlap on
        device instead of serializing on per-family metric pulls, and
        the entire sweep costs ONE blocking host sync.
        ``TRANSMOGRIFAI_SWEEP_ASYNC=0`` restores the per-family settle
        (A/B reruns, and the behavior every fallback path keeps). Only
        meaningful where a stacked path runs at all (the per-fold loop
        is inherently synchronous)."""
        import os
        return os.environ.get("TRANSMOGRIFAI_SWEEP_ASYNC", "1") != "0"

    @staticmethod
    def _refit_warm_enabled() -> bool:
        """Warm winner-refit gating (round 9): default ON. The selector
        then retains warm-capable families' stacked fold parameters past
        the sweep and the winner refit initializes from them (metrics
        within the artifact-gated 1e-5 of the cold refit; trees reuse
        bin codes bitwise regardless of this knob).
        ``TRANSMOGRIFAI_REFIT_WARM=0`` forces every refit cold —
        bitwise-identical to the pre-round-9 serial refit."""
        import os
        return os.environ.get("TRANSMOGRIFAI_REFIT_WARM", "1") != "0"

    @staticmethod
    def _stacked_hbm_budget() -> float:
        """Byte budget for one family's stacked fold batch.
        ``TRANSMOGRIFAI_SWEEP_HBM_BUDGET`` overrides; otherwise half the
        reported memory limit from the shared ``utils/devicewatch.py``
        census — summed across ALL local devices when a mesh is active
        (the stacked batch shards over it), but device 0's alone without
        one (un-meshed, the batch lands on a single device and an N-
        device sum would admit N×-too-large programs) — or 4 GiB when
        the backend exposes none (CPU)."""
        import os
        env = os.environ.get("TRANSMOGRIFAI_SWEEP_HBM_BUDGET")
        if env:
            return float(env)
        try:
            from transmogrifai_tpu.parallel import mesh as pmesh
            from transmogrifai_tpu.utils.devicewatch import (
                device_memory_census,
            )
            census = device_memory_census()
            if pmesh.current_mesh() is not None:
                limit = float(census["bytesLimit"])
            else:
                devices = census["devices"]
                limit = float(devices[0]["bytesLimit"]) if devices else 0.0
            if limit > 0:
                return 0.5 * limit
        except Exception:  # failure-ok: memory-stats probe; conservative default
            pass
        return float(4 << 30)

    def _stacked_fits_memory(self, k: int, n_tr: int, n_va: int, d: int,
                             est, grid) -> bool:
        """HBM guard for the fold-stacked batch: the k-fold training gather
        (plus a standardized/derived copy and the gradient residency the
        trainers materialize), the stacked validation folds, AND the
        per-grid-lane intermediates the vmapped trainer keeps live (scales
        with k x G x rows x the family's per-row lane width — scores,
        logits, activations) must fit the budget, else the sweep falls back
        to the per-fold loop whose peak is 1/k of this."""
        G = max(len(grid), 1)
        width = est.fold_stack_unit_width(grid)
        need = (4.0 * k * n_tr * max(d, 1) * 3.0
                + 4.0 * k * n_va * max(d, 1)
                + 4.0 * k * (n_tr + n_va) * G * width)
        return need <= self._stacked_hbm_budget()

    def _sweep(self, Xt, yt, wt, yt_np) -> tuple[list[ModelEvaluation],
                                                 list[tuple[float, int, int]],
                                                 list[dict], dict]:
        """Run every (candidate family, grid point) over the validator's
        fold plan; returns per-candidate evaluations, (mean metric, cand,
        grid) triples, recorded failures, and the refit-reuse state
        (retained warm-start parameters + tree bin plans) for
        ``_finalize``.

        Execution model (PERF.md "Sweep execution", round 9): the sweep
        is TWO phases. The DISPATCH phase walks the families and launches
        every stacked program — linear/NB/GLM/MLP fold-stacks
        (``grid_scores_folds_retained``) and tree depth-groups
        (``_family_tree_stacked``) alike — handing each family's ``[k, G]``
        metric batch back as a DEVICE FUTURE; no family blocks the host,
        so their programs overlap on device. The SETTLE phase
        (``_settle``) then materializes every future behind a single
        ``jax.block_until_ready`` — the whole sweep costs ONE blocking
        host sync (``SweepCounters.sweep_host_syncs``), not one per
        family/depth-group. The once-per-sweep label statistics (class
        count, tree base-score stats) are pulled up front so no family
        pays a blocking scalar sync at dispatch.

        ``TRANSMOGRIFAI_SWEEP_ASYNC=0``, a custom evaluator without the
        device metric variant, and every fallback route (per-fold loop,
        HBM-guard refusal under ``TRANSMOGRIFAI_SWEEP_STACKED`` gating)
        keep the pre-round-9 per-family settle. Work units shard 2-D
        over the mesh (rows on "data", fold/grid candidates on "model").

        Semantics preserved exactly from the per-fold loop: failure
        isolation per family (dispatch-time errors isolate immediately;
        settle-time errors re-settle family by family to isolate the
        poisoned program), the ``max_wait_s`` budget (checked at
        dispatch), checkpoint/restart (stacked families checkpoint one
        per-family key carrying per-fold value vectors, written at
        settle), and non-finite-metric exclusion.
        """
        from transmogrifai_tpu.parallel import mesh as pmesh
        refit_state: dict = {"warm": {}, "bin_plans": {}}
        self._sweep_degradations = []
        n = int(Xt.shape[0])
        d = int(Xt.shape[1])
        try:
            tr_idx, va_idx = self.validator.stacked_splits(n, yt_np)
        except ValueError:
            # custom validator with unequal fold shapes: no fold axis exists
            results, mean_metrics, failures = self._sweep_loop(
                self._fold_arrays_iter(Xt, yt, wt, yt_np))
            return results, mean_metrics, failures, refit_state
        k, n_tr = tr_idx.shape
        n_va = int(va_idx.shape[1])
        ev0 = self.evaluators[0]
        fold_metrics = getattr(ev0, "metric_batch_scores_folds", None)
        fold_metrics_dev = getattr(ev0, "metric_batch_scores_folds_device",
                                   None)
        async_on = self._async_enabled() and fold_metrics_dev is not None
        per_candidate_scores: dict[tuple[int, int], list[float]] = {}
        failures: list[dict] = []
        pending: list[dict] = []  # device futures awaiting the one settle
        deadline = (time.time() + self.max_wait_s
                    if self.max_wait_s is not None else None)
        done = self._ckpt_load()
        n_tr_pad = pmesh.pad_rows(n_tr)
        tree_cache: dict = {}  # stacked code/label gathers shared by trees

        try:
            self._dispatch(
                Xt, yt, wt, tr_idx, va_idx, k, n_tr, n_va, d, n_tr_pad,
                done, deadline, per_candidate_scores, failures, pending,
                refit_state, async_on, fold_metrics, fold_metrics_dev,
                tree_cache)
        except BaseException:
            # mid-sweep crash (KeyboardInterrupt, preemption, ...): settle
            # whatever was already dispatched so completed families reach
            # the checkpoint before the crash propagates — the same
            # crash granularity the per-family settle always had (a real
            # SIGKILL can't salvage; it just re-runs those families)
            if pending:
                try:
                    self._settle(pending, done, per_candidate_scores,
                                 failures)
                except Exception:  # noqa: BLE001 failure-ok: salvage is best-effort
                    pass
            raise
        if pending:
            oom_retry: list[int] = []
            self._settle(pending, done, per_candidate_scores, failures,
                         oom_retry=oom_retry)
            # degradation ladder: a family whose stacked program OOMed at
            # settle re-dispatches down the ladder on the per-fold loop
            # (peak HBM 1/k of the stacked batch) instead of recording a
            # candidate failure — completed families' checkpoints are
            # untouched
            for ci in oom_retry:
                est, grid = self.models_and_grids[ci]
                # release the FAILED stacked program's retained fold
                # parameters: they are that program's output buffers —
                # holding them keeps the OOMed program's memory resident
                # through the retry, and a winner refit warm-started
                # from them could materialize a poisoned buffer
                refit_state.get("warm", {}).pop(ci, None)
                from transmogrifai_tpu.utils.tracing import span
                with span("resource.degrade", site="sweep.settle",
                          family=self._family_name(ci), rung="fold_loop"):
                    self._family_fold_loop(
                        ci, est, grid, Xt, yt, wt, tr_idx, va_idx, done,
                        deadline, per_candidate_scores, failures,
                        refit_state=refit_state)
        results, mean_metrics, failures = self._collect_results(
            per_candidate_scores, failures)
        return results, mean_metrics, failures, refit_state

    def _dispatch(self, Xt, yt, wt, tr_idx, va_idx, k, n_tr, n_va, d,
                  n_tr_pad, done, deadline, per_candidate_scores, failures,
                  pending, refit_state, async_on, fold_metrics,
                  fold_metrics_dev, tree_cache) -> None:
        """The sweep's dispatch phase (see ``_sweep``): walk the families,
        replay checkpointed ones, launch every stacked program, and queue
        device metric futures on ``pending``; per-family-settle and loop
        fallbacks record their values inline."""
        from transmogrifai_tpu.models.base import (
            supports_fold_stacking, supports_tree_stacking,
        )
        from transmogrifai_tpu.parallel import mesh as pmesh
        from transmogrifai_tpu.utils.devicewatch import compile_telemetry
        from transmogrifai_tpu.utils.profiling import sweep_counters
        from transmogrifai_tpu.utils.retry import with_device_retry
        from transmogrifai_tpu.utils.tracing import span
        stacked_data = None  # built on the first stacked-capable family
        n_classes_hint = None  # once-per-sweep label pulls (O(1), uncounted)
        tree_stats = None
        with span("sweep.dispatch", families=len(self.models_and_grids),
                  mode="async" if async_on else "per_family"):
            for ci, (est, grid) in enumerate(self.models_and_grids):
                fname = self._family_name(ci)
                skey = f"{ci}:stacked:{k}x{n_tr}x{d}"
                if skey in done and len(done[skey]) == k * len(grid):
                    # restart path: this family's whole fold batch already
                    # scored under the per-family stacked key (fold-major)
                    for f in range(k):
                        for gj in range(len(grid)):
                            per_candidate_scores.setdefault(
                                (ci, gj), []).append(
                                float(done[skey][f * len(grid) + gj]))
                    sweep_counters.count(fname, mode="resumed")
                    continue
                tgroups = (est.tree_stack_groups(grid)
                           if supports_tree_stacking(est) else None)
                if tgroups and self._treestack_replay(ci, tgroups, k, n_tr,
                                                      d, done,
                                                      per_candidate_scores):
                    # restart path: every depth-group of this tree family
                    # already scored under per-group treestack keys —
                    # replays regardless of the current gating, so a
                    # stacked-written checkpoint resumes under the loop
                    # layout too
                    sweep_counters.count(fname, mode="resumed")
                    continue
                fold_keys = [f"{f}:{ci}:{n_tr_pad}x{d}" for f in range(k)]
                if all(fk in done and len(done[fk]) == len(grid)
                       for fk in fold_keys):
                    # restart path: a previous per-fold-loop run completed
                    # this family fold by fold
                    for fk in fold_keys:
                        for gj, val in enumerate(done[fk]):
                            per_candidate_scores.setdefault(
                                (ci, gj), []).append(float(val))
                    sweep_counters.count(fname, mode="resumed")
                    continue
                if self._deadline_skip(ci, grid, deadline,
                                       per_candidate_scores, failures,
                                       pop=False):
                    continue
                use_stacked = (self._stacked_enabled()
                               and fold_metrics is not None
                               and supports_fold_stacking(est)
                               and self._stacked_fits_memory(
                                   k, n_tr, n_va, d, est, grid))
                if use_stacked:
                    if stacked_data is None:
                        # one device gather builds the whole fold batch — no
                        # per-fold Xtr materialization on host; training
                        # rows pad+shard 2-D over the mesh (rows on "data",
                        # folds on "model" when they divide it); validation
                        # folds stay unpadded — metrics must see real rows
                        # only
                        jtr = jnp.asarray(tr_idx)
                        jva = jnp.asarray(va_idx)
                        stacked_data = (
                            pmesh.shard_stacked_training_rows(
                                jnp.take(Xt, jtr, axis=0),
                                jnp.take(yt, jtr, axis=0),
                                jnp.take(wt, jtr, axis=0))
                            + (jnp.take(Xt, jva, axis=0),
                               jnp.take(yt, jva, axis=0)))
                    Xtr_s, ytr_s, wtr_s, Xva_s, yva_s = stacked_data
                    if n_classes_hint is None:
                        # the ONE class-count pull every softmax/NB/MLP
                        # family would otherwise block on at dispatch —
                        # same expression on the same stacked labels, so
                        # threading it is value-identical
                        n_classes_hint = max(
                            int(np.asarray(jnp.max(ytr_s))) + 1, 2)
                    try:
                        with sweep_counters.tracking(fname), \
                                compile_telemetry.building(
                                    f"sweep.family:{fname}"), \
                                span("sweep.family", family=fname,
                                     mode="fold_stacked", folds=k,
                                     grid=len(grid)):
                            # fused unit: stacked train + stacked scores in
                            # one call (no per-(fold, grid) model
                            # materialization — the sweep discards models;
                            # the winner refits), retaining the stacked
                            # parameters as the refit's warm-start handle
                            retain = (self._refit_warm_enabled()
                                      and est.supports_warm_refit())
                            scores, warm = with_device_retry(
                                est.grid_scores_folds_retained, Xtr_s,
                                ytr_s, wtr_s, grid, Xva_s,
                                _n_classes=n_classes_hint, site="sweep.fit")
                            if scores is None:
                                raise _FoldStackFallback()
                            if retain and warm is not None:
                                refit_state["warm"][ci] = warm
                            # the family's [k, G] metric batch: a device
                            # FUTURE on the async path (settled once for
                            # the whole sweep), a host pull otherwise
                            vals_kg = (fold_metrics_dev if async_on
                                       else fold_metrics)(
                                yva_s, scores, self.validation_metric)
                    except _FoldStackFallback:
                        use_stacked = False  # no stacked axis: fold loop
                    except Exception as e:  # noqa: BLE001 — isolation by design
                        from transmogrifai_tpu.utils.faults import (
                            FaultHarnessError,
                        )
                        if isinstance(e, FaultHarnessError):
                            raise  # a preempted process dies, not isolates
                        if self._oom_ladder(e):
                            # degradation ladder: the k-fold stacked batch
                            # exceeded real device memory (the HBM guard's
                            # estimate was optimistic) — retry this family
                            # one rung down on the per-fold loop, whose
                            # peak is 1/k of the stacked gather, instead
                            # of failing the candidate. Any warm handle
                            # the failed unit already retained is the
                            # failed program's output — release it.
                            refit_state["warm"].pop(ci, None)
                            self._degrade(
                                "sweep.stacked", "fold_loop", error=e,
                                family=fname, folds=int(k),
                                grid=len(grid), rows=int(n_tr),
                                cols=int(d))
                            use_stacked = False
                        else:
                            failures.append({
                                "modelName": fname,
                                "reason": f"stacked sweep: "
                                          f"{type(e).__name__}: "
                                          f"{str(e)[:300]}"})
                            continue
                    else:
                        sweep_counters.count(fname, dispatches=1,
                                             mode="fold_stacked")
                        if async_on:
                            pending.append({
                                "kind": "stacked", "ci": ci, "fname": fname,
                                "key": skey, "k": k, "grid_len": len(grid),
                                "chunks": [(0, len(grid), vals_kg)]})
                            sweep_counters.count_run(async_families=1)
                            continue
                        # per-family settle (TRANSMOGRIFAI_SWEEP_ASYNC=0 or
                        # a host-only evaluator): the pre-round-9 behavior
                        flat = [float(v)
                                for v in np.asarray(vals_kg).reshape(-1)]
                        for f in range(k):
                            for gj in range(len(grid)):
                                per_candidate_scores.setdefault(
                                    (ci, gj), []).append(
                                    flat[f * len(grid) + gj])
                        sweep_counters.count(fname, host_syncs=1)
                        sweep_counters.count_run(host_syncs=1)
                        done[skey] = flat
                        self._ckpt_save(done)
                        continue
                if (tgroups and self._tree_stacked_enabled()
                        and fold_metrics is not None):
                    if tree_stats is None:
                        # the tree families' (max, mean, clipped-mean)
                        # label pull, once per sweep — each value produced
                        # by the same device expression the per-family
                        # ``_loss_and_nout`` probe runs, so threading it
                        # is bitwise-identical
                        tree_stats = tuple(np.asarray(jnp.stack(
                            [jnp.max(yt), jnp.mean(yt),
                             jnp.clip(jnp.mean(yt), 1e-6, 1 - 1e-6)])))
                    if self._family_tree_stacked(
                            ci, est, grid, tgroups, Xt, yt, wt, tr_idx,
                            va_idx, done, deadline, per_candidate_scores,
                            failures, tree_cache, async_on=async_on,
                            pending=pending, tree_stats=tree_stats,
                            refit_state=refit_state):
                        continue
                # ---- per-fold fallback loop for this family ----------------
                self._family_fold_loop(
                    ci, est, grid, Xt, yt, wt, tr_idx, va_idx, done,
                    deadline, per_candidate_scores, failures,
                    refit_state=refit_state)

    def _settle(self, pending, done, per_candidate_scores,
                failures, oom_retry: Optional[list] = None) -> None:
        """The ONE settle of the async sweep: block until every dispatched
        family's metric futures are ready — a single
        ``jax.block_until_ready`` over the whole sweep, counted as ONE
        run-level host sync — then materialize, record, and checkpoint
        each family's values (the per-family ``host_syncs`` counter keeps
        its metric-pull meaning: one per family / per tree lane chunk).

        If the barrier itself raises (an async runtime failure inside
        some family's program), families re-settle one by one so the
        poisoned program isolates into ITS family's failure record — the
        same per-family isolation the dispatch phase applies — at the
        cost of per-family barriers for that (already failing) sweep.
        A settle-time failure classified as an allocation OOM (device
        pressure materialized only when the overlapped programs actually
        ran) collects its family into ``oom_retry`` instead — the caller
        re-dispatches those one rung down the degradation ladder."""
        import jax
        from transmogrifai_tpu.utils import devicewatch
        from transmogrifai_tpu.utils.faults import FaultHarnessError
        from transmogrifai_tpu.utils.profiling import sweep_counters
        from transmogrifai_tpu.utils.tracing import span
        with span("sweep.settle",
                  families=len({e["ci"] for e in pending}),
                  units=sum(len(e["chunks"]) for e in pending)), \
                contextlib.ExitStack() as ledger_stack:
            # the dispatch ledger the hang autopsy inventories: one
            # labeled entry per pending family/depth-group, completed as
            # that family settles (or unconditionally on exit — a
            # poisoned program must not leak a phantom in-flight entry)
            for e in pending:
                e["_dw"] = devicewatch.dispatch_ledger.register(
                    "sweep.pending", family=e["fname"],
                    unitKind=e["kind"], units=len(e["chunks"]))
                ledger_stack.callback(
                    devicewatch.dispatch_ledger.complete, e["_dw"])
            barrier_ok = True
            try:
                # the watchdog arms a stall deadline around the ONE
                # blocking sync; it adds no host syncs of its own (the
                # sweepHostSyncs == 1 contract holds armed, counter-
                # asserted in tests + DEVICEWATCH_OVERHEAD.json), and an
                # exception here — e.g. an OOM retried down the ladder —
                # disarms the deadline on block exit
                with devicewatch.guard(
                        "sweep.settle", site="sweep.settle",
                        families=len({e["ci"] for e in pending}),
                        units=sum(len(e["chunks"]) for e in pending)):
                    jax.block_until_ready(
                        [a for e in pending for _c0, _ln, a in e["chunks"]])
                sweep_counters.count_run(host_syncs=1)
            except FaultHarnessError:
                raise  # a preempted process dies; it does not isolate
            except Exception:  # noqa: BLE001 — re-settled per family below
                barrier_ok = False
            failed_cis: set[int] = set()
            for e in pending:
                ci = e["ci"]
                if ci in failed_cis:
                    continue
                try:
                    if not barrier_ok:
                        with devicewatch.guard(
                                "sweep.settle", site="sweep.settle",
                                family=e["fname"]):
                            jax.block_until_ready(
                                [a for _c0, _ln, a in e["chunks"]])
                        sweep_counters.count_run(host_syncs=1)
                    if e["kind"] == "stacked":
                        vals = np.asarray(e["chunks"][0][2])
                    else:  # tree depth-group: reassemble lane chunks
                        vals = np.empty((e["k"], len(e["lanes"])),
                                        np.float64)
                        for c0, ln, arr in e["chunks"]:
                            vals[:, c0:c0 + ln] = np.asarray(arr)
                except FaultHarnessError:
                    raise
                except Exception as err:  # noqa: BLE001 — isolation by design
                    failed_cis.add(ci)
                    grid = self.models_and_grids[ci][1]
                    for gj in range(len(grid)):
                        per_candidate_scores.pop((ci, gj), None)
                    if oom_retry is not None and self._oom_ladder(err):
                        # NB: "kind" would collide with emit()'s own
                        # positional — the event attr is unitKind
                        self._degrade(
                            "sweep.settle", "fold_loop", error=err,
                            family=e["fname"], unitKind=e["kind"])
                        oom_retry.append(ci)
                        continue
                    failures.append({
                        "modelName": e["fname"],
                        "reason": f"async settle: {type(err).__name__}: "
                                  f"{str(err)[:300]}"})
                    continue
                flat = [float(v) for v in vals.reshape(-1)]
                if e["kind"] == "stacked":
                    for f in range(e["k"]):
                        for gj in range(e["grid_len"]):
                            per_candidate_scores.setdefault(
                                (ci, gj), []).append(
                                flat[f * e["grid_len"] + gj])
                    sweep_counters.count(e["fname"], host_syncs=1)
                else:
                    self._record_treestack(per_candidate_scores, ci,
                                           e["lanes"], e["k"], flat)
                    sweep_counters.count(e["fname"],
                                         host_syncs=len(e["chunks"]))
                done[e["key"]] = flat
                self._ckpt_save(done)
                # settled: this family's futures are no longer in flight
                devicewatch.dispatch_ledger.complete(e.get("_dw"))

    # -- fold x grid-stacked tree sweep (round 8) ----------------------------
    @staticmethod
    def _treestack_key(ci: int, gi: int, k: int, n_tr: int, d: int,
                       group: dict) -> str:
        """Per-depth-group checkpoint key. Carries the fold plan AND the
        training shape (``n_tr x d``) like the per-fold and linear
        stacked keys do — same config against reshaped data must
        recompute, not replay stale scores."""
        return (f"{ci}:treestack:{gi}:{k}x{n_tr}x{d}:"
                f"{len(group['lanes'])}x{group['max_depth']}")

    @staticmethod
    def _record_treestack(per_candidate_scores, ci: int, lanes, k: int,
                          flat) -> None:
        """Unpack one depth-group's fold-major ``k x L`` value vector
        into per-candidate score lists — the ONE place the checkpoint
        layout is decoded (replay, group resume, and fresh scoring all
        route through here)."""
        L = len(lanes)
        for f in range(k):
            for li, gj in enumerate(lanes):
                per_candidate_scores.setdefault((ci, gj), []).append(
                    float(flat[f * L + li]))

    def _treestack_replay(self, ci, tgroups, k, n_tr, d, done,
                          per_candidate_scores) -> bool:
        """Replay a tree family whose EVERY depth-group checkpointed under
        the per-group treestack keys (fold-major k x L value vectors).
        True when the whole family was replayed."""
        keys = [self._treestack_key(ci, gi, k, n_tr, d, g)
                for gi, g in enumerate(tgroups)]
        if not all(tk in done and len(done[tk]) == k * len(g["lanes"])
                   for tk, g in zip(keys, tgroups)):
            return False
        for tk, g in zip(keys, tgroups):
            self._record_treestack(per_candidate_scores, ci, g["lanes"],
                                   k, done[tk])
        return True

    def _family_tree_stacked(self, ci, est, grid, tgroups, Xt, yt, wt,
                             tr_idx, va_idx, done, deadline,
                             per_candidate_scores, failures,
                             cache: dict, *, async_on: bool = False,
                             pending: Optional[list] = None,
                             tree_stats=None,
                             refit_state: Optional[dict] = None) -> bool:
        """One tree family's fold x grid-stacked sweep: every depth-group
        (grid lanes sharing one compiled-program shape) trains all
        k folds x L lanes as ONE compiled program over the stacked gather
        of the dataset-level bin codes (``fold_sweep_plan`` — no
        re-binning), scores its validation folds batched, and pulls the
        whole group's ``[k, L]`` metric block with ONE host sync. The HBM
        guard (``tree_stack_bytes``) splits a too-wide group into lane
        chunks (one dispatch + one sync each) instead of falling all the
        way back. Returns True when the family was fully handled (scored,
        group-resumed, failed-and-isolated, or deadline-skipped); False
        routes it to the per-fold loop untouched (multiclass, bin-once
        disabled, or a group where not even one lane fits the budget —
        sub-grid loop units can't be expressed, so the loop keeps the
        whole family)."""
        import inspect
        from transmogrifai_tpu.parallel import mesh as pmesh
        from transmogrifai_tpu.utils.profiling import sweep_counters
        from transmogrifai_tpu.utils.retry import with_device_retry
        from transmogrifai_tpu.utils.tracing import span
        fname = self._family_name(ci)
        # the selector's once-per-sweep label stats elide what was ONE
        # blocking family-level sync here (signature-gated: a subclass
        # overriding the lnb probe with the old arity keeps its own pull)
        if tree_stats is not None and "_stats" in inspect.signature(
                est.tree_stack_scalar_lnb).parameters:
            lnb = est.tree_stack_scalar_lnb(yt, _stats=tree_stats)
        else:
            lnb = est.tree_stack_scalar_lnb(yt)
        if lnb is None:
            return False  # multiclass: no batched scalar score
        k, n_tr = tr_idx.shape
        n_va = int(va_idx.shape[1])
        d = int(Xt.shape[1])
        budget = self._stacked_hbm_budget()
        chunk_sizes = []
        for g in tgroups:
            shared, per_lane = est.tree_stack_bytes(k, n_tr, n_va, d, g)
            max_lanes = (int((budget - shared) // per_lane)
                         if budget > shared and per_lane > 0 else 0)
            if max_lanes < 1:
                return False  # not even one lane fits: loop (peak 1/k)
            chunk_sizes.append(max_lanes)
        import os
        if os.environ.get("TRANSMOGRIFAI_TREE_BIN_ONCE", "1") == "0":
            return False  # exact per-fold edges requested: nothing stacks
        jtr = jnp.asarray(tr_idx)
        jva = jnp.asarray(va_idx)
        if "yva" not in cache:
            cache["yva"] = jnp.take(yt, jva, axis=0)
        yva_s = cache["yva"]
        needed = [mb for mb in sorted({g["max_bins"] for g in tgroups})
                  if mb not in cache]
        if needed:
            # bin codes depend only on (X, max_bins), so the dataset-level
            # plan and its stacked gathers are shared across tree families
            # — only missing max_bins pay the quantile sort + searchsorted
            plan = est.fold_sweep_plan(Xt, grid)
            if plan is None:
                return False
            if refit_state is not None:
                # retained for the winner refit: the SAME codes fit_arrays
                # would recompute from the identical full matrix, so the
                # refit's duplicate quantization pass is deleted bitwise
                refit_state["bin_plans"].update(plan)
        for mb in needed:
            # one stacked fold gather of the dataset-level codes per
            # max_bins — int8 when the codes fit (4x fewer gathered
            # bytes); training rows pad+shard 2-D over the mesh (rows
            # on "data", folds on "model" when they divide it);
            # validation codes stay unpadded — metrics must see real
            # rows only
            _, codes, _ = plan[mb]
            if int(mb) <= 127:
                codes = codes.astype(jnp.int8)
            cache[mb] = (pmesh.shard_stacked_training_rows(
                jnp.take(codes, jtr, axis=0),
                jnp.take(yt, jtr, axis=0),
                jnp.take(wt, jtr, axis=0))
                + (jnp.take(codes, jva, axis=0),))
        ev0 = self.evaluators[0]
        fold_metrics = ev0.metric_batch_scores_folds
        for gi, g in enumerate(tgroups):
            lanes = g["lanes"]
            L = len(lanes)
            depth = g["max_depth"]
            tk = self._treestack_key(ci, gi, k, n_tr, d, g)
            if tk in done and len(done[tk]) == k * L:
                # restart path: this depth-group already scored
                self._record_treestack(per_candidate_scores, ci, lanes,
                                       k, done[tk])
                continue
            if self._deadline_skip(ci, grid, deadline,
                                   per_candidate_scores, failures,
                                   pop=True):
                return True
            Xb_tr, ytr_s, wtr_s, Xb_va = cache[g["max_bins"]]
            if "fold_means" not in cache:
                # the folds' label means feed the host-computed per-fold
                # base scores (bitwise parity with the loop's per-fold
                # ``_loss_and_nout``); ONE uncounted family-level pull
                # per sweep, shared across tree families — the analog of
                # the loop path's per-fold lnb sync
                cache["fold_means"] = np.asarray(jnp.stack(
                    [jnp.mean(ytr_s[f]) for f in range(k)]))
            cs = chunk_sizes[gi]
            ev0_f = self.evaluators[0]
            fold_metrics_dev = getattr(ev0_f,
                                       "metric_batch_scores_folds_device",
                                       None)
            use_async = (async_on and pending is not None
                         and fold_metrics_dev is not None)
            vals_kl = np.empty((k, L), np.float64)
            chunks: list[tuple[int, int, Any]] = []  # async device futures
            cs_cur = cs  # degradation ladder may narrow it mid-group
            from transmogrifai_tpu.utils.devicewatch import (
                compile_telemetry,
            )
            try:
                with sweep_counters.tracking(fname), \
                        compile_telemetry.building(
                            f"sweep.tree:{fname}"):
                    c0 = 0
                    while c0 < L:
                        chunk = g["params"][c0:c0 + cs_cur]
                        try:
                            with span("sweep.tree_group", family=fname,
                                      mode="tree_stacked", k=int(k),
                                      lanes=len(chunk), depth=int(depth),
                                      group=gi):
                                # fused unit: stacked train + stacked
                                # scores in one compiled program (no
                                # per-(fold, lane) model materialization
                                # — the sweep discards models; the
                                # winner refits)
                                scores = with_device_retry(
                                    est.tree_stack_scores, Xb_tr, ytr_s,
                                    wtr_s, Xb_va, chunk, lnb,
                                    fold_means=cache["fold_means"],
                                    site="sweep.fit")
                                # the chunk's [k, Lc] metric batch: a
                                # device FUTURE on the async path
                                # (settled once for the whole sweep),
                                # one host pull otherwise
                                vals = (fold_metrics_dev if use_async
                                        else fold_metrics)(
                                    yva_s, scores, self.validation_metric)
                        except Exception as oom_e:  # noqa: BLE001 — re-raised unless an OOM rung applies
                            from transmogrifai_tpu.utils.faults import (
                                FaultHarnessError,
                            )
                            if isinstance(oom_e, FaultHarnessError):
                                raise
                            if not self._oom_ladder(oom_e) or cs_cur <= 1:
                                raise
                            # degradation ladder: this chunk's k x Lc
                            # stacked program exceeded device memory —
                            # halve the lane-chunk width and retry the
                            # SAME lanes (per-lane values are
                            # vmap-independent: chunk width cannot change
                            # them), leaving every other group/chunk
                            # untouched
                            cs_cur = max(1, cs_cur // 2)
                            self._degrade(
                                "sweep.tree_group",
                                f"lane_chunk_{cs_cur}", error=oom_e,
                                family=fname, group=gi,
                                depth=int(depth), folds=int(k),
                                lanes=len(chunk))
                            continue
                        if use_async:
                            chunks.append((c0, len(chunk), vals))
                        else:
                            vals_kl[:, c0:c0 + len(chunk)] = \
                                np.asarray(vals)
                            sweep_counters.count(fname, host_syncs=1)
                            sweep_counters.count_run(host_syncs=1)
                        sweep_counters.count(
                            fname, dispatches=1, lane_chunks=1,
                            mode="tree_stacked")
                        c0 += len(chunk)
                sweep_counters.count(fname, stacked_groups=1)
            except Exception as e:  # noqa: BLE001 — isolation by design
                from transmogrifai_tpu.utils.faults import FaultHarnessError
                if isinstance(e, FaultHarnessError):
                    raise  # a preempted process dies; it does not isolate
                for gj in range(len(grid)):
                    per_candidate_scores.pop((ci, gj), None)
                if self._oom_ladder(e):
                    # bottom of the stacked rungs: even one lane at a
                    # time OOMs — the whole family falls to the per-fold
                    # loop (peak 1/k). Drop any pending async futures of
                    # this family so the settle can't double-record it.
                    self._degrade("sweep.tree_group", "fold_loop",
                                  error=e, family=fname, group=gi,
                                  depth=int(depth))
                    if pending is not None:
                        pending[:] = [p for p in pending
                                      if p["ci"] != ci]
                    return False
                failures.append({
                    "modelName": fname,
                    "reason": f"tree stacked sweep (group {gi}): "
                              f"{type(e).__name__}: {str(e)[:300]}"})
                return True
            if use_async:
                first_entry = not any(p["ci"] == ci for p in pending)
                pending.append({"kind": "tree", "ci": ci, "fname": fname,
                                "key": tk, "k": k, "lanes": lanes,
                                "chunks": chunks})
                if first_entry:
                    sweep_counters.count_run(async_families=1)
                continue
            flat = [float(v) for v in vals_kl.reshape(-1)]
            self._record_treestack(per_candidate_scores, ci, lanes, k,
                                   flat)
            done[tk] = flat
            self._ckpt_save(done)
        return True

    def _deadline_skip(self, ci, grid, deadline, per_candidate_scores,
                       failures, pop: bool) -> bool:
        """True when the family must be skipped for exceeding the
        ``max_wait_s`` budget (reference maxWait) — never when it is the
        only family with any chance of scoring (a winner must survive).
        ``pop`` drops partial fold scores (a partial-fold mean must not
        compete against full-fold means)."""
        if deadline is None or time.time() <= deadline:
            return False
        if not any(kk[0] != ci for kk in per_candidate_scores):
            return False
        if pop:
            for gj in range(len(grid)):
                per_candidate_scores.pop((ci, gj), None)
        failures.append({
            "modelName": self._family_name(ci),
            "reason": f"skipped: sweep exceeded max_wait_s="
                      f"{self.max_wait_s}"})
        return True

    def _run_fold_unit(self, ci, est, grid, fold_i, Xtr, ytr, wtr, Xva, yva,
                       done, deadline, per_candidate_scores, failures,
                       fit_kwargs=None) -> bool:
        """One (fold, family) train+score+record unit — the shared body of
        the stacked sweep's fallback loop and the legacy fold-major loop:
        checkpoint replay, the mid-family ``max_wait_s`` check (after
        replay — replaying is free and never skipped), failure isolation,
        counter bookkeeping. ``Xtr``/``ytr``/``wtr`` arrive mesh-sharded.
        Returns False when the family is dropped (failed or past budget) —
        the caller skips its remaining folds."""
        from transmogrifai_tpu.utils.profiling import sweep_counters
        from transmogrifai_tpu.utils.retry import with_device_retry
        ev0 = self.evaluators[0]
        batch_metrics = getattr(ev0, "metric_batch_scores", None)
        fname = self._family_name(ci)
        ckey = f"{fold_i}:{ci}:{int(Xtr.shape[0])}x{int(Xtr.shape[1])}"
        if ckey in done and len(done[ckey]) == len(grid):
            # restart path: this (fold, family) batch already scored
            for gj, val in enumerate(done[ckey]):
                per_candidate_scores.setdefault((ci, gj), []).append(
                    float(val))
            return True
        if self._deadline_skip(ci, grid, deadline, per_candidate_scores,
                               failures, pop=True):
            return False
        from transmogrifai_tpu.utils.tracing import span
        try:
            with sweep_counters.tracking(fname), \
                    span("sweep.fold_unit", family=fname, fold=fold_i,
                         grid=len(grid)):
                models = with_device_retry(
                    est.grid_fit_arrays, Xtr, ytr, wtr, grid,
                    site="sweep.fit", **(fit_kwargs or {}))
                scores = (est.grid_predict_scores(models, Xva)
                          if batch_metrics is not None else None)
                if scores is not None:
                    # one device program scores + one computes the metric
                    # for the whole grid; a single host sync per
                    # (fold, family)
                    vals = [float(v) for v in batch_metrics(
                        yva, scores, self.validation_metric)]
                    sweep_counters.count(fname, dispatches=1,
                                         host_syncs=1, mode="fold_loop")
                    sweep_counters.count_run(host_syncs=1)
                else:
                    vals = []
                    for model in models:
                        pred = model.predict_arrays(Xva)
                        # summary-only metric: evaluators skip their
                        # deep report families inside the sweep
                        vals.append(ev0.metric_from_arrays(
                            yva, pred, self.validation_metric))
                    sweep_counters.count(fname, dispatches=1,
                                         host_syncs=max(len(grid), 1),
                                         mode="fold_loop")
                    sweep_counters.count_run(
                        host_syncs=max(len(grid), 1))
        except Exception as e:  # noqa: BLE001 — isolation by design
            from transmogrifai_tpu.utils.faults import FaultHarnessError
            if isinstance(e, FaultHarnessError):
                raise  # a preempted process dies; it does not isolate
            for gj in range(len(grid)):
                per_candidate_scores.pop((ci, gj), None)
            failures.append({
                "modelName": fname,
                "reason": f"fold {fold_i}: {type(e).__name__}: "
                          f"{str(e)[:300]}"})
            return False
        # bookkeeping outside the isolation try: a checkpoint I/O problem
        # must not convert a successful fit into a candidate failure
        # (_ckpt_save is best-effort anyway)
        for gj, val in enumerate(vals):
            per_candidate_scores.setdefault((ci, gj), []).append(val)
        done[ckey] = vals
        self._ckpt_save(done)
        return True

    def _family_fold_loop(self, ci, est, grid, Xt, yt, wt, tr_idx, va_idx,
                          done, deadline, per_candidate_scores,
                          failures, refit_state=None) -> None:
        """One family's sequential per-fold sweep (the fallback path and
        the home of families without a fold axis — tree ensembles, custom
        subclasses). Tree families still avoid re-binning every fold: a
        ``fold_sweep_plan`` computes dataset-level quantile codes once and
        each fold gathers its rows from them (and the winner refit reuses
        the same codes via ``refit_state``)."""
        import inspect
        from transmogrifai_tpu.parallel import mesh as pmesh
        plan = None
        plan_fn = getattr(est, "fold_sweep_plan", None)
        if (plan_fn is not None and pmesh.current_mesh() is None
                and "_fold_plan" in inspect.signature(
                    est.grid_fit_arrays).parameters):
            plan = plan_fn(Xt, grid)
            if plan is not None and refit_state is not None:
                refit_state["bin_plans"].update(plan)
        for fold_i in range(tr_idx.shape[0]):
            jtr = jnp.asarray(tr_idx[fold_i])
            jva = jnp.asarray(va_idx[fold_i])
            # row-parallel training over the mesh: fold rows padded to the
            # data-axis multiple with weight 0 (validation stays unpadded —
            # metrics must see real rows only)
            Xtr, ytr, wtr = pmesh.shard_training_rows(
                Xt[jtr], yt[jtr], wt[jtr])
            fit_kwargs = ({"_fold_plan": plan, "_fold_rows": jtr}
                          if plan is not None else None)
            if not self._run_fold_unit(
                    ci, est, grid, fold_i, Xtr, ytr, wtr, Xt[jva], yt[jva],
                    done, deadline, per_candidate_scores, failures,
                    fit_kwargs=fit_kwargs):
                return

    def _fold_arrays_iter(self, Xt, yt, wt, yt_np):
        for tr, va in self.validator.splits(int(Xt.shape[0]), yt_np):
            jtr, jva = jnp.asarray(tr), jnp.asarray(va)
            yield Xt[jtr], yt[jtr], wt[jtr], Xt[jva], yt[jva]

    def _sweep_loop(self, fold_arrays) -> tuple[list[ModelEvaluation],
                                                list[tuple[float, int, int]],
                                                list[dict]]:
        """Fold-major sequential sweep over materialized fold arrays — the
        legacy path, kept for workflow-level CV (``fit_with_dag`` refits
        feature stages per fold, so fold features differ and cannot stack)
        and for validators without equal fold shapes. Per-(fold, family)
        semantics live in the shared ``_run_fold_unit``."""
        from transmogrifai_tpu.parallel import mesh as pmesh
        per_candidate_scores: dict[tuple[int, int], list[float]] = {}
        failures: list[dict] = []
        failed_families: set[int] = set()
        deadline = (time.time() + self.max_wait_s
                    if self.max_wait_s is not None else None)
        done = self._ckpt_load()
        for fold_i, (Xtr, ytr, wtr, Xva, yva) in enumerate(fold_arrays):
            Xtr, ytr, wtr = pmesh.shard_training_rows(Xtr, ytr, wtr)
            for ci, (est, grid) in enumerate(self.models_and_grids):
                if ci in failed_families:
                    continue
                if not self._run_fold_unit(
                        ci, est, grid, fold_i, Xtr, ytr, wtr, Xva, yva,
                        done, deadline, per_candidate_scores, failures):
                    failed_families.add(ci)
        return self._collect_results(per_candidate_scores, failures)

    def _collect_results(self, per_candidate_scores, failures
                         ) -> tuple[list[ModelEvaluation],
                                    list[tuple[float, int, int]],
                                    list[dict]]:
        results: list[ModelEvaluation] = []
        mean_metrics: list[tuple[float, int, int]] = []
        for (ci, gj), vals in per_candidate_scores.items():
            est, grid = self.models_and_grids[ci]
            mean = float(np.mean(vals))
            name = f"{type(est).__name__}_{ci}_{gj}"
            results.append(ModelEvaluation(
                model_name=name,
                model_uid=est.uid,
                model_type=type(est).__name__,
                params={**est.params, **grid[gj]},
                metric_values={self.validation_metric: mean}))
            if np.isfinite(mean):
                mean_metrics.append((mean, ci, gj))
            else:
                failures.append({
                    "modelName": name,
                    "reason": "non-finite validation metric (diverged fit)"})
        if not mean_metrics:
            raise RuntimeError(
                "ModelSelector: every candidate failed or diverged; "
                f"failures: {failures}")
        return results, mean_metrics, failures

    # -- winner refit (round 9) ----------------------------------------------
    def _refit_ckpt_paths(self) -> Optional[tuple[str, str]]:
        """(json path, npz path) of the refit checkpoint, or None when
        checkpointing is off/unusable."""
        if not self.checkpoint_dir:
            return None
        import os

        from transmogrifai_tpu.utils.durable import ensure_checkpoint_dir
        if not ensure_checkpoint_dir(self.checkpoint_dir,
                                     "refit checkpoint"):
            return None
        return (os.path.join(self.checkpoint_dir, "refit.json"),
                os.path.join(self.checkpoint_dir, "refit.npz"))

    def _refit_ckpt_save(self, rkey: str, model) -> None:
        """Persist the refitted winner (best-effort, atomic): a run
        preempted AFTER the refit but before/while evaluating resumes
        without retraining the winner. Keyed on the sweep-config
        fingerprint plus a shape-carrying refit key (``{ci}:{gj}:refit:
        {n}x{d}``) — same staleness rules as ``sweep.json``."""
        paths = self._refit_ckpt_paths()
        if paths is None:
            return
        from transmogrifai_tpu.serialization import fitted_stage_record
        from transmogrifai_tpu.utils.durable import (
            atomic_json_dump, best_effort_checkpoint_write,
        )

        def write() -> None:
            rec, arrays = fitted_stage_record(model)
            import os
            import tempfile
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(paths[1]),
                                       suffix=".npz.tmp")
            try:
                # a file OBJECT: np.savez appends ".npz" to bare paths,
                # which would leave the mkstemp file empty
                with os.fdopen(fd, "wb") as fh:
                    np.savez(fh, **arrays)
                os.replace(tmp, paths[1])
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)  # failure-ok: leftover tmp cleanup
            atomic_json_dump({"fingerprint": self._ckpt_fingerprint(),
                              "key": rkey, "record": rec}, paths[0])

        best_effort_checkpoint_write(
            write, "refit checkpoint write failed; continuing without it")

    def _refit_ckpt_load(self, rkey: str):
        """The checkpointed refit winner when fingerprint AND refit key
        match, else None (stale/missing/corrupt files cost a fresh refit,
        never a crash)."""
        paths = self._refit_ckpt_paths()
        if paths is None:
            return None
        import json
        import os
        if not (os.path.exists(paths[0]) and os.path.exists(paths[1])):
            return None
        try:
            with open(paths[0]) as fh:
                doc = json.load(fh)
            if doc.get("fingerprint") != self._ckpt_fingerprint() \
                    or doc.get("key") != rkey:
                return None
            from transmogrifai_tpu.serialization import restore_fitted_stage
            with np.load(paths[1], allow_pickle=False) as npz:
                arrays = {k: npz[k] for k in npz.files}
            return restore_fitted_stage(doc["record"], arrays)
        except Exception as e:  # noqa: BLE001 — corrupt ckpt costs a refit
            import warnings
            warnings.warn(
                f"refit checkpoint: unreadable state at {paths[0]!r} "
                f"({type(e).__name__}: {e}); refitting the winner fresh",
                RuntimeWarning)
            return None

    def _refit(self, best_ci: int, best_gj: int, best_params: dict, Xt,
               yt, wt, refit_state: dict):
        """Train the winner on the full prepared data through the stacked
        refit machinery (round 9): resume from the refit checkpoint when
        one matches; otherwise hand the family its retained warm-start
        handle (the sweep's stacked fold parameters, G=1 lane selected by
        ``best_gj``) and the dataset-level tree bin plans via
        ``refit_winner``. Families without reuse run the exact cold
        ``fit_arrays`` the serial path always ran (bitwise). The
        ``selector.refit`` fault site fires after the checkpoint write —
        the preemption seam the chaos suite resumes across."""
        import contextlib

        from transmogrifai_tpu.parallel import mesh as pmesh
        from transmogrifai_tpu.utils.faults import fault_point
        from transmogrifai_tpu.utils.profiling import sweep_counters
        from transmogrifai_tpu.utils.retry import with_device_retry
        from transmogrifai_tpu.utils.tracing import span
        best_est = self.models_and_grids[best_ci][0]
        fname = self._family_name(best_ci)
        n, d = int(Xt.shape[0]), int(Xt.shape[1])
        rkey = f"{best_ci}:{best_gj}:refit:{n}x{d}"
        restored = self._refit_ckpt_load(rkey)
        if restored is not None:
            fault_point("selector.refit")
            return restored
        Xs, ys, ws = pmesh.shard_training_rows(Xt, yt, wt)
        warm = (refit_state.get("warm", {}).get(best_ci)
                if self._refit_warm_enabled() else None)
        hints = {}
        bin_plans = refit_state.get("bin_plans")
        if bin_plans and int(Xs.shape[0]) == n:
            # mesh padding grows the refit rows past the dataset-level
            # codes; the reuse only holds row-for-row
            hints["bin_plans"] = bin_plans
        stacked_refit = warm is not None or bool(hints)
        cm = (span("selector.refit_stacked", family=fname, lane=best_gj,
                   warm=warm is not None)
              if stacked_refit else contextlib.nullcontext())
        from transmogrifai_tpu.utils.devicewatch import compile_telemetry
        try:
            with sweep_counters.tracking(fname), \
                    compile_telemetry.building(
                        f"selector.refit:{fname}"), cm:
                best_model, warm_used = with_device_retry(
                    best_est.refit_winner, Xs, ys, ws, best_params,
                    warm=warm, lane=best_gj, hints=hints or None,
                    site="sweep.fit")
        except Exception as e:  # noqa: BLE001 — re-raised unless an OOM rung applies
            from transmogrifai_tpu.utils.faults import FaultHarnessError
            if isinstance(e, FaultHarnessError) or warm is None \
                    or not self._oom_ladder(e):
                raise
            # degradation ladder: the warm-started refit holds the
            # retained stacked fold parameters live alongside the
            # full-data program's peak — release them and refit COLD
            # (bitwise the pre-round-9 serial refit) instead of dying
            self._degrade("selector.refit", "cold_refit", error=e,
                          family=fname, lane=int(best_gj),
                          rows=int(n), cols=int(d))
            warm = None
            refit_state.get("warm", {}).pop(best_ci, None)
            with sweep_counters.tracking(fname), \
                    compile_telemetry.building(
                        f"selector.refit:{fname}"):
                best_model, warm_used = with_device_retry(
                    best_est.refit_winner, Xs, ys, ws, best_params,
                    warm=None, lane=best_gj, hints=hints or None,
                    site="sweep.fit")
        if warm_used:
            sweep_counters.count_run(refit_warm_starts=1)
        self._refit_ckpt_save(rkey, best_model)
        fault_point("selector.refit")
        return best_model

    def _finalize(self, results, mean_metrics, Xt, yt, wt, Xh, yh,
                  prep_results: dict, t0: float,
                  failures: Optional[list] = None,
                  refit_state: Optional[dict] = None) -> SelectedModel:
        """Refit the winning candidate on the full prepared training data,
        evaluate train + holdout, assemble the summary."""
        ev0 = self.evaluators[0]
        bigger = ev0.larger_is_better(self.validation_metric)
        _, best_ci, best_gj = (max if bigger else min)(
            mean_metrics, key=lambda t: t[0])
        best_est, best_grid = self.models_and_grids[best_ci]
        best_params = {**best_est.params, **best_grid[best_gj]}
        warm_all = (refit_state or {}).get("warm")
        if warm_all:
            # only the winner's handle is ever read — release the losing
            # families' stacked fold parameters before the full-data refit
            # program peaks HBM
            for ci in [c for c in warm_all if c != best_ci]:
                del warm_all[ci]
        best_model = self._refit(best_ci, best_gj, best_params, Xt, yt, wt,
                                 refit_state or {})

        train_eval: dict = {}
        holdout_eval: dict = {}
        pred_train = best_model.predict_arrays(Xt)
        for ev in self.evaluators:
            train_eval[ev.name] = EvaluatorBase.to_json(
                ev.evaluate_arrays(yt, pred_train))
        if Xh is not None and int(Xh.shape[0]):
            pred_h = best_model.predict_arrays(Xh)
            for ev in self.evaluators:
                holdout_eval[ev.name] = EvaluatorBase.to_json(
                    ev.evaluate_arrays(yh, pred_h))

        summary = ModelSelectorSummary(
            validation_type=self.validator.name,
            validation_metric=self.validation_metric,
            best_model_uid=best_est.uid,
            best_model_name=f"{type(best_est).__name__}_{best_ci}_{best_gj}",
            best_model_type=type(best_est).__name__,
            best_params=best_params,
            validation_results=results,
            train_evaluation=train_eval,
            holdout_evaluation=holdout_eval,
            data_prep_results=prep_results,
            wall_time_s=time.time() - t0,
            failures=list(failures or []),
        )
        return SelectedModel(model=best_model, summary=summary)

    def fit_model(self, data) -> SelectedModel:
        from transmogrifai_tpu.dag import _plog
        from transmogrifai_tpu.utils.profiling import OpStep, profiler
        from transmogrifai_tpu.utils.tracing import span as _span
        t0 = time.time()
        label_name, feat_name = self.input_names
        # the ingest->sweep handoff (round 14): with fused FE the feature
        # matrix is already an HBM-resident, rows-on-"data"-sharded device
        # column — the sweep consumes it pre-partitioned, no host pull and
        # no resharding device_put. `presharded` makes that assertable.
        presharded = feat_name in data.device
        with _span("sweep.operands", presharded=presharded,
                   feature=feat_name):
            X = data.device_col(feat_name).values
            y = data.device_col(label_name).values
        n = data.n_rows  # logical rows: device arrays may carry mesh padding

        train_idx, holdout_idx, w_train, prep_results = \
            self._split_prepare(n, y[:n])
        Xt, yt = X[jnp.asarray(train_idx)], y[jnp.asarray(train_idx)]
        wt = jnp.asarray(w_train)
        _plog("selector: split+prepare", t0)

        yt_np = (np.asarray(yt)
                 if getattr(self.validator, "stratify", False) else None)
        t1 = time.time()

        from transmogrifai_tpu.utils.tracing import span
        with profiler.phase(OpStep.CROSS_VALIDATION), \
                span("selector.sweep", hbm=True, stage_uid=self.uid,
                     stage_cls=type(self).__name__, phase="sweep",
                     n_families=len(self.models_and_grids)):
            results, mean_metrics, failures, refit_state = \
                self._sweep(Xt, yt, wt, yt_np)
        _plog("selector: CV sweep", t1)
        t1 = time.time()
        Xh = X[jnp.asarray(holdout_idx)] if holdout_idx.size else None
        yh = y[jnp.asarray(holdout_idx)] if holdout_idx.size else None
        with profiler.phase(OpStep.MODEL_TRAINING), \
                span("selector.refit", hbm=True, stage_uid=self.uid,
                     stage_cls=type(self).__name__, phase="refit"):
            selected = self._finalize(results, mean_metrics, Xt, yt, wt,
                                      Xh, yh, prep_results, t0, failures,
                                      refit_state=refit_state)
        _plog("selector: refit+evaluate", t1)
        return selected

    def fit_with_dag(self, data, during_dag, executor):
        """Leakage-free workflow-level CV (reference ``OpWorkflow.
        withWorkflowCV`` + ``ModelSelector.findBestEstimator`` over the in-CV
        DAG): the label-dependent feature stages in ``during_dag`` are refit
        inside every fold on that fold's training rows only, then the
        candidate sweep runs on the fold-local features.

        Returns ``(selected_model, fitted_during_dag, transformed_data)``
        where ``fitted_during_dag`` was refit on the full prepared training
        rows and ``transformed_data`` is the input data pushed through it
        (all rows, holdout included).
        """
        t0 = time.time()
        label_name, feat_name = self.input_names
        y = data.device_col(label_name).values
        n = data.n_rows  # logical rows: device arrays may carry mesh padding

        train_idx, holdout_idx, w_train, prep_results = \
            self._split_prepare(n, y[:n])
        data_train = data.take(train_idx)
        wt_full = jnp.asarray(w_train)
        yt_np = (np.asarray(y)[train_idx]
                 if getattr(self.validator, "stratify", False) else None)

        def fold_arrays():
            for tr, va in self.validator.splits(train_idx.size, yt_np):
                d_tr = data_train.take(tr)
                d_va = data_train.take(va)
                # scratch executor per fold: the fold's fitted models carry
                # fold-specific static config (vocabs, splits), so their
                # compiled programs must not accumulate in the workflow's
                # long-lived executor cache
                fold_ex = type(executor)()
                d_tr2, fitted = fold_ex.fit_transform(d_tr, during_dag)
                d_va2 = fold_ex.transform(d_va, fitted)
                # validation slices back to logical rows: take() re-pads
                # device columns under a mesh, and metrics must see real
                # rows only (training padding is weight-masked instead)
                n_va = d_va2.n_rows
                yield (d_tr2.device_col(feat_name).values,
                       d_tr2.device_col(label_name).values,
                       wt_full[jnp.asarray(tr)],
                       d_va2.device_col(feat_name).values[:n_va],
                       d_va2.device_col(label_name).values[:n_va])

        # the in-CV DAG refits per fold, so fold features differ and cannot
        # stack: workflow-level CV keeps the fold-major loop
        results, mean_metrics, failures = self._sweep_loop(fold_arrays())

        # refit the in-CV feature DAG on the full prepared training rows,
        # then push ALL rows (train + holdout) through it for downstream use
        _, fitted_during = executor.fit_transform(data_train, during_dag)
        full_data = executor.transform(data, fitted_during)
        X = full_data.device_col(feat_name).values
        y_full = full_data.device_col(label_name).values
        Xt = X[jnp.asarray(train_idx)]
        yt = y_full[jnp.asarray(train_idx)]
        Xh = X[jnp.asarray(holdout_idx)] if holdout_idx.size else None
        yh = y_full[jnp.asarray(holdout_idx)] if holdout_idx.size else None
        selected = self._finalize(results, mean_metrics, Xt, yt, wt_full,
                                  Xh, yh, prep_results, t0, failures)
        selected._inputs = self._inputs
        selected._output = self.get_output()
        return selected, fitted_during, full_data
