"""Validation strategies: k-fold CV and train/validation split.

Parity: reference ``core/.../stages/impl/tuning/{OpValidator,
OpCrossValidation,OpTrainValidationSplit}.scala`` — k folds (optionally
label-stratified), metric per (estimator, grid point) averaged across folds,
best = argbest mean metric.

TPU-first: fold membership is an index partition computed on host; each
fold's candidate sweep trains via the estimator family's stacked
``grid_fit_arrays`` (one vmapped program for all grid points) instead of the
reference's Future thread pool.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

__all__ = ["OpCrossValidation", "OpTrainValidationSplit"]


class _ValidatorBase:
    def splits(self, n: int, y: Optional[np.ndarray] = None
               ) -> list[tuple[np.ndarray, np.ndarray]]:
        raise NotImplementedError

    def stacked_splits(self, n: int, y: Optional[np.ndarray] = None
                       ) -> tuple[np.ndarray, np.ndarray]:
        """The fold plan as two stacked index matrices ``(train [k, n_tr],
        val [k, n_va])`` — the input layout of the ModelSelector's
        fold-stacked sweep, which gathers all k folds on device in one shot
        instead of materializing per-fold arrays in a host loop. Relies on
        the equal-fold-shape guarantee of ``splits`` (every validator here
        provides it; a custom one that doesn't cannot be stacked)."""
        splits = self.splits(n, y)
        tr_sizes = {t.size for t, _ in splits}
        va_sizes = {v.size for _, v in splits}
        if len(tr_sizes) != 1 or len(va_sizes) != 1:
            raise ValueError(
                f"{type(self).__name__}.splits produced unequal fold shapes "
                f"(train {sorted(tr_sizes)}, val {sorted(va_sizes)}): the "
                "fold axis cannot be stacked")
        return (np.stack([t for t, _ in splits]),
                np.stack([v for _, v in splits]))

    @staticmethod
    def _stratified_folds(y: np.ndarray, n_folds: int, rng) -> np.ndarray:
        """Assign each row a fold id, stratified per label value."""
        fold_of = np.zeros(y.shape[0], dtype=np.int64)
        for label in np.unique(y):
            idx = np.flatnonzero(y == label)
            rng.shuffle(idx)
            fold_of[idx] = np.arange(idx.size) % n_folds
        return fold_of


class OpCrossValidation(_ValidatorBase):
    def __init__(self, n_folds: int = 3, seed: int = 42,
                 stratify: bool = False):
        if n_folds < 2:
            raise ValueError("n_folds must be >= 2")
        self.n_folds = n_folds
        self.seed = seed
        self.stratify = stratify
        self.name = "Cross Validation"

    def splits(self, n, y=None):
        """Equal-shape folds: every fold has exactly n//k validation rows and
        n - n//k training rows (leftover rows train in every fold), so the
        per-fold training/eval programs compile once and replay k times —
        fold-shape stability is the TPU analog of Spark reusing one physical
        plan across folds."""
        rng = np.random.default_rng(self.seed)
        k = self.n_folds
        n_val = n // k
        if n_val == 0:
            raise ValueError(f"not enough rows ({n}) for {k} folds")
        if self.stratify and y is not None:
            fold_of = self._stratified_folds(np.asarray(y), k, rng)
            perm = np.argsort(fold_of, kind="stable")  # grouped by fold
            vals = [np.flatnonzero(fold_of == f) for f in range(k)]
            vals = [rng.permutation(v)[:n_val] for v in vals]
        else:
            perm = rng.permutation(n)
            vals = [perm[f * n_val:(f + 1) * n_val] for f in range(k)]
        out = []
        all_rows = np.arange(n)
        for f in range(k):
            val = np.sort(vals[f])
            train = np.setdiff1d(all_rows, val, assume_unique=False)
            if train.size != n - n_val:  # stratified trim for equal shapes
                train = train[:n - n_val]
            out.append((train, val))
        return out


class OpTrainValidationSplit(_ValidatorBase):
    def __init__(self, train_ratio: float = 0.75, seed: int = 42,
                 stratify: bool = False):
        self.train_ratio = train_ratio
        self.seed = seed
        self.stratify = stratify
        self.name = "Train Validation Split"

    def splits(self, n, y=None):
        rng = np.random.default_rng(self.seed)
        if self.stratify and y is not None:
            fold_of = self._stratified_folds(
                np.asarray(y), max(int(round(1 / (1 - self.train_ratio))), 2),
                rng)
            val = np.flatnonzero(fold_of == 0)
            train = np.flatnonzero(fold_of != 0)
        else:
            perm = rng.permutation(n)
            n_train = int(round(n * self.train_ratio))
            train, val = perm[:n_train], perm[n_train:]
        return [(np.sort(train), np.sort(val))]
