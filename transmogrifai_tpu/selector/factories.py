"""Selector factories with reference-default candidate grids.

Parity: reference ``core/.../stages/impl/classification/
BinaryClassificationModelSelector.scala:49-272``,
``MultiClassificationModelSelector``, ``regression/RegressionModelSelector``
and ``selector/DefaultSelectorParams.scala`` — ``.withCrossValidation()`` /
``.withTrainValidationSplit()`` assembling default candidates + grids.

Default candidate sets grow with the model zoo (trees land in models/trees);
grid values mirror DefaultSelectorParams where the family exists.
"""

from __future__ import annotations

from typing import Optional, Sequence

from transmogrifai_tpu.evaluators import (
    OpBinaryClassificationEvaluator, OpMultiClassificationEvaluator,
    OpRegressionEvaluator,
)
from transmogrifai_tpu.models.linear import (
    OpLinearRegression, OpLinearSVC, OpLogisticRegression,
)
from transmogrifai_tpu.selector.model_selector import ModelSelector
from transmogrifai_tpu.selector.splitters import (
    DataBalancer, DataCutter, DataSplitter,
)
from transmogrifai_tpu.selector.validator import (
    OpCrossValidation, OpTrainValidationSplit,
)

__all__ = ["BinaryClassificationModelSelector",
           "MultiClassificationModelSelector", "RegressionModelSelector"]

# DefaultSelectorParams analogs
_REG_GRID = [0.001, 0.01, 0.1, 0.2]
_ELASTIC_GRID = [0.0, 0.5]


def _lr_grid():
    return [{"reg_param": r, "elastic_net_param": e}
            for r in _REG_GRID for e in _ELASTIC_GRID]


def _svc_grid():
    return [{"reg_param": r} for r in _REG_GRID]


def _default_binary_candidates():
    cands = [(OpLogisticRegression(), _lr_grid()),
             (OpLinearSVC(), _svc_grid())]
    try:
        from transmogrifai_tpu.models.trees import (
            OpGBTClassifier, OpRandomForestClassifier,
        )
        cands.append((OpRandomForestClassifier(), [
            {"num_trees": 50, "max_depth": d} for d in (6, 12)]))
        cands.append((OpGBTClassifier(), [
            {"num_rounds": 50, "max_depth": d} for d in (3, 6)]))
    except ImportError:
        pass
    return cands


def _default_multi_candidates():
    # reference multiclass defaults: LR + RF + DT + NB
    cands = [(OpLogisticRegression(), _lr_grid())]
    try:
        from transmogrifai_tpu.models.trees import OpRandomForestClassifier
        cands.append((OpRandomForestClassifier(), [
            {"num_trees": 50, "max_depth": d} for d in (6, 12)]))
    except ImportError:
        pass
    try:
        from transmogrifai_tpu.models.extras import OpNaiveBayes
        cands.append((OpNaiveBayes(), [{}]))
    except ImportError:
        pass
    return cands


def _default_regression_candidates():
    cands = [(OpLinearRegression(), _lr_grid())]
    try:
        from transmogrifai_tpu.models.trees import (
            OpGBTRegressor, OpRandomForestRegressor,
        )
        cands.append((OpRandomForestRegressor(), [
            {"num_trees": 50, "max_depth": d} for d in (6, 12)]))
        cands.append((OpGBTRegressor(), [
            {"num_rounds": 50, "max_depth": d} for d in (3, 6)]))
    except ImportError:
        pass
    return cands


class BinaryClassificationModelSelector:
    @staticmethod
    def with_cross_validation(
            n_folds: int = 3,
            validation_metric: str = "auPR",
            seed: int = 42,
            splitter: Optional[DataSplitter] = None,
            models_and_parameters: Optional[Sequence] = None,
            stratify: bool = False,
            max_wait_s: Optional[float] = 3600.0,
            checkpoint_dir: Optional[str] = None,
    ) -> ModelSelector:
        return ModelSelector(
            models_and_grids=(models_and_parameters
                              or _default_binary_candidates()),
            validator=OpCrossValidation(n_folds=n_folds, seed=seed,
                                        stratify=stratify),
            splitter=splitter if splitter is not None
            else DataSplitter(seed=seed),
            evaluators=[OpBinaryClassificationEvaluator()],
            validation_metric=validation_metric,
            max_wait_s=max_wait_s,
            checkpoint_dir=checkpoint_dir,
        )

    @staticmethod
    def with_train_validation_split(
            train_ratio: float = 0.75,
            validation_metric: str = "auPR",
            seed: int = 42,
            splitter: Optional[DataSplitter] = None,
            models_and_parameters: Optional[Sequence] = None,
            max_wait_s: Optional[float] = 3600.0,
            checkpoint_dir: Optional[str] = None,
    ) -> ModelSelector:
        return ModelSelector(
            models_and_grids=(models_and_parameters
                              or _default_binary_candidates()),
            validator=OpTrainValidationSplit(train_ratio=train_ratio, seed=seed),
            splitter=splitter if splitter is not None
            else DataSplitter(seed=seed),
            evaluators=[OpBinaryClassificationEvaluator()],
            validation_metric=validation_metric,
            max_wait_s=max_wait_s,
            checkpoint_dir=checkpoint_dir,
        )


class MultiClassificationModelSelector:
    @staticmethod
    def with_cross_validation(
            n_folds: int = 3,
            validation_metric: str = "F1",
            seed: int = 42,
            splitter: Optional[DataSplitter] = None,
            models_and_parameters: Optional[Sequence] = None,
            stratify: bool = False,
            max_wait_s: Optional[float] = 3600.0,
            checkpoint_dir: Optional[str] = None,
    ) -> ModelSelector:
        return ModelSelector(
            models_and_grids=(models_and_parameters
                              or _default_multi_candidates()),
            validator=OpCrossValidation(n_folds=n_folds, seed=seed,
                                        stratify=stratify),
            splitter=splitter if splitter is not None
            else DataCutter(seed=seed),
            evaluators=[OpMultiClassificationEvaluator()],
            validation_metric=validation_metric,
            max_wait_s=max_wait_s,
            checkpoint_dir=checkpoint_dir,
        )

    @staticmethod
    def with_train_validation_split(
            train_ratio: float = 0.75,
            validation_metric: str = "F1",
            seed: int = 42,
            splitter: Optional[DataSplitter] = None,
            models_and_parameters: Optional[Sequence] = None,
            max_wait_s: Optional[float] = 3600.0,
            checkpoint_dir: Optional[str] = None,
    ) -> ModelSelector:
        return ModelSelector(
            models_and_grids=(models_and_parameters
                              or _default_multi_candidates()),
            validator=OpTrainValidationSplit(train_ratio=train_ratio,
                                             seed=seed),
            splitter=splitter if splitter is not None
            else DataCutter(seed=seed),
            evaluators=[OpMultiClassificationEvaluator()],
            validation_metric=validation_metric,
            max_wait_s=max_wait_s,
            checkpoint_dir=checkpoint_dir,
        )


class RegressionModelSelector:
    @staticmethod
    def with_cross_validation(
            n_folds: int = 3,
            validation_metric: str = "RMSE",
            seed: int = 42,
            splitter: Optional[DataSplitter] = None,
            models_and_parameters: Optional[Sequence] = None,
            max_wait_s: Optional[float] = 3600.0,
            checkpoint_dir: Optional[str] = None,
    ) -> ModelSelector:
        return ModelSelector(
            models_and_grids=(models_and_parameters
                              or _default_regression_candidates()),
            validator=OpCrossValidation(n_folds=n_folds, seed=seed),
            splitter=splitter if splitter is not None
            else DataSplitter(seed=seed),
            evaluators=[OpRegressionEvaluator()],
            validation_metric=validation_metric,
            max_wait_s=max_wait_s,
            checkpoint_dir=checkpoint_dir,
        )

    @staticmethod
    def with_train_validation_split(
            train_ratio: float = 0.75,
            validation_metric: str = "RMSE",
            seed: int = 42,
            splitter: Optional[DataSplitter] = None,
            models_and_parameters: Optional[Sequence] = None,
            max_wait_s: Optional[float] = 3600.0,
            checkpoint_dir: Optional[str] = None,
    ) -> ModelSelector:
        return ModelSelector(
            models_and_grids=(models_and_parameters
                              or _default_regression_candidates()),
            validator=OpTrainValidationSplit(train_ratio=train_ratio,
                                             seed=seed),
            splitter=splitter if splitter is not None
            else DataSplitter(seed=seed),
            evaluators=[OpRegressionEvaluator()],
            validation_metric=validation_metric,
            max_wait_s=max_wait_s,
            checkpoint_dir=checkpoint_dir,
        )
