"""Selector extras: random hyperparameter search + model combination.

Parity: reference ``selector/RandomParamBuilder.scala`` (random grids over
subset/uniform/exponential supports) and ``selector/SelectedModelCombiner
.scala`` (ensemble of two selector outputs weighted by validation metric).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.evaluators.base import EvaluatorBase
from transmogrifai_tpu.models.base import PredictionModel
from transmogrifai_tpu.stages.base import Estimator
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["RandomParamBuilder", "SelectedModelCombiner", "CombinedModel"]


class RandomParamBuilder:
    """``RandomParamBuilder(seed).subset("a", [1,2]).uniform("b", 0, 1)
    .exponential("c", 1e-4, 1e-1).build(10)`` -> 10 random param dicts."""

    def __init__(self, seed: int = 42):
        self._rng = np.random.default_rng(seed)
        self._specs: list[tuple[str, str, object]] = []

    def subset(self, name: str, values: Sequence) -> "RandomParamBuilder":
        self._specs.append((name, "subset", list(values)))
        return self

    def uniform(self, name: str, low: float, high: float) -> "RandomParamBuilder":
        self._specs.append((name, "uniform", (low, high)))
        return self

    def exponential(self, name: str, low: float, high: float
                    ) -> "RandomParamBuilder":
        if low <= 0 or high <= 0:
            raise ValueError("exponential bounds must be positive")
        self._specs.append((name, "exponential", (low, high)))
        return self

    def build(self, n: int) -> list[dict]:
        out = []
        for _ in range(n):
            d = {}
            for name, kind, spec in self._specs:
                if kind == "subset":
                    d[name] = spec[self._rng.integers(len(spec))]
                elif kind == "uniform":
                    lo, hi = spec
                    d[name] = float(self._rng.uniform(lo, hi))
                else:
                    lo, hi = spec
                    d[name] = float(np.exp(
                        self._rng.uniform(np.log(lo), np.log(hi))))
            out.append(d)
        return out


class CombinedModel(PredictionModel):
    """Weighted average of two Prediction inputs."""

    in_types = (ft.RealNN, ft.Prediction, ft.Prediction)
    out_type = ft.Prediction

    def __init__(self, weight1: float = 0.5, weight2: float = 0.5,
                 uid: Optional[str] = None):
        self.weight1 = float(weight1)
        self.weight2 = float(weight2)
        super().__init__(uid=uid)

    def runtime_input_names(self):
        return self.input_names[1:]

    def device_params(self):
        return (jnp.float32(self.weight1), jnp.float32(self.weight2))

    def device_apply(self, params, p1: fr.PredictionColumn,
                     p2: fr.PredictionColumn) -> fr.PredictionColumn:
        w1, w2 = params
        prob = w1 * p1.probability + w2 * p2.probability
        raw = w1 * p1.raw_prediction + w2 * p2.raw_prediction
        if prob.shape[1] >= 2:
            pred = jnp.argmax(prob, axis=1).astype(jnp.float32)
        else:
            pred = w1 * p1.prediction + p2.prediction * w2
        return fr.PredictionColumn(pred, raw, prob)

    def transform_row(self, *values):
        p1, p2 = values[-2], values[-1]
        keys = set(p1) | set(p2)
        out = {k: self.weight1 * p1.get(k, 0.0) + self.weight2 * p2.get(k, 0.0)
               for k in keys}
        probs = [(int(k.rsplit("_", 1)[1]), v) for k, v in out.items()
                 if k.startswith("probability_")]
        if probs:
            out["prediction"] = float(max(probs, key=lambda kv: kv[1])[0])
        return out


class SelectedModelCombiner(Estimator):
    """(label, pred1, pred2) -> combined Prediction weighted by each input's
    metric on the training data."""

    in_types = (ft.RealNN, ft.Prediction, ft.Prediction)
    out_type = ft.Prediction

    def __init__(self, evaluator: Optional[EvaluatorBase] = None,
                 metric: Optional[str] = None,
                 uid: Optional[str] = None):
        from transmogrifai_tpu.evaluators import OpBinaryClassificationEvaluator
        self.evaluator = evaluator or OpBinaryClassificationEvaluator()
        self.metric = metric
        super().__init__(uid=uid)

    def fit_model(self, data):
        label_name, p1_name, p2_name = self.input_names
        y = data.device_col(label_name).values
        ev = self.evaluator
        m1 = ev.metric_value(ev.evaluate_arrays(y, data.device_col(p1_name)),
                             self.metric)
        m2 = ev.metric_value(ev.evaluate_arrays(y, data.device_col(p2_name)),
                             self.metric)
        if not ev.larger_is_better(self.metric):
            m1, m2 = 1.0 / max(m1, 1e-12), 1.0 / max(m2, 1e-12)
        total = m1 + m2
        w1 = m1 / total if total > 0 else 0.5
        model = CombinedModel(weight1=w1, weight2=1.0 - w1)
        model.summary = {"weight1": w1, "weight2": 1.0 - w1,
                         "metric1": m1, "metric2": m2}
        return model
