from transmogrifai_tpu.selector.splitters import (
    DataBalancer, DataCutter, DataSplitter,
)
from transmogrifai_tpu.selector.validator import (
    OpCrossValidation, OpTrainValidationSplit,
)
from transmogrifai_tpu.selector.model_selector import (
    ModelSelector, SelectedModel, ModelSelectorSummary,
)
from transmogrifai_tpu.selector.factories import (
    BinaryClassificationModelSelector, MultiClassificationModelSelector,
    RegressionModelSelector,
)

__all__ = [
    "DataBalancer", "DataCutter", "DataSplitter",
    "OpCrossValidation", "OpTrainValidationSplit",
    "ModelSelector", "SelectedModel", "ModelSelectorSummary",
    "BinaryClassificationModelSelector", "MultiClassificationModelSelector",
    "RegressionModelSelector",
]
