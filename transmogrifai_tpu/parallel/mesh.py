"""Device mesh management: the framework's distributed substrate.

Replaces the reference's Spark driver/executor + shuffle/broadcast comm layer
(SURVEY §2.7): all distribution here is a single-program `jax.sharding.Mesh`
with XLA collectives over ICI/DCN. Two named axes:

- ``"data"``  — rows (batch) shard here; the workhorse axis (reference P1).
- ``"model"`` — model-selection candidates / feature-width shard here
  (reference P3/P5 thread pools and the O(d^2) stats decomposition).

Multi-host pods join the same mesh via ``jax.distributed.initialize`` (DCN);
see ``transmogrifai_tpu.parallel.distributed``.
"""

from __future__ import annotations

import contextlib
import contextvars
import math
from dataclasses import dataclass
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "MeshContext", "make_mesh", "use_mesh", "current_mesh", "row_sharding",
    "replicated", "pad_rows", "shard_rows", "num_data_shards",
    "pad_and_shard_rows", "shard_training_rows", "fold_axis_on_model",
    "shard_stacked_training_rows", "shard_map_compat",
]

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclass(frozen=True)
class MeshContext:
    """A mesh plus the framework's axis conventions."""

    mesh: Mesh

    @property
    def n_data(self) -> int:
        return self.mesh.shape[DATA_AXIS]

    @property
    def n_model(self) -> int:
        return self.mesh.shape.get(MODEL_AXIS, 1)

    def row_sharding(self, *trailing_axes: Optional[str]) -> NamedSharding:
        """Rows sharded over 'data'; trailing dims per ``trailing_axes``."""
        return NamedSharding(self.mesh, P(DATA_AXIS, *trailing_axes))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def model_sharding(self, *trailing_axes: Optional[str]) -> NamedSharding:
        """Leading candidate axis sharded over 'model'."""
        return NamedSharding(self.mesh, P(MODEL_AXIS, *trailing_axes))


_current: contextvars.ContextVar[Optional[MeshContext]] = contextvars.ContextVar(
    "transmogrifai_mesh", default=None)


def make_mesh(n_data: Optional[int] = None, n_model: int = 1,
              devices=None) -> MeshContext:
    """Build a (data, model) mesh over available devices.

    Defaults to all devices on the data axis — the right choice for the
    row-parallel workhorse path. ``n_model > 1`` carves off a candidate-
    parallel axis for the ModelSelector sweep.
    """
    devices = list(devices if devices is not None else jax.devices())
    total = len(devices)
    if n_data is None:
        n_data = total // n_model
    if n_data * n_model != total:
        raise ValueError(
            f"mesh shape {n_data}x{n_model} != device count {total}")
    arr = np.asarray(devices).reshape(n_data, n_model)
    return MeshContext(Mesh(arr, (DATA_AXIS, MODEL_AXIS)))


@contextlib.contextmanager
def use_mesh(ctx: MeshContext):
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def current_mesh() -> Optional[MeshContext]:
    """The active mesh, or None (single-device eager fallback)."""
    return _current.get()


def row_sharding() -> Optional[NamedSharding]:
    ctx = current_mesh()
    return None if ctx is None else ctx.row_sharding()


def replicated() -> Optional[NamedSharding]:
    ctx = current_mesh()
    return None if ctx is None else ctx.replicated()


def num_data_shards() -> int:
    ctx = current_mesh()
    return 1 if ctx is None else ctx.n_data


def pad_rows(n: int, multiple: Optional[int] = None) -> int:
    """Rows padded up so the batch axis divides the data-axis size. Padded
    slots carry mask=0 so every masked statistic ignores them."""
    if multiple is None:
        multiple = num_data_shards()
    return int(math.ceil(n / multiple) * multiple) if multiple > 1 else n


def _already_placed(arr, sharding) -> bool:
    """True when ``arr`` is a jax array ALREADY carrying a sharding
    equivalent to the target — the round-14 "pre-partitioned operands"
    contract: a device frame placed rows-on-"data" at first touch flows
    into the sweep with no resharding device_put (and therefore no
    resharding collectives on a real mesh)."""
    s = getattr(arr, "sharding", None)
    if s is None:
        return False
    try:
        same = s.is_equivalent_to(sharding, getattr(arr, "ndim", 1))
    except Exception:  # failure-ok: version-dependent API; fall back to ==
        same = s == sharding
    if same:
        from transmogrifai_tpu.utils.profiling import ingest_counters
        ingest_counters.presharded_skips += 1
    return bool(same)


def shard_rows(arr: jax.Array) -> jax.Array:
    """Place an array with its leading (row) axis sharded over the mesh.
    No-op without an active mesh, and a counted no-op when the array
    already carries the target sharding (``_already_placed``)."""
    ctx = current_mesh()
    if ctx is None:
        return arr
    spec = P(DATA_AXIS, *([None] * (arr.ndim - 1)))
    sharding = NamedSharding(ctx.mesh, spec)
    if _already_placed(arr, sharding):
        return arr
    return jax.device_put(arr, sharding)


def pad_and_shard_rows(arr, pad_value=0.0):
    """Pad the row axis up to a multiple of the data-axis size, then shard.

    The device_put row-sharding path requires the leading dim to divide the
    mesh; padded slots are poisoned with ``pad_value`` (callers pair this
    with a zeroed mask/weight so every masked statistic ignores them).
    Accepts numpy or jax arrays; pads on host before transfer. No-op
    without an active mesh.
    """
    ctx = current_mesh()
    if ctx is None:
        return arr
    n = int(arr.shape[0])
    n_pad = pad_rows(n, ctx.n_data)
    if n_pad != n:
        width = [(0, n_pad - n)] + [(0, 0)] * (arr.ndim - 1)
        if isinstance(arr, np.ndarray):
            arr = np.pad(arr, width, constant_values=pad_value)
        else:
            import jax.numpy as jnp
            arr = jnp.pad(arr, width, constant_values=pad_value)
    return shard_rows(arr)


def shard_map_compat(fn, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across jax versions: >= 0.5 exposes it top-level
    with ``check_vma``; older releases ship it as
    ``jax.experimental.shard_map.shard_map`` with the equivalent knob named
    ``check_rep``. Every explicit-collective program in the framework (tree
    histogram all-reduce, monoid stats reduction) routes through here so
    the distributed substrate works on both."""
    kw = {}
    if hasattr(jax, "shard_map"):
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    if check_vma is not None:
        kw["check_rep"] = check_vma
    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def fold_axis_on_model(k: int) -> bool:
    """True when a stacked fold axis of size ``k`` rides the mesh "model"
    axis (it must divide it evenly). The ModelSelector's fold-stacked sweep
    uses this to pick which of its two candidate-parallel axes (fold vs
    grid) the "model" axis shards: folds win when they divide; otherwise the
    grid scalars take the axis (``_shard_candidates``) and folds replicate."""
    ctx = current_mesh()
    return ctx is not None and ctx.n_model > 1 and k % ctx.n_model == 0


def shard_stacked_training_rows(X, y, w):
    """Fold-stacked ([k, n, ...]) analog of ``shard_training_rows``: the
    ROW axis (axis 1) pads to the data-axis multiple with weight 0 and
    shards over "data"; the leading FOLD axis shards over "model" when it
    divides that axis (``fold_axis_on_model``), else replicates. This is
    the 2-D placement of the ModelSelector's (fold x grid) work units:
    rows over "data", fold/grid candidates over "model" (SURVEY §2.7
    P1 + P3 combined). ``X`` may be float features (the linear families'
    stacked batch) or integer bin codes (the fold x grid-stacked tree
    sweep's int8 code gather) — padding is dtype-preserving and padded
    slots carry weight 0, so every weighted statistic ignores them.
    No-op without an active mesh."""
    ctx = current_mesh()
    if ctx is None:
        return X, y, w
    import jax.numpy as jnp
    k = int(X.shape[0])
    n = int(X.shape[1])
    n_pad = pad_rows(n, ctx.n_data)

    def pad1(a, val):
        if n_pad == n:
            return a
        width = [(0, 0), (0, n_pad - n)] + [(0, 0)] * (a.ndim - 2)
        if isinstance(a, np.ndarray):
            return np.pad(a, width,
                          constant_values=np.asarray(val, a.dtype))
        return jnp.pad(a, width,
                       constant_values=jnp.asarray(val, a.dtype))

    fold_ax = MODEL_AXIS if fold_axis_on_model(k) else None

    def put(a):
        spec = P(fold_ax, DATA_AXIS, *([None] * (a.ndim - 2)))
        sharding = NamedSharding(ctx.mesh, spec)
        if _already_placed(a, sharding):
            return a
        return jax.device_put(a, sharding)

    return (put(pad1(X, 0.0)), put(pad1(y, 0.0)), put(pad1(w, 0.0)))


def shard_training_rows(X, y, w):
    """Distribute one (features, label, weight) training set over the mesh:
    rows padded to the data-axis multiple with weight 0, so every weighted
    trainer (`fit_arrays(X, y, w, ...)`) computes identical results sharded
    or not. No-op without an active mesh. This is the seam that makes the
    ModelSelector sweep row-parallel (reference P1 pervasiveness:
    FitStagesUtil.scala:96-119 — every fit is distributed)."""
    ctx = current_mesh()
    if ctx is None:
        return X, y, w
    return (pad_and_shard_rows(X), pad_and_shard_rows(y),
            pad_and_shard_rows(w, pad_value=0.0))
