from transmogrifai_tpu.parallel.mesh import (
    MeshContext, current_mesh, make_mesh, pad_rows, row_sharding, use_mesh,
)

__all__ = [
    "MeshContext", "current_mesh", "make_mesh", "pad_rows", "row_sharding",
    "use_mesh",
]
