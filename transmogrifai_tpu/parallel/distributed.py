"""Multi-host (DCN) initialization.

The reference scales out via Spark's driver/executor RPC; XGBoost adds a
Rabit all-reduce ring (SURVEY §2.7). The TPU-native equivalent is a single
SPMD program across hosts: ``jax.distributed.initialize`` joins processes over
DCN, after which ``jax.devices()`` spans the pod and the normal mesh/collective
path (mesh.py, collectives.py) is multi-host transparently.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

__all__ = ["initialize", "is_multi_process", "process_index", "process_count"]

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join a multi-host pod. No-op when single-process (tests, one chip).

    Arguments default from the standard env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID) or TPU metadata autodetection.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None and os.environ.get("JAX_NUM_PROCESSES") is None:
        return  # single process
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True


def is_multi_process() -> bool:
    return jax.process_count() > 1


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()
