"""Multi-host (DCN) communication backend.

The reference scales out via Spark's driver/executor RPC; XGBoost adds a
Rabit all-reduce ring (SURVEY §2.7). The TPU-native equivalent is a single
SPMD program across hosts: ``jax.distributed.initialize`` joins processes
over DCN, after which ``jax.devices()`` spans the pod and the normal
mesh/collective path (mesh.py, collectives.py) is multi-host transparently —
collectives ride ICI within a host/slice and DCN across, inserted by XLA
from the same mesh program. ``tests/test_distributed.py`` proves the path
end-to-end with two real OS processes on the CPU backend (coordinator
handshake, global mesh over both processes' devices, cross-process monoid
psum, global-array scatter)."""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

__all__ = ["initialize", "is_multi_process", "process_index",
           "process_count", "global_mesh", "shard_global_rows", "barrier"]

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join a multi-host pod. No-op when single-process (tests, one chip).

    Arguments default from the standard env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID) or TPU metadata autodetection.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or \
        os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        return  # single process
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True


def is_multi_process() -> bool:
    return jax.process_count() > 1


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def global_mesh(n_model: int = 1):
    """A (data, model) MeshContext over EVERY device in the pod — local and
    remote processes alike (the multi-host analog of make_mesh's default)."""
    from transmogrifai_tpu.parallel.mesh import make_mesh
    return make_mesh(n_model=n_model, devices=jax.devices())


def shard_global_rows(ctx, local_rows: np.ndarray,
                      timeout_s: Optional[float] = None) -> jax.Array:
    """Assemble a GLOBAL row-sharded array from each process's local rows
    (the multi-host ingest seam: every host reads its own partition, the
    result behaves as one logical array over the whole mesh).

    The global row count is ``sum over processes`` of local counts; local
    row counts must be equal (pad with masked rows first if not).

    The assembly is a cross-host collective (device uploads + an implicit
    rendezvous): transient device errors retry with capped jittered
    backoff, and the retry loop as a whole runs under a deadline — a dead
    peer host raises ``CollectiveTimeoutError`` with per-host diagnostics
    instead of hanging the pod (``timeout_s`` / env
    ``TRANSMOGRIFAI_COLLECTIVE_TIMEOUT_S``). The retry sits INSIDE the
    deadline, never around it: re-entering a collective while a timed-out
    attempt's thread is still blocked in the old one would pair this
    host's retry with its peers' first attempt and desynchronize the
    pod's collective stream — a timeout here means restart-and-resume,
    not retry."""
    from jax.experimental import multihost_utils

    from transmogrifai_tpu.parallel.collectives import run_with_deadline
    from transmogrifai_tpu.utils.faults import fault_point
    from transmogrifai_tpu.utils.retry import with_device_retry

    def assemble():
        fault_point("collective")
        return multihost_utils.host_local_array_to_global_array(
            local_rows, ctx.mesh,
            jax.sharding.PartitionSpec(
                "data", *([None] * (np.ndim(local_rows) - 1))))

    return run_with_deadline(
        lambda: with_device_retry(assemble),
        name="shard_global_rows", timeout_s=timeout_s)


def barrier(name: str = "transmogrifai",
            timeout_s: Optional[float] = None) -> None:
    """Block until every process reaches this point (DCN sync) — bounded.

    A host that died before reaching the barrier used to hang every other
    host forever; the sync now runs under a deadline (``timeout_s``,
    default env ``TRANSMOGRIFAI_COLLECTIVE_TIMEOUT_S`` = 600s, ``0``
    restores unbounded waiting) and raises ``CollectiveTimeoutError``
    naming the barrier and this host so the orchestrator can restart the
    job and resume from checkpoints."""
    from jax.experimental import multihost_utils

    from transmogrifai_tpu.parallel.collectives import run_with_deadline
    from transmogrifai_tpu.utils.faults import fault_point

    def sync():
        fault_point("collective")
        multihost_utils.sync_global_devices(name)

    run_with_deadline(sync, name=f"barrier[{name}]", timeout_s=timeout_s)
