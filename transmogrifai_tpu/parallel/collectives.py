"""Monoid-pytree reductions over the mesh — the statistics comm backend.

The reference computes every distributed statistic as an algebird monoid
reduced via Spark ``reduce``/``reduceByKey``/``treeAggregate`` (SURVEY §2.7
P2: RawFeatureFilter summaries, SmartTextVectorizer TextStats, SanityChecker
contingency). Here the same algebra runs as:

- inside ``shard_map``: ``tree_psum(stats, axis="data")`` — XLA all-reduce
  over ICI, one collective per fused stats program;
- at host level (multi-process): ``jax.experimental.multihost_utils`` style
  all-gather is unnecessary because stats arrays are device-resident and
  jit output shardings already materialize the reduced value replicated.

A "monoid" here is any pytree of arrays whose combine is elementwise ``+``
(sums, counts, histograms, contingency tables) — min/max/moment variants
provide their own combine.
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from transmogrifai_tpu.parallel.mesh import (
    DATA_AXIS, MeshContext, shard_map_compat,
)

__all__ = ["tree_psum", "tree_pmax", "tree_pmin", "mesh_reduce_stats",
           "reduce_host_metrics", "CollectiveTimeoutError",
           "run_with_deadline", "collective_timeout_s"]


class CollectiveTimeoutError(RuntimeError):
    """A multihost collective/barrier exceeded its deadline. One dead or
    partitioned host makes every OTHER host block inside the collective
    forever — this error converts the silent pod-wide hang into a fast,
    per-host-attributed failure an orchestrator can act on (restart the
    pod, resume from checkpoints). Carries ``DEADLINE_EXCEEDED`` in the
    message so retry classification treats it as transient infrastructure.
    """


def collective_timeout_s(timeout_s: Optional[float] = None) -> float:
    """Effective collective deadline: the explicit argument, else
    ``TRANSMOGRIFAI_COLLECTIVE_TIMEOUT_S`` (default 600). ``0`` disables
    the guard (legacy block-forever behavior)."""
    if timeout_s is not None:
        return float(timeout_s)
    from transmogrifai_tpu.utils.retry import _env_float
    return _env_float("TRANSMOGRIFAI_COLLECTIVE_TIMEOUT_S", 600.0)


def _host_diagnostics() -> str:
    try:
        return (f"host {jax.process_index()}/{jax.process_count()}, "
                f"{len(jax.local_devices())} local device(s), "
                f"backend={jax.default_backend()}")
    except Exception:  # failure-ok: diagnostics must never mask the timeout
        return "host ?/? (jax backend unavailable)"


def run_with_deadline(fn: Callable[[], Any], *, name: str,
                      timeout_s: Optional[float] = None) -> Any:
    """Run a blocking collective with a deadline: ``fn()`` executes on a
    worker thread; if it has not returned within the timeout, raise
    :class:`CollectiveTimeoutError` naming the collective and this host
    instead of hanging the pod. The abandoned thread is daemonic — the
    expected reaction to a timeout is tearing the process down and
    resuming from checkpoints, exactly what resumable training enables.

    Deliberately guarded even single-process: barrier/shard_global_rows
    are rare, per-phase calls whose bounded-wait contract must hold (and
    be chaos-testable) everywhere; only the per-stats-call hot path
    (``mesh_reduce_stats``) skips the guard when no peer can be dead."""
    timeout = collective_timeout_s(timeout_s)
    if timeout <= 0:
        return fn()
    from transmogrifai_tpu.utils import devicewatch
    box: dict[str, Any] = {}
    done = threading.Event()

    def work() -> None:
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — reraised on the caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=work, daemon=True,
                         name=f"collective[{name}]")
    t0 = time.time()
    eid = devicewatch.dispatch_ledger.register("collective", name=name,
                                               timeoutSeconds=timeout)
    try:
        t.start()
        if not done.wait(timeout):
            # freeze the device-execution autopsy BEFORE raising: the
            # abandoned worker thread's stack (blocked inside the
            # collective), the in-flight dispatch inventory, and the HBM
            # census are exactly the evidence a pod-hang postmortem
            # needs. Gated like every observatory seam — a disabled
            # watchdog (TRANSMOGRIFAI_DEVICEWATCH=0) restores the
            # pre-observatory timeout byte for byte
            if devicewatch.watchdog.enabled:
                try:
                    devicewatch.stall_autopsy(
                        f"collective.timeout:{name}", site="collective",
                        wait={"name": name, "site": "collective",
                              "timeoutS": timeout, "t0": t0,
                              "thread": t.name})
                except Exception as e:  # noqa: BLE001 — diagnostics must never mask the timeout
                    warnings.warn(
                        f"collective-timeout autopsy failed "
                        f"({type(e).__name__}: {e})", RuntimeWarning)
            raise CollectiveTimeoutError(
                f"DEADLINE_EXCEEDED: collective {name!r} timed out after "
                f"{timeout:g}s on {_host_diagnostics()} — a peer host is "
                "likely dead or partitioned; restart the job and resume "
                "from checkpoints (docs/ROBUSTNESS.md)")
    finally:
        devicewatch.dispatch_ledger.complete(eid)
    if "error" in box:
        raise box["error"]
    return box["value"]


def tree_psum(tree: Any, axis: str = DATA_AXIS) -> Any:
    """All-reduce-sum every leaf across a mesh axis (use under shard_map)."""
    return jax.tree_util.tree_map(lambda x: jax.lax.psum(x, axis), tree)


def tree_pmax(tree: Any, axis: str = DATA_AXIS) -> Any:
    return jax.tree_util.tree_map(lambda x: jax.lax.pmax(x, axis), tree)


def tree_pmin(tree: Any, axis: str = DATA_AXIS) -> Any:
    return jax.tree_util.tree_map(lambda x: jax.lax.pmin(x, axis), tree)


def mesh_reduce_stats(ctx: MeshContext,
                      local_stats_fn: Callable[..., Any],
                      *row_sharded_args: jax.Array,
                      reduce: Callable[[Any], Any] | None = None,
                      timeout_s: Optional[float] = None) -> Any:
    """Run a per-shard statistics function over row-sharded inputs and
    all-reduce the resulting monoid pytree across the data axis.

    ``local_stats_fn(*shard_args) -> stats pytree`` sees only its shard of the
    rows (masked rows contribute identity). The result is replicated.
    This is the direct analog of the reference's
    ``rdd.map(prepare).reduce(monoid.plus)``.

    ``reduce`` combines the per-shard pytrees (default ``tree_psum``); pass a
    custom combiner for non-additive monoids, e.g. one that psums sums but
    pmins/pmaxes extrema — it runs inside shard_map with the data axis bound.

    Multihost, the all-reduce rides DCN and a dead peer host blocks it
    forever: the dispatch + materialization runs under a deadline
    (``timeout_s``, default env ``TRANSMOGRIFAI_COLLECTIVE_TIMEOUT_S``)
    and raises :class:`CollectiveTimeoutError` with per-host diagnostics
    instead of hanging the pod. Single-process meshes skip the guard — no
    peer can be dead, and stats calls stay thread-free on the hot path.
    """
    combine = reduce if reduce is not None else tree_psum
    in_specs = tuple(
        P(DATA_AXIS, *([None] * (a.ndim - 1))) for a in row_sharded_args)

    def shard_fn(*args):
        return combine(local_stats_fn(*args))

    fn = shard_map_compat(shard_fn, mesh=ctx.mesh, in_specs=in_specs,
                          out_specs=P())
    if jax.process_count() <= 1:
        return fn(*row_sharded_args)
    # block inside the deadline: jit dispatch is async, so only a
    # block_until_ready surfaces a cross-host hang at this seam
    return run_with_deadline(
        lambda: jax.block_until_ready(fn(*row_sharded_args)),
        name="mesh_reduce_stats", timeout_s=timeout_s)


def reduce_host_metrics(ctx: MeshContext, values: dict[str, float],
                        timeout_s: Optional[float] = None
                        ) -> dict[str, float]:
    """Sum a host-local ``{name: value}`` metrics mapping across every
    host of the mesh — the observability reduction behind one-run-summary
    multihost metrics (``utils.profiling.aggregate_across_hosts``).

    Every host MUST call this with the same sorted key set (phase/stage
    names come from the same program on every host, so they do) — the
    values pack into one vector, each host spreads its vector over its
    local rows of the data axis, and the same deadline-guarded
    ``mesh_reduce_stats`` all-reduce that serves training statistics sums
    them. Single-process meshes reduce locally (identity sum) with no
    deadline thread, like every other collective here.
    """
    import numpy as np

    names = sorted(values)
    if not names:
        return {}
    n_proc = jax.process_count()
    axis = ctx.mesh.shape[DATA_AXIS]
    rows_local = max(axis // max(n_proc, 1), 1)
    v = jnp.asarray([float(values[n]) for n in names], jnp.float32)
    # spread this host's vector over its local rows so the data-axis psum
    # equals the straight sum over hosts
    block = jnp.tile(v / rows_local, (rows_local, 1))
    if n_proc > 1:
        from jax.sharding import NamedSharding
        arr = jax.make_array_from_process_local_data(
            NamedSharding(ctx.mesh, P(DATA_AXIS)), np.asarray(block))
    else:
        arr = block
    out = mesh_reduce_stats(ctx, lambda rows: jnp.sum(rows, axis=0), arr,
                            timeout_s=timeout_s)
    out = np.asarray(out, np.float64)
    return {n: float(out[i]) for i, n in enumerate(names)}
