"""Monoid-pytree reductions over the mesh — the statistics comm backend.

The reference computes every distributed statistic as an algebird monoid
reduced via Spark ``reduce``/``reduceByKey``/``treeAggregate`` (SURVEY §2.7
P2: RawFeatureFilter summaries, SmartTextVectorizer TextStats, SanityChecker
contingency). Here the same algebra runs as:

- inside ``shard_map``: ``tree_psum(stats, axis="data")`` — XLA all-reduce
  over ICI, one collective per fused stats program;
- at host level (multi-process): ``jax.experimental.multihost_utils`` style
  all-gather is unnecessary because stats arrays are device-resident and
  jit output shardings already materialize the reduced value replicated.

A "monoid" here is any pytree of arrays whose combine is elementwise ``+``
(sums, counts, histograms, contingency tables) — min/max/moment variants
provide their own combine.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from transmogrifai_tpu.parallel.mesh import (
    DATA_AXIS, MeshContext, shard_map_compat,
)

__all__ = ["tree_psum", "tree_pmax", "tree_pmin", "mesh_reduce_stats"]


def tree_psum(tree: Any, axis: str = DATA_AXIS) -> Any:
    """All-reduce-sum every leaf across a mesh axis (use under shard_map)."""
    return jax.tree_util.tree_map(lambda x: jax.lax.psum(x, axis), tree)


def tree_pmax(tree: Any, axis: str = DATA_AXIS) -> Any:
    return jax.tree_util.tree_map(lambda x: jax.lax.pmax(x, axis), tree)


def tree_pmin(tree: Any, axis: str = DATA_AXIS) -> Any:
    return jax.tree_util.tree_map(lambda x: jax.lax.pmin(x, axis), tree)


def mesh_reduce_stats(ctx: MeshContext,
                      local_stats_fn: Callable[..., Any],
                      *row_sharded_args: jax.Array,
                      reduce: Callable[[Any], Any] | None = None) -> Any:
    """Run a per-shard statistics function over row-sharded inputs and
    all-reduce the resulting monoid pytree across the data axis.

    ``local_stats_fn(*shard_args) -> stats pytree`` sees only its shard of the
    rows (masked rows contribute identity). The result is replicated.
    This is the direct analog of the reference's
    ``rdd.map(prepare).reduce(monoid.plus)``.

    ``reduce`` combines the per-shard pytrees (default ``tree_psum``); pass a
    custom combiner for non-additive monoids, e.g. one that psums sums but
    pmins/pmaxes extrema — it runs inside shard_map with the data axis bound.
    """
    combine = reduce if reduce is not None else tree_psum
    in_specs = tuple(
        P(DATA_AXIS, *([None] * (a.ndim - 1))) for a in row_sharded_args)

    def shard_fn(*args):
        return combine(local_stats_fn(*args))

    fn = shard_map_compat(shard_fn, mesh=ctx.mesh, in_specs=in_specs,
                          out_specs=P())
    return fn(*row_sharded_args)
