"""RecordInsightsLOCO: per-row leave-one-column-out explanations.

Parity: reference ``core/.../stages/impl/insights/RecordInsightsLOCO.scala:
52-347`` — for each row, zero each feature group's columns of the input
vector and measure the prediction delta; text/date hash groups aggregate
(Avg strategy); topK by absolute delta (or positives/negatives).

TPU-first: the reference loops per row per column; here the whole batch
evaluates all G group-masks in ONE vmapped program — ``[G]`` masked forward
passes over the full ``[n, d]`` matrix, all on device (SURVEY: "TPUs make
LOCO cheaper than the reference").
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.models.base import PredictionModel
from transmogrifai_tpu.stages.base import HostTransformer
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import VectorMetadata

__all__ = ["RecordInsightsLOCO"]

#: Avg-strategy column-sweep block size: peak memory is
#: [_AVG_CHUNK_COLS, n, d] masked inputs when XLA can't fuse the mask
#: into the score fn (gather-based tree predicts at hashed widths)
_AVG_CHUNK_COLS = 256


class RecordInsightsLOCO(HostTransformer):
    """OPVector -> TextMap of ``column/group name -> score delta`` (json
    numbers as strings, like the reference's insight map values)."""

    in_types = (ft.OPVector,)
    out_type = ft.TextMap

    def __init__(self, model: Optional[PredictionModel] = None,
                 top_k: int = 20, aggregate_groups: bool = True,
                 aggregation_strategy: str = "LeaveOutVector",
                 top_k_strategy: str = "Abs",
                 uid: Optional[str] = None):
        if aggregation_strategy not in ("LeaveOutVector", "Avg"):
            raise ValueError(
                f"unknown aggregation_strategy {aggregation_strategy!r}")
        if top_k_strategy not in ("Abs", "PositiveNegative"):
            raise ValueError(f"unknown top_k_strategy {top_k_strategy!r}")
        self.model = model
        self.top_k = top_k
        self.aggregate_groups = aggregate_groups
        #: reference VectorAggregationStrategy: LeaveOutVector zeroes the
        #: whole group at once; Avg averages the per-column LOCO deltas
        self.aggregation_strategy = aggregation_strategy
        #: reference TopKStrategy: Abs = top-k by |delta|;
        #: PositiveNegative = top k/2 positive + top k/2 negative
        self.top_k_strategy = top_k_strategy
        super().__init__(uid=uid)

    # -- grouping ------------------------------------------------------------
    def _groups(self, meta: Optional[VectorMetadata], d: int
                ) -> list[tuple[str, list[int]]]:
        if meta is None or meta.size != d:
            return [(f"col_{j}", [j]) for j in range(d)]
        if not self.aggregate_groups:
            return [(c.make_col_name(), [c.index]) for c in meta.columns]
        groups: dict[str, list[int]] = {}
        order: list[str] = []
        for c in meta.columns:
            # hash/date descriptor columns aggregate per parent feature;
            # pivot indicator columns stay individual (like the reference)
            if c.descriptor_value is not None and c.grouping is not None:
                key = f"{'_'.join(c.parent_feature)}::{c.grouping}"
            else:
                key = c.make_col_name()
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(c.index)
        return [(k, groups[k]) for k in order]

    # -- scoring -------------------------------------------------------------
    def _score_fn(self):
        model = self.model
        params = model.device_params()

        def score(X):
            out = model.device_apply(params, fr.VectorColumn(X))
            prob = out.probability
            if prob is not None and prob.ndim == 2 and prob.shape[1] >= 2:
                return prob[:, 1]
            return out.prediction

        return score

    def host_apply(self, *cols: fr.HostColumn) -> fr.HostColumn:
        col = cols[0]
        X = jnp.asarray(col.values, jnp.float32)
        n, d = X.shape
        meta = col.meta
        groups = self._groups(meta, d)
        if d == 0:  # zero-width vector (e.g. every key blocklisted):
            # nothing to leave out, every row's insight map is empty
            return fr.HostColumn(
                ft.TextMap, np.array([{} for _ in range(n)], dtype=object))
        score = self._score_fn()
        base = score(X)                                     # [n]
        if self.aggregation_strategy == "Avg":
            # per-COLUMN deltas, averaged within each group (reference Avg
            # strategy). The column sweep is CHUNKED (lax.map over blocks
            # of an inner vmap): a flat vmap over all d columns batches the
            # masked input to [d, n, d], which only stays un-materialized
            # if XLA fuses the mask into the score fn — for gather-based
            # tree predicts at hashed widths (d ~10k+) it may not, and the
            # program OOMs. Chunking caps the peak at [chunk, n, d] while
            # the per-chunk segment-sum keeps the running result at [G, n].
            group_of = np.zeros(d, np.int32)
            sizes = np.zeros(len(groups), np.float32)
            for gi, (_, idxs) in enumerate(groups):
                group_of[idxs] = gi
                sizes[gi] = len(idxs)
            chunk = min(d, _AVG_CHUNK_COLS)  # d >= 1 past the early return
            n_chunks = -(-d // chunk)
            pad = n_chunks * chunk - d
            # padded tail columns map to a scratch segment dropped below
            col_ids = jnp.arange(n_chunks * chunk).reshape(n_chunks, chunk)
            seg = jnp.concatenate(
                [jnp.asarray(group_of),
                 jnp.full((pad,), len(groups), jnp.int32)])

            def chunk_deltas(js):                            # [chunk] ids
                cd = jax.vmap(
                    lambda j: base - score(
                        X * (1.0 - jax.nn.one_hot(j, d, dtype=X.dtype))))(
                    jnp.minimum(js, d - 1))                  # [chunk, n]
                return jax.ops.segment_sum(
                    cd * (js < d)[:, None].astype(X.dtype), seg[js],
                    num_segments=len(groups) + 1)            # [G+1, n]

            summed = jax.lax.map(chunk_deltas, col_ids).sum(0)[:-1]
            deltas = np.asarray(summed / jnp.asarray(sizes)[:, None]).T
        else:
            masks = np.ones((len(groups), d), dtype=np.float32)
            for gi, (_, idxs) in enumerate(groups):
                masks[gi, idxs] = 0.0
            deltas = jax.vmap(lambda m: base - score(X * m))(
                jnp.asarray(masks))                          # [G, n]
            deltas = np.asarray(deltas).T                    # [n, G]
        names = [g for g, _ in groups]
        out = np.empty(n, dtype=object)
        for i in range(n):
            row = deltas[i]
            if self.top_k_strategy == "PositiveNegative":
                # top ceil(k/2) positives + floor(k/2) negatives, each side
                # capped at its own sign's supply — never pad one side with
                # the other's leftovers, never exceed top_k
                n_pos = (self.top_k + 1) // 2
                n_neg = self.top_k - n_pos
                order = np.argsort(-row)
                pos = [j for j in order[:n_pos] if row[j] > 0]
                neg = [j for j in order[::-1][:n_neg] if row[j] < 0]
                top = np.asarray(pos + neg, dtype=int)
            else:
                top = np.argsort(-np.abs(row))[:self.top_k]
            out[i] = {names[j]: f"{row[j]:.6f}" for j in top
                      if row[j] != 0.0}
        return fr.HostColumn(ft.TextMap, out)

    def transform_row(self, vec):
        host = fr.HostColumn(ft.OPVector,
                             np.asarray(vec, np.float32)[None, :])
        return self.host_apply(host).values[0]
