"""RecordInsightsLOCO: per-row leave-one-column-out explanations.

Parity: reference ``core/.../stages/impl/insights/RecordInsightsLOCO.scala:
52-347`` — for each row, zero each feature group's columns of the input
vector and measure the prediction delta; text/date hash groups aggregate
(Avg strategy); topK by absolute delta (or positives/negatives).

TPU-first: the reference loops per row per column; here the whole batch
evaluates all G group-masks in ONE vmapped program — ``[G]`` masked forward
passes over the full ``[n, d]`` matrix, all on device (SURVEY: "TPUs make
LOCO cheaper than the reference").

Compiled-program reuse (round 15): ``host_apply`` used to rebuild the
masked-score closure on EVERY call, so each invocation re-traced and
re-compiled the whole masked sweep — fatal for streaming scoring and the
line-rate serving path, which call it per batch. Programs now live in a
process-wide :data:`loco_programs` cache keyed on ``(model fingerprint,
padded batch rows, d, strategy, group layout)``; batches pad (replicating
the last row — scoring transforms are row-local) to the next power of two
so a stream of varying batch sizes touches a LOG-bounded set of shapes,
and ``transform_row``'s ``[1, d]`` program is one cached entry instead of
a fresh trace per row. The serving half (``serving/explain.py``) shares
the grouping/mask helpers here and compiles LOCO *into* the serving DAG's
padded-bucket programs.
"""

from __future__ import annotations

import collections
import hashlib
import json
import threading
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.models.base import PredictionModel
from transmogrifai_tpu.stages.base import HostTransformer
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import VectorMetadata

__all__ = ["RecordInsightsLOCO", "loco_groups", "group_masks",
           "stage_fingerprint", "loco_programs", "LocoProgramCache"]

#: Avg-strategy column-sweep block size: peak memory is
#: [_AVG_CHUNK_COLS, n, d] masked inputs when XLA can't fuse the mask
#: into the score fn (gather-based tree predicts at hashed widths)
_AVG_CHUNK_COLS = 256


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def loco_groups(meta: Optional[VectorMetadata], d: int,
                aggregate_groups: bool = True
                ) -> list[tuple[str, list[int]]]:
    """The LOCO feature-group layout of a ``d``-wide vector: hash/date
    descriptor columns aggregate per (parent feature, grouping); pivot
    indicator columns stay individual (like the reference). Without
    usable metadata every column is its own ``col_<j>`` group. Shared by
    the offline stage and the serving ``CompiledExplainer``."""
    if meta is None or meta.size != d:
        return [(f"col_{j}", [j]) for j in range(d)]
    if not aggregate_groups:
        return [(c.make_col_name(), [c.index]) for c in meta.columns]
    groups: dict[str, list[int]] = {}
    order: list[str] = []
    for c in meta.columns:
        if c.descriptor_value is not None and c.grouping is not None:
            key = f"{'_'.join(c.parent_feature)}::{c.grouping}"
        else:
            key = c.make_col_name()
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(c.index)
    return [(k, groups[k]) for k in order]


def group_masks(groups: Sequence[tuple[str, list[int]]],
                d: int) -> np.ndarray:
    """``[G, d]`` float32 leave-one-group-out masks (1 keeps, 0 drops)."""
    masks = np.ones((len(groups), d), dtype=np.float32)
    for gi, (_, idxs) in enumerate(groups):
        masks[gi, idxs] = 0.0
    return masks


def stage_fingerprint(model) -> str:
    """Content fingerprint of one fitted prediction stage (class + config
    + parameter bytes) — the LOCO program-cache key component that lets
    two stage instances over byte-identical fitted models share compiled
    programs while differently-fitted ones can never collide. Cached on
    the instance: the param pull + hash runs once per model."""
    cached = getattr(model, "_loco_fingerprint", None)
    if cached is not None:
        return cached
    h = hashlib.blake2b(digest_size=16)
    h.update(type(model).__name__.encode())
    try:
        h.update(json.dumps(model.config(), sort_keys=True,
                            default=str).encode())
    except Exception:  # config is id context only; params still hash (failure-ok)
        pass
    for leaf in jax.tree_util.tree_leaves(model.device_params()):
        h.update(np.asarray(leaf).tobytes())
    fp = h.hexdigest()
    try:
        model._loco_fingerprint = fp
    except Exception:  # unwritable stage: recompute next call (failure-ok)
        pass
    return fp


class LocoProgramCache:
    """Process-wide LRU of compiled LOCO programs.

    Keyed ``(model fingerprint, n_pad, d, strategy, G[, chunk])`` — the
    full jit-shape identity of one masked-sweep program. ``hits`` /
    ``insertions`` make program reuse counter-assertable (tests and the
    serving bench require repeat batches to be pure hits)."""

    def __init__(self, max_entries: int = 64):
        self.max_entries = int(max_entries)
        self._lock = threading.Lock()
        self._programs: "collections.OrderedDict" = collections.OrderedDict()
        self.hits = 0
        self.insertions = 0

    def get(self, key, factory):
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                self._programs.move_to_end(key)
                self.hits += 1
                return prog
        prog = factory()
        with self._lock:
            if key not in self._programs:
                self._programs[key] = prog
                self.insertions += 1
                while len(self._programs) > self.max_entries:
                    self._programs.popitem(last=False)
            else:  # racing factory: keep the first inserted program
                prog = self._programs[key]
                self.hits += 1
        return prog

    def __len__(self) -> int:
        with self._lock:
            return len(self._programs)

    def clear(self) -> None:
        with self._lock:
            self._programs.clear()
            self.hits = 0
            self.insertions = 0

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._programs), "hits": self.hits,
                    "insertions": self.insertions}


#: the process-wide compiled-LOCO-program cache
loco_programs = LocoProgramCache()


class RecordInsightsLOCO(HostTransformer):
    """OPVector -> TextMap of ``column/group name -> score delta`` (json
    numbers as strings, like the reference's insight map values)."""

    in_types = (ft.OPVector,)
    out_type = ft.TextMap

    def __init__(self, model: Optional[PredictionModel] = None,
                 top_k: int = 20, aggregate_groups: bool = True,
                 aggregation_strategy: str = "LeaveOutVector",
                 top_k_strategy: str = "Abs",
                 uid: Optional[str] = None):
        if aggregation_strategy not in ("LeaveOutVector", "Avg"):
            raise ValueError(
                f"unknown aggregation_strategy {aggregation_strategy!r}")
        if top_k_strategy not in ("Abs", "PositiveNegative"):
            raise ValueError(f"unknown top_k_strategy {top_k_strategy!r}")
        self.model = model
        self.top_k = top_k
        self.aggregate_groups = aggregate_groups
        #: reference VectorAggregationStrategy: LeaveOutVector zeroes the
        #: whole group at once; Avg averages the per-column LOCO deltas
        self.aggregation_strategy = aggregation_strategy
        #: reference TopKStrategy: Abs = top-k by |delta|;
        #: PositiveNegative = top k/2 positive + top k/2 negative
        self.top_k_strategy = top_k_strategy
        #: static device operands (group masks / segment maps) keyed by
        #: the group layout — a stream of same-schema batches re-uploads
        #: nothing (the [G, d] mask matrix is the expensive part)
        self._op_cache: dict = {}
        super().__init__(uid=uid)

    # -- grouping ------------------------------------------------------------
    def _groups(self, meta: Optional[VectorMetadata], d: int
                ) -> list[tuple[str, list[int]]]:
        return loco_groups(meta, d, self.aggregate_groups)

    # -- compiled programs ---------------------------------------------------
    def _score_expr(self):
        """The traced positive-class score of one masked input — shared
        by both strategies' programs. ``params`` ride as operands so the
        cached program serves any same-fingerprint stage instance."""
        model = self.model

        def score(params, X):
            out = model.device_apply(params, fr.VectorColumn(X))
            prob = out.probability
            if prob is not None and prob.ndim == 2 and prob.shape[1] >= 2:
                return prob[:, 1]
            return out.prediction

        return score

    def _leave_out_program(self):
        score = self._score_expr()

        def program(params, X, masks):
            base = score(params, X)                          # [n]
            return jax.vmap(lambda m: base - score(params, X * m))(
                masks)                                       # [G, n]

        return jax.jit(program)

    def _avg_program(self, d: int, n_groups: int):
        score = self._score_expr()

        def program(params, X, col_ids, seg):
            base = score(params, X)

            def chunk_deltas(js):                            # [chunk] ids
                cd = jax.vmap(
                    lambda j: base - score(params, X * (
                        1.0 - jax.nn.one_hot(j, d, dtype=X.dtype))))(
                    jnp.minimum(js, d - 1))                  # [chunk, n]
                return jax.ops.segment_sum(
                    cd * (js < d)[:, None].astype(X.dtype), seg[js],
                    num_segments=n_groups + 1)               # [G+1, n]

            return jax.lax.map(chunk_deltas, col_ids).sum(0)[:-1]

        return jax.jit(program)

    # -- scoring -------------------------------------------------------------
    def _deltas(self, X_host: np.ndarray,
                groups: Sequence[tuple[str, list[int]]]) -> np.ndarray:
        """``[n, G]`` LOCO deltas through the cached padded-bucket
        programs: rows pad (replicating the last row — scoring transforms
        are row-local, padded slots compute real discarded values) to the
        next power of two, so streaming batches of every size share a
        log-bounded program set and ``transform_row`` reuses ONE ``[1,
        d]`` program across rows."""
        n, d = X_host.shape
        n_pad = _next_pow2(n)
        if n_pad > n:
            X_host = np.concatenate(
                [X_host, np.repeat(X_host[-1:], n_pad - n, axis=0)])
        X = jnp.asarray(X_host)
        params = self.model.device_params()
        fp = stage_fingerprint(self.model)
        if self.aggregation_strategy == "Avg":
            # per-COLUMN deltas, averaged within each group (reference
            # Avg strategy). The column sweep is CHUNKED (lax.map over
            # blocks of an inner vmap): a flat vmap over all d columns
            # batches the masked input to [d, n, d], which only stays
            # un-materialized if XLA fuses the mask into the score fn —
            # for gather-based tree predicts at hashed widths (d ~10k+)
            # it may not, and the program OOMs. Chunking caps the peak at
            # [chunk, n, d] while the per-chunk segment-sum keeps the
            # running result at [G, n].
            chunk = min(d, _AVG_CHUNK_COLS)  # d >= 1 (zero-width returns
            layout = (d, chunk,              # before _deltas is called)
                      tuple((g, tuple(idxs)) for g, idxs in groups))
            ops = self._op_cache.get(("Avg", layout))
            if ops is None:
                group_of = np.zeros(d, np.int32)
                sizes = np.zeros(len(groups), np.float32)
                for gi, (_, idxs) in enumerate(groups):
                    group_of[idxs] = gi
                    sizes[gi] = len(idxs)
                n_chunks = -(-d // chunk)
                pad = n_chunks * chunk - d
                # padded tail columns map to a scratch segment (dropped)
                col_ids = jnp.asarray(np.arange(
                    n_chunks * chunk,
                    dtype=np.int32).reshape(n_chunks, chunk))
                seg = jnp.asarray(np.concatenate(
                    [group_of, np.full((pad,), len(groups), np.int32)]))
                ops = (col_ids, seg, sizes)
                self._op_cache = {("Avg", layout): ops}
            col_ids, seg, sizes = ops
            prog = loco_programs.get(
                (fp, n_pad, d, "Avg", len(groups), chunk),
                lambda: self._avg_program(d, len(groups)))
            summed = np.asarray(prog(params, X, col_ids,
                                     seg))                  # [G, n_pad]
            deltas = summed / sizes[:, None]
        else:
            layout = (d, tuple((g, tuple(idxs)) for g, idxs in groups))
            masks = self._op_cache.get(("LeaveOutVector", layout))
            if masks is None:
                masks = jnp.asarray(group_masks(groups, d))
                # one layout at a time: a schema change replaces the
                # cache instead of growing it unboundedly
                self._op_cache = {("LeaveOutVector", layout): masks}
            prog = loco_programs.get(
                (fp, n_pad, d, "LeaveOutVector", len(groups)),
                lambda: self._leave_out_program())
            deltas = np.asarray(prog(params, X, masks))
        return deltas[:, :n].T                               # [n, G]

    def host_apply(self, *cols: fr.HostColumn) -> fr.HostColumn:
        col = cols[0]
        X_host = np.asarray(col.values, np.float32)
        n, d = X_host.shape
        meta = col.meta
        groups = self._groups(meta, d)
        if d == 0:  # zero-width vector (e.g. every key blocklisted):
            # nothing to leave out, every row's insight map is empty
            return fr.HostColumn(
                ft.TextMap, np.array([{} for _ in range(n)], dtype=object))
        deltas = self._deltas(X_host, groups)
        names = [g for g, _ in groups]
        out = np.empty(n, dtype=object)
        for i in range(n):
            row = deltas[i]
            if self.top_k_strategy == "PositiveNegative":
                # top ceil(k/2) positives + floor(k/2) negatives, each side
                # capped at its own sign's supply — never pad one side with
                # the other's leftovers, never exceed top_k
                n_pos = (self.top_k + 1) // 2
                n_neg = self.top_k - n_pos
                order = np.argsort(-row)
                pos = [j for j in order[:n_pos] if row[j] > 0]
                neg = [j for j in order[::-1][:n_neg] if row[j] < 0]
                top = np.asarray(pos + neg, dtype=int)
            else:
                top = np.argsort(-np.abs(row))[:self.top_k]
            out[i] = {names[j]: f"{row[j]:.6f}" for j in top
                      if row[j] != 0.0}
        return fr.HostColumn(ft.TextMap, out)

    def transform_row(self, vec):
        host = fr.HostColumn(ft.OPVector,
                             np.asarray(vec, np.float32)[None, :])
        return self.host_apply(host).values[0]
