"""ModelInsights: the merged explainability report.

Parity: reference ``core/src/main/scala/com/salesforce/op/ModelInsights
.scala:64-858`` — one JSON merging: label summary, per-feature derived-column
insights (correlation, Cramér's V, model contribution = coefficients /
importances per model type), RawFeatureFilter results, SanityChecker
metadata, ModelSelector summary, and stage info. Assembled from the fitted
workflow's stages (the metadata-rides-with-the-schema pattern: every source
is already attached to its stage/model).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

__all__ = ["ModelInsights", "FeatureInsights", "DerivedColumnInsights"]


@dataclass
class DerivedColumnInsights:
    name: str
    index: int
    grouping: Optional[str] = None
    indicator_value: Optional[str] = None
    corr_label: Optional[float] = None
    variance: Optional[float] = None
    cramers_v: Optional[float] = None
    contribution: Optional[float] = None
    #: full raw->derived lineage (OpVectorColumnHistory analog,
    #: OpVectorMetadata.scala:216-277): origin raw features + every stage
    #: operation between them and this column
    origin_features: Optional[list] = None
    stages: Optional[list] = None

    def to_json(self):
        names = {"corr_label": "corrLabel", "cramers_v": "cramersV",
                 "indicator_value": "indicatorValue",
                 "origin_features": "parentFeatureOrigins",
                 "stages": "parentFeatureStages"}
        return {names.get(k, k): v for k, v in self.__dict__.items()
                if v is not None}


@dataclass
class FeatureInsights:
    name: str
    feature_type: str
    derived: list = field(default_factory=list)
    exclusion_reasons: list = field(default_factory=list)

    def to_json(self):
        return {
            "featureName": self.name,
            "featureType": self.feature_type,
            "derivedFeatures": [d.to_json() for d in self.derived],
            "exclusionReasons": list(self.exclusion_reasons),
        }


@dataclass
class ModelInsights:
    label_name: str
    label_summary: dict
    problem_type: str
    features: list = field(default_factory=list)
    selected_model: Optional[dict] = None
    sanity_check: Optional[dict] = None
    raw_feature_filter: Optional[dict] = None
    stage_info: list = field(default_factory=list)
    #: SensitiveFeatureInformation analog (reference ModelInsights carries
    #: the name-detection verdict per raw text feature): feature name ->
    #: {detected, probName, genderResultsByStrategy}
    sensitive: dict = field(default_factory=dict)

    # -- assembly ------------------------------------------------------------
    @staticmethod
    def from_workflow(model, prediction=None) -> "ModelInsights":
        """Build insights from a fitted WorkflowModel (reference
        modelInsights(feature))."""
        from transmogrifai_tpu.preparators.sanity_checker import DropIndicesModel
        from transmogrifai_tpu.selector.model_selector import SelectedModel

        pred_f = prediction or model._prediction_feature()
        label_f = model._label_feature(pred_f)

        from transmogrifai_tpu.ops.names import HumanNameDetectorModel

        selected: Optional[SelectedModel] = None
        sanity: Optional[DropIndicesModel] = None
        sensitive: dict[str, dict] = {}
        for t in model.stages():
            if isinstance(t, SelectedModel):
                selected = t
            if isinstance(t, DropIndicesModel):
                sanity = t
            if isinstance(t, HumanNameDetectorModel):
                info = dict(t.metadata or {})
                sensitive[t.input_names[0]] = {
                    "detected": bool(t.treat_as_name),
                    "probName": info.get("predictedNameProb"),
                    "genderResultsByStrategy":
                        info.get("genderResultsByStrategy", {}),
                }
            if hasattr(t, "sensitive_info") and callable(t.sensitive_info):
                # columns/keys a smart vectorizer removed as name/sensitive
                # (scalar SmartTextModel, the map variant, and any future
                # detector share this contract) — the removal must reach
                # the report
                sensitive.update(t.sensitive_info())

        problem = "unknown"
        summary_json = None
        if selected is not None and selected.summary is not None:
            summary_json = selected.summary.to_json()
            best = selected.summary.best_model_type.lower()
            if "regress" in best and "logistic" not in best:
                problem = "regression"
            else:
                problem = "classification"

        # derived-column insights: metadata + sanity stats + contributions
        per_feature: dict[str, FeatureInsights] = {}
        for f in model.raw_features:
            per_feature[f.name] = FeatureInsights(f.name, f.ftype.__name__)

        meta = None
        if sanity is not None and sanity.out_meta is not None:
            meta = sanity.out_meta
        else:
            # fall back to the metadata of the vector the prediction model
            # actually consumes (second SelectedModel input); if the name
            # can't be resolved, last vector-producing stage wins
            want = None
            if selected is not None and len(selected.input_names) > 1:
                want = selected.input_names[1]
            exact = last = None
            for t in model.stages():
                m = getattr(t, "out_meta", None)
                if m is None:
                    continue
                last = m
                if want is not None and m.name == want:
                    exact = m
            meta = exact if exact is not None else last

        contributions = None
        if selected is not None and hasattr(selected.model,
                                            "feature_contributions"):
            try:
                contributions = np.asarray(
                    selected.model.feature_contributions())
            except Exception:  # failure-ok: contributions are optional in the report
                contributions = None

        def _strip_index(name: str) -> str:
            base, _, tail = name.rpartition("_")
            return base if tail.isdigit() else name

        col_stats = {}
        cat_stats = {}
        if sanity is not None and sanity.summary is not None:
            s = sanity.summary
            # sanity stats carry pre-drop indices; keep-columns reindex, so
            # match on the index-stripped column name
            col_stats = {_strip_index(c.name): c for c in s.column_stats}
            cat_stats = dict(s.categorical_stats)

        if meta is not None:
            col_hist = meta.column_history() if meta.history else None
            for i, cm in enumerate(meta.columns):
                name = cm.make_col_name()
                stats = col_stats.get(_strip_index(name))
                group = cm.feature_group()
                h = col_hist[i] if col_hist else {}
                d = DerivedColumnInsights(
                    name=name, index=cm.index, grouping=cm.grouping,
                    indicator_value=cm.indicator_value,
                    origin_features=h.get("parentFeatureOrigins"),
                    stages=h.get("parentFeatureStages"),
                    corr_label=(float(stats.corr_label) if stats else None),
                    variance=(float(stats.variance) if stats else None),
                    cramers_v=(cat_stats.get(group, {}).get("cramersV")
                               if group else None),
                    contribution=(float(contributions[i])
                                  if contributions is not None
                                  and i < len(contributions) else None),
                )
                for parent in cm.parent_feature:
                    if parent in per_feature:
                        per_feature[parent].derived.append(d)

        rff = None
        res = getattr(model, "raw_filter_results", None)
        if res is not None:
            rff = res.to_json()
        # dropped-at-ingest features, with the filter's actual reasons
        for name in model.blocklisted:
            per_feature.setdefault(name, FeatureInsights(name, "unknown"))
            why = (res.exclusion_reasons.get(name)
                   if res is not None else None) or ["RawFeatureFilter"]
            per_feature[name].exclusion_reasons.extend(why)
        # per-key map exclusions attach to their (surviving) map feature
        if res is not None:
            for name, keys in res.map_key_exclusion_reasons.items():
                per_feature.setdefault(name, FeatureInsights(name, "unknown"))
                per_feature[name].exclusion_reasons.extend(
                    f"map key {k!r}: {r}"
                    for k, rs in sorted(keys.items()) for r in rs)

        label_summary = {"name": label_f.name}
        if getattr(model, "label_distribution", None):
            label_summary["distribution"] = model.label_distribution
        return ModelInsights(
            label_name=label_f.name,
            label_summary=label_summary,
            problem_type=problem,
            features=list(per_feature.values()),
            selected_model=summary_json,
            sanity_check=(sanity.summary.to_json()
                          if sanity is not None and sanity.summary else None),
            raw_feature_filter=rff,
            stage_info=[{"uid": t.uid, "operation": t.operation_name}
                        for t in model.stages()],
            sensitive=sensitive,
        )

    # -- rendering -----------------------------------------------------------
    def to_json(self) -> dict:
        return {
            "label": self.label_summary,
            "problemType": self.problem_type,
            "features": [f.to_json() for f in self.features],
            "selectedModel": self.selected_model,
            "sanityCheck": self.sanity_check,
            "rawFeatureFilter": self.raw_feature_filter,
            "stageInfo": self.stage_info,
            "sensitiveFeatures": self.sensitive,
        }

    def json(self) -> str:
        return json.dumps(self.to_json(), indent=2, default=str)

    def top_contributions(self, k: int = 10) -> list[tuple[str, float]]:
        rows = []
        for f in self.features:
            for d in f.derived:
                if d.contribution is not None:
                    rows.append((d.name, d.contribution))
        rows.sort(key=lambda t: -abs(t[1]))
        return rows[:k]

    def pretty(self, k: int = 15) -> str:
        """Multi-section report (the reference's prettyPrint tables:
        selected model, validation results, top contributions + label
        correlations, dropped columns, sensitive features)."""
        from transmogrifai_tpu.utils.table import Table
        sections: list[str] = []

        if self.selected_model:
            sm = self.selected_model
            rows = [("Best model", sm.get("bestModelName", "")),
                    ("Model type", sm.get("bestModelType", "")),
                    ("Validation", sm.get("validationType", "")),
                    ("Metric", sm.get("validationMetric", ""))]
            holdout = sm.get("holdoutEvaluation") or {}
            for ev_name, metrics in holdout.items():
                for mk, mv in (metrics or {}).items():
                    if isinstance(mv, (int, float)) and mv is not None:
                        rows.append((f"holdout {mk}", f"{mv:.4f}"))
            sections.append(str(Table(["Field", "Value"], rows,
                                      title="Selected model")))
            vals = sm.get("validationResults") or []
            if vals:
                metric = sm.get("validationMetric", "")
                def _key(r):
                    mv = (r.get("metricValues") or {}).get(metric)
                    return -(mv if mv is not None else float("-inf"))

                vrows = []
                for r in sorted(vals, key=_key):
                    mv = (r.get("metricValues") or {}).get(metric)
                    vrows.append((r.get("modelName", ""),
                                  "NaN" if mv is None else f"{mv:.4f}"))
                sections.append(str(Table(
                    ["Candidate", metric], vrows[:k],
                    title="Validation results")))

        contrib = [(n, f"{c:+.4f}") for n, c in self.top_contributions(k)]
        if contrib:
            sections.append(str(Table(["Derived column", "Contribution"],
                                      contrib,
                                      title="Top model contributions")))

        corr_rows = []
        for f in self.features:
            for d in f.derived:
                if d.corr_label is not None and np.isfinite(d.corr_label):
                    corr_rows.append((d.name, d.corr_label))
        if corr_rows:
            corr_rows.sort(key=lambda t: -abs(t[1]))
            sections.append(str(Table(
                ["Derived column", "Label correlation"],
                [(n, f"{c:+.4f}") for n, c in corr_rows[:k]],
                title="Top label correlations")))

        if self.sanity_check:
            dropped = self.sanity_check.get("dropped") or []
            if dropped:
                reasons = {c["name"]: "; ".join(c.get("reasons", []))
                           for c in self.sanity_check.get("columnStats", [])}
                sections.append(str(Table(
                    ["Dropped column", "Reason"],
                    [(n, reasons.get(n, "")) for n in dropped[:k]],
                    title="SanityChecker drops")))

        if self.sensitive:
            sections.append(str(Table(
                ["Feature", "Detected name", "P(name)"],
                [(n, str(d.get("detected")),
                  (f"{d['probName']:.3f}"
                   if d.get("probName") is not None else ""))
                 for n, d in self.sensitive.items()],
                title="Sensitive features (name detection)")))

        excl = [(f.name, "; ".join(f.exclusion_reasons))
                for f in self.features if f.exclusion_reasons]
        if excl:
            sections.append(str(Table(["Feature", "Excluded by"], excl,
                                      title="Excluded raw features")))
        return "\n\n".join(sections)
