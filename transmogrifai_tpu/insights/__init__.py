from transmogrifai_tpu.insights.model_insights import ModelInsights
from transmogrifai_tpu.insights.loco import RecordInsightsLOCO

__all__ = ["ModelInsights", "RecordInsightsLOCO"]
