from transmogrifai_tpu.insights.model_insights import ModelInsights
from transmogrifai_tpu.insights.loco import RecordInsightsLOCO
from transmogrifai_tpu.insights.corr import (
    RecordInsightsCorr, RecordInsightsCorrModel, insights_to_text,
    parse_insights,
)

__all__ = ["ModelInsights", "RecordInsightsLOCO", "RecordInsightsCorr",
           "RecordInsightsCorrModel", "insights_to_text", "parse_insights"]
