"""RecordInsightsCorr: correlation-based per-record insights + the insights
text parser.

Parity: reference ``core/.../stages/impl/insights/RecordInsightsCorr.scala``
(220 LoC) — an estimator of (predictions, feature vector) -> TextMap that
fits the feature<->prediction-score correlation matrix plus a feature
normalizer (MinMax / Znorm / MinMaxCentered over training stats), then per
record scores ``importance[p][j] = corr[p][j] * normalized_feature[j]`` and
keeps the topK columns by absolute importance. ``RecordInsightsParser.scala``
round-trips the TextMap: key = the column's metadata JSON, value = JSON
array of ``[prediction_index, importance]`` pairs.

TPU-first: the correlation matrix is ONE [d+p, n] x [n, d+p] MXU matmul over
standardized columns at fit (the Statistics.corr analog), and the per-record
importance/topK is a vectorized numpy pass — no per-row Python loops beyond
the final dict assembly.
"""

from __future__ import annotations

import json
from typing import Optional

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import (
    AllowLabelAsInput, Estimator, HostTransformer,
)
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import VectorColumnMetadata

__all__ = ["RecordInsightsCorr", "RecordInsightsCorrModel",
           "insights_to_text", "parse_insights"]

_NORM_TYPES = ("minMax", "zNorm", "minMaxCentered")


# ---------------------------------------------------------------------------
# RecordInsightsParser analog
# ---------------------------------------------------------------------------

def insights_to_text(column_meta_json: str,
                     score_by_pred: list[tuple[int, float]]) -> tuple[str, str]:
    """(key, value) strings for one column's insights — key is the column's
    metadata JSON, value a JSON array of [prediction index, importance]."""
    return column_meta_json, json.dumps(
        [[int(i), float(v)] for i, v in score_by_pred])


def parse_insights(text_map: dict
                   ) -> list[tuple[VectorColumnMetadata,
                                   list[tuple[int, float]]]]:
    """TextMap -> [(column metadata, [(prediction index, importance)])],
    sorted by max |importance| descending (RecordInsightsParser.parseInsights
    semantics)."""
    out = []
    for k, v in text_map.items():
        try:
            meta = VectorColumnMetadata.from_json(json.loads(k))
        except (json.JSONDecodeError, KeyError, TypeError):
            meta = VectorColumnMetadata((k,), ("Text",))
        pairs = [(int(i), float(s)) for i, s in json.loads(v)]
        out.append((meta, pairs))
    out.sort(key=lambda t: -max((abs(s) for _, s in t[1]), default=0.0))
    return out


# ---------------------------------------------------------------------------
# estimator + model
# ---------------------------------------------------------------------------

class RecordInsightsCorr(Estimator, AllowLabelAsInput):
    """(Prediction, OPVector) -> TextMap of per-record correlation insights.

    ``norm_type``: minMax | zNorm | minMaxCentered (reference NormType).
    """

    in_types = (ft.Prediction, ft.OPVector)
    out_type = ft.TextMap

    def __init__(self, top_k: int = 20, norm_type: str = "minMax",
                 uid: Optional[str] = None):
        if norm_type not in _NORM_TYPES:
            raise ValueError(f"norm_type must be one of {_NORM_TYPES}")
        self.top_k = top_k
        self.norm_type = norm_type
        super().__init__(uid=uid)

    def fit_model(self, data) -> "RecordInsightsCorrModel":
        pred_name, feat_name = self.input_names
        pcol = data.device_col(pred_name)
        fcol = data.device_col(feat_name)
        X = np.asarray(fcol.values, np.float64)
        prob = np.asarray(pcol.probability)
        P = prob if prob.size and prob.ndim == 2 else \
            np.asarray(pcol.prediction)[:, None]
        n = data.n_rows
        X, P = X[:n], P[:n]

        # feature normalizer from training stats (NormType.makeNormalizer)
        mn, mx = X.min(axis=0), X.max(axis=0)
        mean, std = X.mean(axis=0), X.std(axis=0)
        if self.norm_type == "minMax":
            s1, s2, offset = mn, mx - mn, 0.0
        elif self.norm_type == "zNorm":
            s1, s2, offset = mean, std, 0.0
        else:  # minMaxCentered
            s1, s2, offset = mn, (mx - mn) / 2.0, 1.0

        # corr(features, prediction columns) as one standardized matmul
        C = np.concatenate([X, P], axis=1)
        Z = (C - C.mean(axis=0)) / np.where(C.std(axis=0) > 0,
                                            C.std(axis=0), 1.0)
        corr_j = np.asarray(jnp.asarray(Z.T, jnp.float32)
                            @ jnp.asarray(Z, jnp.float32), np.float64) / \
            max(X.shape[0], 1)
        d = X.shape[1]
        score_corr = corr_j[d:, :d]                       # [p, d]
        const = C.std(axis=0) <= 0
        score_corr[:, const[:d]] = np.nan                 # undefined corr

        meta = fcol.metadata
        col_jsons = ([json.dumps(c.to_json()) for c in meta.columns]
                     if meta is not None and meta.size == d
                     else [json.dumps({"parentFeature": [f"col_{j}"],
                                       "parentFeatureType": ["OPVector"]})
                           for j in range(d)])
        return RecordInsightsCorrModel(
            top_k=self.top_k, score_corr=score_corr,
            scale1=np.asarray(s1), scale2=np.asarray(s2),
            offset=float(offset), col_jsons=col_jsons)


class RecordInsightsCorrModel(HostTransformer, AllowLabelAsInput):
    in_types = (ft.Prediction, ft.OPVector)
    out_type = ft.TextMap

    def __init__(self, top_k: int = 20, score_corr=None, scale1=None,
                 scale2=None, offset: float = 0.0, col_jsons=(),
                 uid: Optional[str] = None):
        self.top_k = top_k
        self.score_corr = None if score_corr is None \
            else np.asarray(score_corr, np.float64)
        self.scale1 = None if scale1 is None else np.asarray(scale1)
        self.scale2 = None if scale2 is None else np.asarray(scale2)
        self.offset = offset
        self.col_jsons = list(col_jsons)
        super().__init__(uid=uid)

    def runtime_input_names(self):
        return self.input_names[1:] if len(self.input_names) == 2 \
            else self.input_names

    def _normalize(self, X: np.ndarray) -> np.ndarray:
        safe = np.where(self.scale2 == 0.0, 1.0, self.scale2)
        out = (X - self.scale1) / safe - self.offset
        return np.where(self.scale2 == 0.0, 0.0, out)

    def host_apply(self, *cols: fr.HostColumn) -> fr.HostColumn:
        col = cols[-1]
        X = np.asarray(col.values, np.float64)
        n = X.shape[0]
        Z = self._normalize(X)                              # [n, d]
        corr = np.nan_to_num(self.score_corr, nan=0.0)      # [p, d]
        imp = np.einsum("pd,nd->npd", corr, Z)              # [n, p, d]
        by_col = np.abs(imp).max(axis=1)                    # [n, d]
        out = np.empty(n, dtype=object)
        k = min(self.top_k, X.shape[1])
        top_idx = np.argpartition(-by_col, k - 1, axis=1)[:, :k]
        for i in range(n):
            row = {}
            order = top_idx[i][np.argsort(-by_col[i, top_idx[i]])]
            for j in order:
                key, val = insights_to_text(
                    self.col_jsons[j],
                    [(p, imp[i, p, j])
                     for p in range(imp.shape[1])])
                row[key] = val
            out[i] = row
        return fr.HostColumn(ft.TextMap, out)

    def transform_row(self, *values):
        vec = np.asarray(values[-1], np.float64)[None, :]
        return self.host_apply(
            fr.HostColumn(ft.OPVector, vec)).values[0]

    def fitted_state(self):
        return {"score_corr": self.score_corr, "scale1": self.scale1,
                "scale2": self.scale2}

    def set_fitted_state(self, state):
        self.score_corr = np.asarray(state["score_corr"])
        self.scale1 = np.asarray(state["scale1"])
        self.scale2 = np.asarray(state["scale2"])

    def config(self):
        return {"top_k": self.top_k, "offset": self.offset,
                "col_jsons": self.col_jsons}

    @classmethod
    def from_config(cls, config, uid=None):
        return cls(uid=uid, **config)
