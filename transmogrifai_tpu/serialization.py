"""Fitted workflow persistence.

Parity: reference ``core/.../OpWorkflowModelWriter.scala:57-170`` /
``OpWorkflowModelReader.scala`` — a model saves as a json manifest (result
feature uids, every feature as a TransientFeature, per-stage class + config +
input wiring, layer assignment) plus the fitted arrays; loading reconstructs
stages via the stage registry (the analog of ctor reflection), rewires the
feature graph with the original uids, and restores fitted state.

Layout: ``<dir>/model.json`` + ``<dir>/arrays.npz``.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Optional

import numpy as np

from transmogrifai_tpu.dag import DagExecutor
from transmogrifai_tpu.features.feature import Feature, TransientFeature
from transmogrifai_tpu.stages.base import (
    STAGE_REGISTRY, FeatureGeneratorStage, PipelineStage,
)
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["save_model", "load_model", "MODEL_JSON", "ARRAYS_NPZ",
           "fitted_stage_record", "restore_fitted_stage",
           "resolve_stage_class"]

MODEL_JSON = "model.json"
ARRAYS_NPZ = "arrays.npz"
FORMAT_VERSION = 1


def _feature_json(f) -> dict:
    return f.to_transient().to_json()


def fitted_stage_record(t) -> tuple[dict, dict[str, np.ndarray]]:
    """One fitted transformer as a (json record, arrays) pair — the shared
    persistence unit of full-model save (``save_model``) and per-layer
    train checkpoints (``checkpoint.TrainCheckpoint``). Array-valued fitted
    state splits into the npz side keyed ``uid||key``; everything else
    rides in the record's ``stateJson``."""
    state = t.fitted_state()
    state_json: dict[str, Any] = {}
    arrays: dict[str, np.ndarray] = {}
    for k, v in state.items():
        if isinstance(v, np.ndarray):
            arrays[f"{t.uid}||{k}"] = v
        else:
            state_json[k] = v
    rec = {
        "class": type(t).__name__,
        "module": type(t).__module__,
        "uid": t.uid,
        "operationName": t.operation_name,
        "config": t.config(),
        "stateJson": state_json,
    }
    return rec, arrays


def resolve_stage_class(class_name: str, module: Optional[str] = None):
    """Stage class from the registry, importing ``module`` to fill it if
    needed (the analog of ctor reflection in the reference reader)."""
    cls = STAGE_REGISTRY.get(class_name)
    if cls is None and module:
        import importlib
        try:
            importlib.import_module(module)
        except ImportError:
            pass  # fall through to the actionable KeyError below
        cls = STAGE_REGISTRY.get(class_name)
    if cls is None:
        raise KeyError(f"Unknown stage class {class_name!r}; import its "
                       "module before loading")
    return cls


def restore_fitted_stage(rec: dict, arrays: dict) -> PipelineStage:
    """Rebuild a fitted transformer from a ``fitted_stage_record`` pair.
    The stage comes back UNWIRED (no input/output features) — callers graft
    it onto their feature graph (``load_model`` rebuilds one from the
    manifest; the train checkpoint reuses the live workflow's)."""
    cls = resolve_stage_class(rec["class"], rec.get("module"))
    stage: PipelineStage = cls.from_config(rec["config"], uid=rec["uid"])
    state: dict[str, Any] = dict(rec.get("stateJson") or {})
    prefix = f"{rec['uid']}||"
    for k, v in arrays.items():
        if k.startswith(prefix):
            state[k[len(prefix):]] = v
    if state:
        stage.set_fitted_state(state)
    return stage


def save_model(model, path: str, overwrite: bool = True) -> None:
    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(path)
        if os.path.isdir(path):
            shutil.rmtree(path)
        else:
            os.remove(path)
    os.makedirs(path)

    stages_json = []
    arrays: dict[str, np.ndarray] = {}
    for li, layer in enumerate(model.dag):
        for t in layer:
            rec, t_arrays = fitted_stage_record(t)
            arrays.update(t_arrays)
            rec.update({
                "inputFeatures": [_feature_json(f) for f in t.input_features],
                "outputFeature": _feature_json(t.get_output()),
                "layer": li,
            })
            stages_json.append(rec)

    from transmogrifai_tpu.utils.version import VersionInfo
    manifest = {
        "formatVersion": FORMAT_VERSION,
        "versionInfo": VersionInfo.to_json(),
        "resultFeatures": [_feature_json(f) for f in model.result_features],
        "rawFeatures": [_feature_json(f) for f in model.raw_features],
        "blocklisted": list(model.blocklisted),
        "labelDistribution": getattr(model, "label_distribution", None),
        "stages": stages_json,
    }
    with open(os.path.join(path, MODEL_JSON), "w") as fh:
        json.dump(manifest, fh, indent=2, default=_default)
    if arrays:
        np.savez(os.path.join(path, ARRAYS_NPZ), **arrays)


def _default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"Not JSON serializable: {type(o)}")


def load_model(path: str):
    from transmogrifai_tpu.workflow import WorkflowModel

    with open(os.path.join(path, MODEL_JSON)) as fh:
        manifest = json.load(fh)
    if manifest.get("formatVersion") != FORMAT_VERSION:
        raise ValueError(f"Unsupported model format {manifest.get('formatVersion')}")
    arrays_path = os.path.join(path, ARRAYS_NPZ)
    arrays = dict(np.load(arrays_path, allow_pickle=False)) \
        if os.path.exists(arrays_path) else {}

    features: dict[str, Feature] = {}

    def build_feature(d: dict, origin, parents) -> Feature:
        if d["uid"] in features:
            return features[d["uid"]]
        f = Feature(name=d["name"], uid=d["uid"],
                    ftype=ft.feature_type_of(d["typeName"]),
                    origin_stage=origin, parents=tuple(parents),
                    is_response=d["isResponse"])
        features[d["uid"]] = f
        return f

    # raw features first (origin: reconstructed generator stages)
    raw_feats = []
    for d in manifest["rawFeatures"]:
        gen = FeatureGeneratorStage(name=d["name"], ftype_name=d["typeName"],
                                    is_response=d["isResponse"],
                                    uid=d["originStage"])
        f = build_feature(d, gen, ())
        gen._output = f
        raw_feats.append(f)

    # stages in saved (layer) order; inputs must already exist
    n_layers = 1 + max((s["layer"] for s in manifest["stages"]), default=0)
    dag = [[] for _ in range(n_layers)]
    for s in manifest["stages"]:
        stage: PipelineStage = restore_fitted_stage(s, arrays)
        ins = []
        for fd in s["inputFeatures"]:
            if fd["uid"] not in features:
                raise KeyError(
                    f"Stage {s['uid']} input feature {fd['uid']} not yet built "
                    "(manifest order corrupt)")
            ins.append(features[fd["uid"]])
        stage._inputs = tuple(ins)  # bypass validation: graph is trusted
        out = build_feature(s["outputFeature"], stage, ins)
        stage._output = out
        # type-preserving stages (alias, map filters) resolve their output
        # type from the wired input at set_input time, which this loader
        # bypasses — restore the concrete type from the manifest
        if type(stage).out_type in (ft.FeatureType, ft.OPMap,
                                    ft.OPCollection):
            stage.out_type = out.ftype
        dag[s["layer"]].append(stage)

    result = [features[d["uid"]] for d in manifest["resultFeatures"]]
    return WorkflowModel(
        result_features=result, raw_features=raw_feats,
        dag=[l for l in dag if l], executor=DagExecutor(),
        blocklisted=manifest.get("blocklisted", []),
        label_distribution=manifest.get("labelDistribution"))
