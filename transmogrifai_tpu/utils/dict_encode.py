"""Dictionary encoding of text columns: the Criteo-scale ingest hot loop.

PipelineData turns categorical text columns into int32 codes + a sorted
vocabulary on first device use. The naive path (Python ``sorted(set)`` +
per-row dict lookups) crawls at Criteo widths (SURVEY §6: 26 categorical
columns x 10M+ rows), so the heavy pass is native:

- ASCII columns: one C++ pass (``native/dict_encode.cpp``) — open-addressing
  FNV hash over row byte-slices assigning first-seen ids; Python then sorts
  only the (small) unique set and remaps codes with one vectorized gather.
- everything else: ``np.unique(..., return_inverse=True)`` over a unicode
  array — C-speed sort-based encoding, no per-row interpreter work.
- tiny/ineligible columns: the original dict loop (also the parity oracle).

All three produce IDENTICAL output: codes into the sorted vocabulary, None
-> -1 (the contract ``pipeline_data._encode_text`` always had).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["dict_encode", "dict_encode_py", "scan_column"]

_native_lib = None
_native_tried = False


class _TrailingNul(Exception):
    """Column contains strings that differ only by trailing NUL bytes —
    they collapse in ANY fixed-width numpy layout ('a' == 'a\\x00' once
    zero-padded), so only the object-loop oracle encodes them correctly."""


def _check_trailing_nul(pvals: np.ndarray, fixed: np.ndarray) -> None:
    """Raise if zero-padding lost trailing NULs: compare true object
    lengths (one C loop) against the fixed-width readback lengths (which
    numpy strips trailing zeros from). Non-string objects (e.g. floats
    leaking into a text column — astype stringifies them) can't carry
    NULs, so they are exempt from the comparison."""
    if len(pvals) == 0:
        return

    def _len(v):
        return len(v) if isinstance(v, (str, bytes)) else -1

    lens = np.frompyfunc(_len, 1, 1)(pvals).astype(np.int64)
    strings = lens >= 0
    if (np.char.str_len(fixed)[strings] != lens[strings]).any():
        raise _TrailingNul

#: below this row count the setup cost beats the native win
_NATIVE_MIN_ROWS = 4096


def _native():
    global _native_lib, _native_tried
    if not _native_tried:
        _native_tried = True
        from transmogrifai_tpu.native import build_and_load
        lib = build_and_load("dict_encode.cpp", "dictenc")
        if lib is not None:
            import ctypes
            lib.dict_encode.argtypes = [
                ctypes.c_char_p,
                ctypes.c_int64,
                np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
                ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                ctypes.c_int64,
            ]
            lib.dict_encode.restype = ctypes.c_int64
        _native_lib = lib
    return _native_lib


def dict_encode_py(values) -> tuple[np.ndarray, list[str]]:
    """The original Python loop — parity oracle and small-column path."""
    vocab = sorted({v for v in values if v is not None})
    index = {v: i for i, v in enumerate(vocab)}
    codes = np.fromiter(
        (index.get(v, -1) if v is not None else -1 for v in values),
        count=len(values), dtype=np.int32)
    return codes, vocab


def _encode_ascii(values, null_mask: np.ndarray
                  ) -> Optional[tuple[np.ndarray, list[str]]]:
    """C++ path for all-ASCII string columns; None when ineligible.

    The buffer is built with ONE vectorized ``astype('S')`` (numpy encodes
    every row in C) into a fixed-width zero-padded matrix — no per-row
    Python anywhere on this path."""
    lib = _native()
    if lib is None:
        return None
    n = len(values)
    present = null_mask == 0
    pvals = values[present]
    try:
        strs = pvals.astype("S")  # raises on non-ASCII
    except (TypeError, ValueError, UnicodeEncodeError):
        return None
    _check_trailing_nul(pvals, strs)
    width = strs.dtype.itemsize
    if width == 0:  # all-empty column
        width = 1
        strs = strs.astype("S1")
    buf = np.zeros(n, dtype=f"S{width}")
    buf[present] = strs
    codes = np.empty(n, dtype=np.int32)
    max_u = min(n, 1 << 22)
    rep_rows = np.empty(max_u, dtype=np.int64)
    import ctypes
    n_unique = lib.dict_encode(
        buf.ctypes.data_as(ctypes.c_char_p),  # zero-copy view of the matrix
        np.int64(width), null_mask, np.int64(n), codes, rep_rows,
        np.int64(max_u))
    if n_unique < 0:  # cardinality blew the cap: sort path handles it
        return None
    if n_unique == 0:  # all-null column
        return np.full(n, -1, dtype=np.int32), []
    # sort the uniques (small) and remap first-seen ids -> sorted ranks
    reps = rep_rows[:n_unique]
    vocab_bytes = buf[reps]
    order = np.argsort(vocab_bytes)
    rank = np.empty(n_unique, dtype=np.int32)
    rank[order] = np.arange(n_unique, dtype=np.int32)
    out = np.where(codes >= 0, rank[np.clip(codes, 0, None)],
                   np.int32(-1)).astype(np.int32)
    return out, [v.decode("ascii") for v in vocab_bytes[order]]


def dict_encode(values) -> tuple[np.ndarray, list[str]]:
    """codes (int32, -1 for missing) + sorted vocabulary for a text column."""
    n = len(values)
    if n < _NATIVE_MIN_ROWS:
        return dict_encode_py(values)
    vals = np.asarray(values, dtype=object)
    null_mask = np.equal(vals, None).astype(np.uint8)
    try:
        native = _encode_ascii(vals, null_mask)
    except _TrailingNul:
        return dict_encode_py(values)
    if native is not None:
        return native
    # numpy sort-based fallback (non-ASCII / no toolchain): still C-speed
    present = null_mask == 0
    pvals = vals[present]
    try:
        strs = pvals.astype("U")
    except (TypeError, ValueError):
        return dict_encode_py(values)
    try:
        _check_trailing_nul(pvals, strs)
    except _TrailingNul:
        return dict_encode_py(values)
    vocab, inv = np.unique(strs, return_inverse=True)
    codes = np.full(n, -1, dtype=np.int32)
    codes[present] = inv.astype(np.int32)
    return codes, [str(v) for v in vocab]


def scan_column(vals: np.ndarray) -> tuple[np.ndarray, bool]:
    """ONE Python-level pass over an object column -> (null_mask,
    all_strings).

    ``all_strings`` gates the vectorized dict-encode-backed paths
    (SmartText fit/apply, keyed-map pivot fills): the encoder stringifies
    non-string objects, which would skew category matching between batch
    sizes and against the per-row paths. Folding the null mask into the
    same pass keeps per-column object traffic to a single sweep on the
    Criteo-scale hot path."""
    kind = np.frompyfunc(
        lambda v: 0 if v is None else (1 if isinstance(v, str) else 2),
        1, 1)(vals).astype(np.int8)
    return kind == 0, not (kind == 2).any()
