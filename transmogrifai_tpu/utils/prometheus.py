"""Prometheus text-exposition rendering of the process's metrics.

A tiny pull-model registry (no client_golang-style dependency): every
counter the framework already keeps — ``AppMetrics`` phases/stages,
``RunCounters``, ``SweepCounters``, and a server's ``ServingMetrics``
(latency-histogram buckets, queue depth, degraded gauge, per-padding-
bucket compiles) — renders into Prometheus text exposition format 0.0.4
on demand. ``serving/http.py`` serves the output at ``GET /metrics``.

Naming contract (linted by ``scripts/check_metric_names.py``):

- every metric name is ``snake_case`` with the ``transmogrifai_`` prefix,
- names are registry-unique,
- counters (monotonic within a run) end in ``_total``; gauges don't;
  histograms expose the standard ``_bucket``/``_sum``/``_count`` series.

Collection is lazy: each metric holds a ``collect()`` closure over the
live objects, so a scrape always reads current values and registering
costs nothing on the serving hot path.
"""

from __future__ import annotations

import functools
import os
import re
import time
from typing import Callable, Optional

__all__ = ["PromRegistry", "build_registry", "CONTENT_TYPE"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: process birth (this module imports with the package): the uptime
#: gauge's zero — restarts reset it, which is exactly what makes fleet
#: scrapes correlatable across restarts (a counter that dropped AND
#: uptime near zero = the process bounced, not the workload)
_PROCESS_T0 = time.monotonic()

_NAME_RE = re.compile(r"^transmogrifai_[a-z0-9]+(_[a-z0-9]+)*$")
_TYPES = ("counter", "gauge", "histogram")


def _escape(v) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Metric:
    def __init__(self, name: str, mtype: str, help_: str,
                 collect: Callable[[], list]):
        self.name = name
        self.mtype = mtype
        self.help = help_
        self.collect = collect


class PromRegistry:
    """Named metrics + their collectors; renders text exposition."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}

    def register(self, name: str, mtype: str, help_: str,
                 collect: Callable[[], list]) -> None:
        """``collect()`` returns ``[(labels_dict, value), ...]``; for
        histograms the value is ``{"buckets": {le: cumulative}, "sum":
        s, "count": n}``. Registration enforces the naming contract —
        a bad name is a bug, not a formatting choice."""
        if mtype not in _TYPES:
            raise ValueError(f"metric type {mtype!r}: one of {_TYPES}")
        if not _NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} must be snake_case with the "
                "transmogrifai_ prefix")
        if mtype == "counter" and not name.endswith("_total"):
            raise ValueError(
                f"counter {name!r} must carry the _total suffix "
                "(monotonic-counter naming convention)")
        if mtype != "counter" and name.endswith("_total"):
            raise ValueError(
                f"{mtype} {name!r} must NOT end in _total (reserved for "
                "counters)")
        if name in self._metrics:
            raise ValueError(f"metric {name!r} already registered")
        self._metrics[name] = _Metric(name, mtype, help_, collect)

    def names(self) -> list[str]:
        return sorted(self._metrics)

    def metric_types(self) -> dict[str, str]:
        return {m.name: m.mtype for m in self._metrics.values()}

    def render(self) -> str:
        """The whole registry in exposition format; a collector that
        raises is skipped with a comment line instead of failing the
        scrape (one broken gauge must not take down /metrics)."""
        lines: list[str] = []
        for name in self.names():
            m = self._metrics[name]
            lines.append(f"# HELP {m.name} {_escape(m.help)}")
            lines.append(f"# TYPE {m.name} {m.mtype}")
            try:
                samples = m.collect()
            except Exception as e:  # noqa: BLE001 — surfaced as a scrape comment
                lines.append(f"# collect failed: {type(e).__name__}: "
                             f"{_escape(e)}")
                continue
            for labels, value in samples:
                if m.mtype == "histogram":
                    for le, n in value["buckets"].items():
                        lines.append(
                            f"{m.name}_bucket"
                            f"{_fmt_labels({**labels, 'le': le})} {int(n)}")
                    lines.append(f"{m.name}_sum{_fmt_labels(labels)} "
                                 f"{_fmt_value(value['sum'])}")
                    lines.append(f"{m.name}_count{_fmt_labels(labels)} "
                                 f"{int(value['count'])}")
                else:
                    lines.append(f"{m.name}{_fmt_labels(labels)} "
                                 f"{_fmt_value(value)}")
        return "\n".join(lines) + "\n"


@functools.lru_cache(maxsize=1)
def _build_info_labels() -> dict:
    """One stable label set per process (version/platform provenance);
    cached — VersionInfo shells out to git on first call."""
    try:
        from transmogrifai_tpu.utils.version import VersionInfo
        info = VersionInfo.to_json()
    except Exception:  # noqa: BLE001 — build info must never break a scrape
        info = {}
    import platform as _platform
    return {"version": str(info.get("version") or "unknown"),
            "git_commit": str(info.get("gitCommit") or "unknown"),
            "jax_version": str(info.get("jaxVersion") or "unknown"),
            "backend": str(info.get("backend") or "unknown"),
            "python_version": _platform.python_version()}


def _process_collectors(reg: PromRegistry) -> None:
    """Series every registry carries: build provenance + uptime, so any
    fleet member's scrape is correlatable across restarts and versions
    (the Prometheus ``*_build_info`` convention: constant 1, labels
    carry the facts, dashboards ``join`` on them)."""
    reg.register(
        "transmogrifai_build_info", "gauge",
        "constant 1; labels carry version/git/jax/backend provenance",
        lambda: [(_build_info_labels(), 1)])
    reg.register(
        "transmogrifai_process_uptime_seconds", "gauge",
        "seconds since this process imported the framework",
        lambda: [({}, time.monotonic() - _PROCESS_T0)])


def _event_collectors(reg: PromRegistry) -> None:
    """The flight recorder's own accounting (``utils/events.py``): how
    much history the black box holds and whether it is losing any."""
    from transmogrifai_tpu.utils.events import events

    for attr, name, help_ in (
            ("emitted", "emitted", "wide events recorded"),
            ("dropped", "dropped", "events evicted from the bounded "
                                   "ring (oldest-first)"),
            ("spilled", "spilled", "events written to the durable JSONL "
                                   "spill"),
            ("spill_lost", "spill_lost", "events lost to spill write "
                                         "failures (the JSONL has "
                                         "holes)"),
            ("suppressed", "suppressed", "events withheld by rate "
                                         "limiting")):
        reg.register(f"transmogrifai_events_{name}_total", "counter",
                     help_, lambda a=attr: [({}, getattr(events, a))])
    reg.register("transmogrifai_events_ring_size", "gauge",
                 "events currently retained in the ring",
                 lambda: [({}, len(events))])


def _resource_collectors(reg: PromRegistry) -> None:
    """The ``transmogrifai_resource_*`` surface (``utils/resources.py``):
    degradation-ladder rungs taken (labeled by site), OOM/ENOSPC event
    counts, skipped best-effort writes, and live host-pressure gauges
    (RSS, free disk, 0/1 pressure against the configured budgets).
    Carried by EVERY registry, like the flight-recorder series — an
    operator must see pressure on whatever endpoint they already
    scrape."""
    from transmogrifai_tpu.utils import resources
    rc = resources.resource_counters

    reg.register(
        "transmogrifai_resource_degradations_total", "counter",
        "degradation-ladder rungs taken, by failing site",
        lambda: [({"site": s}, n)
                 for s, n in sorted(rc.to_json()
                                    ["degradationsBySite"].items())]
                or [({"site": "none"}, 0)])
    for attr, name, help_ in (
            ("oom_events", "oom_events",
             "RESOURCE_EXHAUSTED / allocator-OOM errors observed"),
            ("enospc_events", "enospc_events",
             "full-disk (ENOSPC) write failures observed"),
            ("writes_skipped", "writes_skipped",
             "best-effort durable writes skipped under the ENOSPC "
             "cooldown")):
        reg.register(f"transmogrifai_resource_{name}_total", "counter",
                     help_, lambda a=attr: [({}, getattr(rc, a))])
    reg.register(
        "transmogrifai_resource_rss_bytes", "gauge",
        "resident set size of this process",
        lambda: [({}, resources.rss_bytes())])
    reg.register(
        "transmogrifai_resource_disk_free_bytes", "gauge",
        "free bytes on the working filesystem (-1 = probe failed)",
        lambda: [({}, resources.disk_free_bytes())])
    def _pressure_samples():
        state = resources.pressure_state()
        return [({"kind": "rss"}, 1 if state["rssPressure"] else 0),
                ({"kind": "disk"}, 1 if state["diskPressure"] else 0)]

    reg.register(
        "transmogrifai_resource_pressure", "gauge",
        "1 while the sampled value breaches its configured budget",
        _pressure_samples)
    reg.register(
        "transmogrifai_resource_ladder_enabled", "gauge",
        "1 while the adaptive degradation ladder is enabled "
        "(TRANSMOGRIFAI_RESOURCE_LADDER)",
        lambda: [({}, 1 if resources.ladder_enabled() else 0)])


def _net_collectors(reg: PromRegistry) -> None:
    """The network data plane's ``transmogrifai_net_*`` surface
    (``serving/aiohttp_core.net_counters``): slow-client sheds, idle
    reaps, write-deadline aborts, connection-gate sheds, injected
    socket faults, idempotency dedupe hits/waits, and the router's
    hedge/retry classification counters. Carried by EVERY registry —
    chaos drills read these off whatever endpoint is already
    scraped."""
    from transmogrifai_tpu.serving.aiohttp_core import net_counters

    for attr, help_ in (
            ("accepted", "connections accepted by the event-loop "
                         "front"),
            ("shed_connections", "connections shed at the bounded "
                                 "accept gate (503 + Retry-After)"),
            ("slow_clients_shed", "requests shed by the header/body "
                                  "read deadline (slowloris defense; "
                                  "answered 408)"),
            ("idle_closed", "idle keep-alive connections reaped "
                            "silently"),
            ("write_timeouts", "replies aborted by the write deadline "
                               "(dead/slow peer)"),
            ("faults_injected", "socket faults delivered by the "
                                "netchaos proxy in this process"),
            ("dedupe_hits", "retried requests answered from the "
                            "idempotency ring instead of re-scored"),
            ("dedupe_waits", "duplicate requests that waited on the "
                             "original in-flight execution"),
            ("hedges", "tail-latency hedge requests launched to a "
                       "ring successor"),
            ("resets_retried", "mid-request transport failures "
                               "retried under an idempotency key"),
            ("refusals_spilled", "connect-refused replicas spilled "
                                 "past immediately (no retry budget "
                                 "charged)")):
        reg.register(f"transmogrifai_net_{attr}_total", "counter",
                     help_,
                     lambda a=attr: [({}, getattr(net_counters, a))])


def _ingest_collectors(reg: PromRegistry) -> None:
    """The fused-ingest/FE surface (round 14, ``utils/profiling.
    IngestCounters``): fused vs host-side FE stage-rows, fused program
    dispatches + OOM fallbacks, streaming prefetch accounting (chunks,
    decode seconds, consumer blocked seconds, live overlap ratio), the
    device-frame cache's reuse/store/pressure-drop counters, and the
    already-sharded device_put skips the pre-partitioned sweep handoff
    counts. Carried by EVERY registry, like the resource series."""
    from transmogrifai_tpu.dag import fe_fused_enabled
    from transmogrifai_tpu.utils.profiling import ingest_counters as ic

    for attr, name, help_ in (
            ("fe_fused_programs", "fe_fused_programs",
             "fused FE segment programs dispatched"),
            ("fe_fused_stages", "fe_fused_stages",
             "device transformer stages executed inside fused programs"),
            ("fe_fused_rows", "fe_fused_rows",
             "stage-rows (rows x stages) transformed by fused programs"),
            ("fe_host_rows", "fe_host_rows",
             "stage-rows transformed by the stagewise/host FE path"),
            ("fe_host_fallbacks", "fe_host_fallbacks",
             "fused segments degraded to the stagewise rung (OOM)"),
            ("chunks_prefetched", "chunks_prefetched",
             "ingest chunks decoded ahead by the prefetch thread"),
            ("frame_cache_reuses", "frame_cache_reuses",
             "device-frame cache hits (host->device transfer skipped)"),
            ("frame_cache_stores", "frame_cache_stores",
             "device frames registered in the cache"),
            ("frame_cache_drops", "frame_cache_drops",
             "cached device frames released under memory pressure"),
            ("presharded_skips", "presharded_skips",
             "device_puts skipped because the operand already carried "
             "the target sharding")):
        reg.register(f"transmogrifai_ingest_{name}_total", "counter",
                     help_, lambda a=attr: [({}, getattr(ic, a))])
    reg.register(
        "transmogrifai_ingest_prefetch_wait_seconds", "gauge",
        "cumulative consumer seconds blocked waiting on the prefetch "
        "queue", lambda: [({}, ic.prefetch_wait_s)])
    reg.register(
        "transmogrifai_ingest_decode_seconds", "gauge",
        "cumulative background decode seconds spent by the prefetcher",
        lambda: [({}, ic.decode_s)])

    def _overlap():
        # decode seconds the consumer did NOT wait for = overlapped work;
        # 1.0 = decode fully hidden behind device compute
        d = ic.decode_s
        if d <= 0:
            return [({}, 0.0)]
        return [({}, max(0.0, min(1.0, (d - ic.prefetch_wait_s) / d)))]

    reg.register(
        "transmogrifai_ingest_overlap_ratio", "gauge",
        "fraction of prefetch decode seconds hidden behind consumer "
        "compute (1 = fully overlapped)", _overlap)
    reg.register(
        "transmogrifai_ingest_fe_fused_enabled", "gauge",
        "1 while fused FE is enabled (TRANSMOGRIFAI_FE_FUSED)",
        lambda: [({}, 1 if fe_fused_enabled() else 0)])


def _devicewatch_collectors(reg: PromRegistry) -> None:
    """The device-execution observatory (``utils/devicewatch.py``):
    dispatch-watchdog stall accounting, the in-flight dispatch ledger,
    the all-device HBM census gauges, and the ``transmogrifai_compile_*``
    compile-telemetry series. Carried by EVERY registry, like the
    flight-recorder and resource series — a wedged device must be
    visible on whatever endpoint an operator already scrapes."""
    from transmogrifai_tpu.utils import devicewatch as dw

    # collectors go through the LOCKED to_json() copies, never the live
    # dicts: a scrape iterating by_site while a compile lands would raise
    # dictionary-changed-size (same discipline as the resource series)
    reg.register(
        "transmogrifai_device_stalls_total", "counter",
        "blocking device waits that exceeded their stall deadline, by "
        "guarded site",
        lambda: [({"site": s}, n)
                 for s, n in sorted(
                     dw.watchdog.to_json()["stallsBySite"].items())]
                or [({"site": "none"}, 0)])
    reg.register(
        "transmogrifai_device_guarded_waits_total", "counter",
        "blocking device waits armed under the dispatch watchdog",
        lambda: [({}, dw.watchdog.guards)])
    reg.register(
        "transmogrifai_device_autopsies_total", "counter",
        "stall autopsies fired (device.stall events / incident dumps)",
        lambda: [({}, dw.watchdog.autopsies)])
    reg.register(
        "transmogrifai_device_watch_enabled", "gauge",
        "1 while the dispatch watchdog is enabled "
        "(TRANSMOGRIFAI_DEVICEWATCH)",
        lambda: [({}, 1 if dw.watchdog.enabled else 0)])
    reg.register(
        "transmogrifai_device_pending_dispatches", "gauge",
        "device dispatches currently in flight (ledger entries)",
        lambda: [({}, len(dw.dispatch_ledger))])
    # bounded census: a scrape of a wedged backend serves the last good
    # sample instead of hanging /metrics exactly when it matters most
    reg.register(
        "transmogrifai_device_hbm_bytes_in_use", "gauge",
        "bytes in use summed across every local device (bounded census; "
        "0 when the backend exposes no memory stats)",
        lambda: [({}, dw.device_memory_bounded()[0])])
    reg.register(
        "transmogrifai_device_hbm_peak_bytes", "gauge",
        "peak bytes in use summed across every local device",
        lambda: [({}, dw.device_memory_bounded()[1])])
    # one locked snapshot shared by both compile collectors per scrape
    # (the same short-memo trick the SLO collectors use) — to_json()
    # copies the whole telemetry map, and doing it twice per scrape
    # doubles lock contention with the compile path's _on_event
    memo = {"t": 0.0, "v": None}

    def _by_site():
        now = time.monotonic()
        if memo["v"] is None or now - memo["t"] > 0.25:
            memo["v"] = dw.compile_telemetry.to_json()["bySite"]
            memo["t"] = now
        return memo["v"]

    reg.register(
        "transmogrifai_compile_programs_total", "counter",
        "XLA backend compiles observed, by attributed site",
        lambda: [({"site": s}, v["programs"])
                 for s, v in sorted(_by_site().items())]
                or [({"site": "none"}, 0)])
    reg.register(
        "transmogrifai_compile_wall_seconds_total", "counter",
        "XLA backend compile wall seconds, by attributed site",
        lambda: [({"site": s}, v["wallSeconds"])
                 for s, v in sorted(_by_site().items())]
                or [({"site": "none"}, 0)])
    reg.register(
        "transmogrifai_compile_slow_total", "counter",
        "backend compiles over the slow threshold "
        "(TRANSMOGRIFAI_SLOW_COMPILE_S)",
        lambda: [({}, dw.compile_telemetry.slow)])
    reg.register(
        "transmogrifai_compile_in_progress", "gauge",
        "program builds currently in flight (building() blocks open)",
        lambda: [({}, dw.compile_telemetry.in_progress)])
    reg.register(
        "transmogrifai_compile_max_wall_seconds", "gauge",
        "slowest backend compile observed this process",
        lambda: [({}, dw.compile_telemetry.max_wall_s)])


def _slo_collectors(reg: PromRegistry, engine) -> None:
    """The ``transmogrifai_slo_*`` surface over a ``utils.slo.SLOEngine``:
    targets, per-(alert, window) burn rates, and 0/1 alert states —
    enough for dashboards to chart budget burn and for an external
    alertmanager to mirror the engine's own firing decisions. The three
    gauge collectors share one short-lived memo so a single scrape runs
    a single engine evaluation (not three)."""
    memo = {"t": 0.0, "v": None}

    def samples(key):
        now = time.monotonic()
        if memo["v"] is None or now - memo["t"] > 0.25:
            memo["v"] = engine.gauge_samples()
            memo["t"] = now
        return memo["v"][key]

    reg.register(
        "transmogrifai_slo_target", "gauge",
        "configured good-fraction target per ratio objective",
        lambda: samples("targets"))
    reg.register(
        "transmogrifai_slo_burn_rate", "gauge",
        "error-budget burn rate per objective, alert and window "
        "(1.0 = exactly sustainable)",
        lambda: samples("burns"))
    reg.register(
        "transmogrifai_slo_alert_firing", "gauge",
        "1 while the objective's multi-window alert fires",
        lambda: samples("firing"))
    reg.register(
        "transmogrifai_slo_evaluations_total", "counter",
        "SLO engine evaluations",
        lambda: [({}, engine.evaluations)])


def _app_collectors(reg: PromRegistry) -> None:
    from transmogrifai_tpu.utils import profiling

    def phases(field: str):
        def collect():
            return [({"phase": k}, getattr(p, field))
                    for k, p in profiler_metrics().phases.items()]
        return collect

    def profiler_metrics():
        return profiling.profiler.metrics

    reg.register("transmogrifai_phase_wall_seconds_total", "counter",
                 "exclusive wall seconds per OpStep phase", phases("wall_s"))
    reg.register("transmogrifai_phase_device_seconds_total", "counter",
                 "attributed device-busy seconds per phase",
                 phases("device_s"))
    reg.register("transmogrifai_phase_runs_total", "counter",
                 "phase occurrences", phases("count"))
    reg.register("transmogrifai_phase_peak_hbm_bytes", "gauge",
                 "peak device HBM high-water mark attributed to the phase",
                 phases("peak_hbm_bytes"))
    reg.register(
        "transmogrifai_stage_wall_seconds_total", "counter",
        "inclusive wall seconds per DAG stage (tracing span rollup)",
        lambda: [({"stage": k}, v.get("wallSeconds", 0.0))
                 for k, v in profiler_metrics().stages.items()])
    reg.register(
        "transmogrifai_stage_device_seconds_total", "counter",
        "attributed device seconds per DAG stage",
        lambda: [({"stage": k}, v.get("deviceSeconds", 0.0))
                 for k, v in profiler_metrics().stages.items()])

    rc = profiling.run_counters
    for attr, help_ in (("layers_fitted", "DAG layers fit live"),
                        ("layers_resumed", "DAG layers replayed from a "
                                           "train checkpoint"),
                        ("stages_resumed", "stages restored from a train "
                                           "checkpoint"),
                        ("retries", "transient device retries"),
                        ("faults_injected", "chaos-plan faults delivered")):
        reg.register(f"transmogrifai_run_{attr}_total", "counter", help_,
                     lambda a=attr: [({}, getattr(rc, a))])

    sc = profiling.sweep_counters
    for attr, help_ in (("compiles", "XLA backend compiles during the "
                                     "family's sweep"),
                        ("device_dispatches", "sweep device program "
                                              "dispatches"),
                        ("host_syncs", "sweep device->host metric pulls"),
                        ("stacked_groups", "tree depth-groups dispatched "
                                           "fold x grid-stacked"),
                        ("lane_chunks", "HBM-guard lane chunks dispatched "
                                        "on the stacked tree path")):
        reg.register(
            f"transmogrifai_sweep_{attr}_total", "counter", help_,
            lambda a=attr: [({"family": name}, getattr(fc, a))
                            for name, fc in sc.families.items()])
    # run-level one-sync counters (round 9): unlabeled — they describe the
    # WHOLE sweep (the per-family host_syncs above count each family's
    # metric pull; run_host_syncs counts blocking settle barriers, 1 on
    # the async overlapped path however many families dispatched)
    for attr, name, help_ in (
            ("sweep_host_syncs", "run_host_syncs",
             "blocking device->host settle barriers for the whole sweep"),
            ("async_families", "async_families",
             "families dispatched asynchronously (metrics held as device "
             "futures until the single settle)"),
            ("refit_warm_starts", "refit_warm_starts",
             "winner refits warm-started from sweep state (stacked fold "
             "parameters / reused tree bin codes)")):
        reg.register(f"transmogrifai_sweep_{name}_total", "counter", help_,
                     lambda a=attr: [({}, getattr(sc, a))])


def _serving_collectors(reg: PromRegistry, lanes_fn) -> None:
    """The serving series over ``lanes_fn() -> [(labels, ServingMetrics),
    ...]`` — one sample set per lane. A single ``ScoringServer`` is the
    one-lane, no-labels case; a ``FleetServer`` emits the SAME series
    once per model with a ``model`` label, so dashboards aggregate or
    split without a second naming scheme."""
    def per_lane(attr: str):
        def collect():
            return [(labels, getattr(m, attr)) for labels, m in lanes_fn()]
        return collect

    for attr, name, help_ in (
            ("admitted", "requests_admitted", "requests accepted at the "
                                              "door"),
            ("completed", "requests_completed", "requests settled ok"),
            ("failed", "requests_failed", "requests settled with an error"),
            ("expired", "requests_expired", "requests whose queue deadline "
                                            "expired"),
            ("batches", "batches", "dispatched micro-batches"),
            ("degraded_batches", "degraded_batches", "batches served on "
                                                     "the row path"),
            ("data_error_batches", "data_error_batches",
             "batches row-scored for a malformed row (no degradation)"),
            ("batch_rows", "batch_rows", "rows dispatched in batches"),
            ("degraded_entries", "degraded_entries", "degraded-mode "
                                                     "entries"),
            ("recoveries", "recoveries", "compiled-path recoveries"),
            ("dispatch_retries", "dispatch_retries", "transient dispatch "
                                                     "retries"),
            ("batch_wall_s", "batch_wall_seconds", "cumulative batch "
                                                   "dispatch wall")):
        reg.register(f"transmogrifai_serving_{name}_total", "counter",
                     help_, per_lane(attr))
    reg.register(
        "transmogrifai_serving_rejected_total", "counter",
        "requests rejected at admission, by reason",
        lambda: [({**labels, "reason": "backpressure"},
                  m.rejected_backpressure)
                 for labels, m in lanes_fn()]
               + [({**labels, "reason": "invalid"}, m.rejected_invalid)
                  for labels, m in lanes_fn()])
    reg.register(
        "transmogrifai_serving_latency_seconds", "histogram",
        "request latency, admission to settlement",
        lambda: [(labels, m.latency_histogram())
                 for labels, m in lanes_fn()])
    reg.register(
        "transmogrifai_serving_queue_depth", "gauge",
        "requests waiting in the admission queue",
        lambda: [(labels, (m.queue_depth_fn or (lambda: 0))())
                 for labels, m in lanes_fn()])
    reg.register(
        "transmogrifai_serving_queue_capacity", "gauge",
        "admission queue bound",
        lambda: [(labels, m.queue_capacity or 0)
                 for labels, m in lanes_fn()])
    reg.register(
        "transmogrifai_serving_degraded", "gauge",
        "1 while the server is on the degraded row path",
        lambda: [(labels, m.degraded_active)
                 for labels, m in lanes_fn()])
    reg.register(
        "transmogrifai_serving_throughput_rolling_rps", "gauge",
        "completions/s over the rolling window",
        lambda: [(labels, m.rolling_rps()) for labels, m in lanes_fn()])
    reg.register(
        "transmogrifai_serving_throughput_lifetime_rps", "gauge",
        "completions/s since server start",
        lambda: [(labels, m.throughput_rps())
                 for labels, m in lanes_fn()])

    def per_bucket(attr: str):
        def collect():
            out = []
            for labels, m in lanes_fn():
                cc = m.compile_counters
                if cc is None:
                    continue
                out.extend(({**labels, "bucket": str(b)},
                            getattr(c, attr))
                           for b, c in sorted(cc.buckets.items()))
            return out
        return collect

    # precision-ladder lifecycle: the counters carry the bare
    # transmogrifai_precision_ prefix — the ladder is ONE surface
    # whether a lane or a fleet runs it — and the bits gauge rides the
    # serving namespace per lane (32 = f32 master, 16 = bf16, 8 = int8)
    for attr, name, help_ in (
            ("precision_promotions", "promotions",
             "precision-rung promotions accepted by the shadow gate "
             "(candidate within score-diff tolerance of f32)"),
            ("precision_rejections", "rejections",
             "candidate rungs rejected by the shadow gate (the batch "
             "served the f32 scores bit-identically)"),
            ("precision_demotions", "demotions",
             "gate-skipping precision demotions forced by resource "
             "pressure")):
        reg.register(f"transmogrifai_precision_{name}_total", "counter",
                     help_, per_lane(attr))
    reg.register(
        "transmogrifai_serving_precision_bits", "gauge",
        "active precision-rung width in bits per lane",
        per_lane("precision_bits"))
    reg.register("transmogrifai_serving_compiles_total", "counter",
                 "fused-program compiles per padding bucket",
                 per_bucket("compiles"))
    reg.register("transmogrifai_serving_dispatches_total", "counter",
                 "batch dispatches per padding bucket",
                 per_bucket("dispatches"))
    reg.register("transmogrifai_serving_cache_evictions_total", "counter",
                 "shared-cache entries evicted per padding bucket (the "
                 "next dispatch at that bucket recompiles)",
                 per_bucket("evictions"))


def _explain_collectors(reg: PromRegistry, servers_fn) -> None:
    """The explain-lane series over ``servers_fn() -> [(labels,
    ScoringServer), ...]`` (only servers whose explain lane is enabled).
    Same shape discipline as the serving series: one sample set per
    lane, ``model``-labeled under a fleet, unlabeled standalone — the
    ``transmogrifai_explain_*`` namespace is the explained-traffic half
    of every dashboard."""
    def lanes():
        return [(labels, srv.explain_metrics)
                for labels, srv in servers_fn()
                if srv.explain_metrics is not None]

    def per_lane(attr: str):
        def collect():
            return [(labels, getattr(m, attr)) for labels, m in lanes()]
        return collect

    for attr, name, help_ in (
            ("admitted", "requests_admitted", "explain requests accepted "
                                              "at the door"),
            ("completed", "requests_completed", "explain requests settled "
                                                "ok"),
            ("failed", "requests_failed", "explain requests settled with "
                                          "an error"),
            ("expired", "requests_expired", "explain requests whose queue "
                                            "deadline expired"),
            ("batches", "batches", "dispatched explain micro-batches"),
            ("degraded_batches", "degraded_batches",
             "explain batches served as row-path scores without "
             "attributions (ladder exhausted)"),
            ("batch_rows", "batch_rows", "rows dispatched in explain "
                                         "batches"),
            ("dispatch_retries", "dispatch_retries", "transient explain "
                                                     "dispatch retries"),
            ("batch_wall_s", "batch_wall_seconds", "cumulative explain "
                                                   "batch dispatch wall")):
        reg.register(f"transmogrifai_explain_{name}_total", "counter",
                     help_, per_lane(attr))
    reg.register(
        "transmogrifai_explain_rejected_total", "counter",
        "explain requests rejected at admission, by reason",
        lambda: [({**labels, "reason": "backpressure"},
                  m.rejected_backpressure)
                 for labels, m in lanes()]
               + [({**labels, "reason": "invalid"}, m.rejected_invalid)
                  for labels, m in lanes()])
    reg.register(
        "transmogrifai_explain_latency_seconds", "histogram",
        "explain request latency, admission to settlement",
        lambda: [(labels, m.latency_histogram())
                 for labels, m in lanes()])
    reg.register(
        "transmogrifai_explain_queue_depth", "gauge",
        "requests waiting in the explain admission queue",
        lambda: [(labels, (m.queue_depth_fn or (lambda: 0))())
                 for labels, m in lanes()])
    reg.register(
        "transmogrifai_explain_throughput_rolling_rps", "gauge",
        "explained completions/s over the rolling window",
        lambda: [(labels, m.rolling_rps()) for labels, m in lanes()])
    reg.register(
        "transmogrifai_explain_mask_chunk", "gauge",
        "current LOCO mask-chunk width (the serving.explain ladder rung "
        "halves it under memory pressure)",
        lambda: [(labels, srv.explainer.mask_chunk)
                 for labels, srv in servers_fn()
                 if srv.explainer is not None])
    reg.register(
        "transmogrifai_explain_groups", "gauge",
        "LOCO feature groups of the served vector (0 until the first "
        "explain dispatch resolves them)",
        lambda: [(labels, srv.explainer.n_groups or 0)
                 for labels, srv in servers_fn()
                 if srv.explainer is not None])

    def per_bucket(attr: str):
        def collect():
            out = []
            for labels, m in lanes():
                cc = m.compile_counters
                if cc is None:
                    continue
                out.extend(({**labels, "bucket": str(b)},
                            getattr(c, attr))
                           for b, c in sorted(cc.buckets.items()))
            return out
        return collect

    reg.register("transmogrifai_explain_compiles_total", "counter",
                 "explain-program compiles per padding bucket",
                 per_bucket("compiles"))
    reg.register("transmogrifai_explain_dispatches_total", "counter",
                 "explain batch dispatches per padding bucket",
                 per_bucket("dispatches"))


#: cap on `model`-labeled tenant series per scrape: the K busiest lanes
#: keep their own label, the tail aggregates into ONE `_other` sample
#: set. <= 0 = unlimited (the pre-tiering behavior)
TENANT_TOPK_ENV = "TRANSMOGRIFAI_METRICS_TENANT_TOPK"
TENANT_TOPK_DEFAULT = 20

#: the model=_other rollup series — per-tenant label cardinality is
#: bounded; everything still SUMS correctly across the label
TENANT_OTHER_LABEL = "_other"

_ROLLUP_SUM_ATTRS = frozenset({
    "admitted", "completed", "failed", "expired", "batches",
    "degraded_batches", "data_error_batches", "batch_rows",
    "degraded_entries", "recoveries", "dispatch_retries",
    "batch_wall_s", "rejected_backpressure", "rejected_invalid",
    "precision_promotions", "precision_rejections",
    "precision_demotions"})


class _ServingRollup:
    """The ``model="_other"`` aggregate over the tail lanes' metrics:
    counters sum, the latency histogram merges bucket-wise, gauges take
    the honest aggregate (sum for depth/capacity/rps, any() for the
    degraded flag). ``compile_counters`` is None — per-bucket compile
    series stay per-model-only: a bucket histogram summed across
    heterogeneous tail models would chart nothing anyone can act on."""

    compile_counters = None

    def __init__(self, members):
        self._members = list(members)

    def __getattr__(self, attr):
        if attr in _ROLLUP_SUM_ATTRS:
            return sum(getattr(m, attr) for m in self._members)
        raise AttributeError(attr)

    @property
    def degraded_active(self):
        return int(any(m.degraded_active for m in self._members))

    @property
    def precision_bits(self):
        # the honest aggregate is the WORST (narrowest) rung: a single
        # demoted tail lane must show through the rollup
        return min((m.precision_bits for m in self._members), default=32)

    @property
    def queue_capacity(self):
        return sum(m.queue_capacity or 0 for m in self._members)

    @property
    def queue_depth_fn(self):
        members = self._members
        return lambda: sum((m.queue_depth_fn or (lambda: 0))()
                           for m in members)

    def latency_histogram(self) -> dict:
        buckets: dict = {}
        total_sum = 0.0
        total_count = 0
        for m in self._members:
            h = m.latency_histogram()
            for le, cum in h["buckets"].items():
                buckets[le] = buckets.get(le, 0) + cum
            total_sum += h["sum"]
            total_count += h["count"]
        return {"buckets": buckets, "sum": total_sum,
                "count": total_count}

    def rolling_rps(self) -> float:
        return sum(m.rolling_rps() for m in self._members)

    def throughput_rps(self) -> float:
        return sum(m.throughput_rps() for m in self._members)


class _ExplainRollupLane:
    """Server-shaped wrapper carrying the tail lanes' explain rollup
    (``explainer`` stays None: mask-chunk/group gauges are
    per-model-only, like the compile buckets)."""

    explainer = None

    def __init__(self, members):
        self.explain_metrics = _ServingRollup(members)


def tenant_topk() -> int:
    env = os.environ.get(TENANT_TOPK_ENV)
    if env is None or not env.strip():
        return TENANT_TOPK_DEFAULT
    try:
        return int(float(env))
    except ValueError:
        return TENANT_TOPK_DEFAULT


def _split_topk_lanes(fleet, k: int) -> tuple:
    """``(top, tail)`` over the fleet's active lanes: the ``k`` busiest
    (lifetime admitted — stable under scrape-to-scrape load wiggle,
    unlike a rolling rate) keep their own ``model`` label; the rest
    roll up. Top is re-sorted by id so scrape output stays diff-able."""
    lanes = sorted(fleet.active_lanes().items())
    if k <= 0 or len(lanes) <= k:
        return lanes, []
    ranked = sorted(lanes,
                    key=lambda kv: (-kv[1].metrics.admitted, kv[0]))
    return sorted(ranked[:k]), ranked[k:]


def _fleet_collectors(reg: PromRegistry, fleet) -> None:
    """Fleet-level series: swap lifecycle, shared compiled-program cache
    accounting, per-model state — plus every serving series labeled
    ``model=<id>`` via ``_serving_collectors`` over the active lanes.

    Label cardinality is BOUNDED: at 1000 tenants, per-model series
    make every scrape megabytes, so only the top-K busiest lanes
    (``TRANSMOGRIFAI_METRICS_TENANT_TOPK``, default 20) keep their own
    ``model`` label and the tail aggregates into ``model="_other"``
    (fleet-wide sums over the label stay exact)."""
    topk = tenant_topk()

    def serving_lanes():
        top, tail = _split_topk_lanes(fleet, topk)
        out = [({"model": mid}, lane.metrics) for mid, lane in top]
        if tail:
            out.append(({"model": TENANT_OTHER_LABEL},
                        _ServingRollup([ln.metrics for _, ln in tail])))
        return out

    def explain_lanes():
        top, tail = _split_topk_lanes(fleet, topk)
        out = [({"model": mid}, lane) for mid, lane in top
               if getattr(lane, "explain_metrics", None) is not None]
        tail_m = [ln.explain_metrics for _, ln in tail
                  if getattr(ln, "explain_metrics", None) is not None]
        if tail_m:
            out.append(({"model": TENANT_OTHER_LABEL},
                        _ExplainRollupLane(tail_m)))
        return out

    _serving_collectors(reg, serving_lanes)
    _explain_collectors(reg, explain_lanes)
    fm = fleet.metrics
    for attr, name, help_ in (
            ("swaps", "swaps", "completed zero-downtime hot-swaps"),
            ("swap_failures", "swap_failures", "aborted hot-swaps (old "
                                               "version kept serving)"),
            ("shadow_parity_failures", "shadow_parity_failures",
             "hot-swaps aborted by the shadow-scoring parity gate"),
            ("models_registered", "models_registered", "registry "
                                                       "registrations"),
            ("models_unloaded", "models_unloaded", "registry unloads")):
        reg.register(f"transmogrifai_fleet_{name}_total", "counter",
                     help_, lambda a=attr: [({}, getattr(fm, a))])
    cache = fleet.program_cache
    for attr, name, help_ in (
            ("hits", "cache_hits", "shared compiled-program cache hits"),
            ("insertions", "cache_insertions", "shared-cache compiled "
                                               "entries inserted"),
            ("evictions", "cache_evictions", "shared-cache entries "
             "evicted by the HBM budget LRU")):
        reg.register(f"transmogrifai_fleet_{name}_total", "counter",
                     help_, lambda a=attr: [({}, getattr(cache, a))])
    reg.register("transmogrifai_fleet_cache_bytes", "gauge",
                 "accounted HBM bytes of cached compiled programs",
                 lambda: [({}, cache.current_bytes)])
    reg.register("transmogrifai_fleet_cache_budget_bytes", "gauge",
                 "configured shared-cache HBM budget (0 = unbounded)",
                 lambda: [({}, cache.budget_bytes or 0)])
    reg.register("transmogrifai_fleet_cache_entries", "gauge",
                 "live shared-cache entries",
                 lambda: [({}, len(cache))])
    reg.register("transmogrifai_fleet_models", "gauge",
                 "models with a running active lane",
                 lambda: [({}, len(fleet.active_lanes()))])
    def model_state():
        top, tail = _split_topk_lanes(fleet, topk)
        out = [({"model": mid, "state": lane.state}, 1)
               for mid, lane in top]
        if tail:
            counts: dict = {}
            for _, lane in tail:
                counts[lane.state] = counts.get(lane.state, 0) + 1
            out.extend(({"model": TENANT_OTHER_LABEL, "state": s}, n)
                       for s, n in sorted(counts.items()))
        return out

    reg.register(
        "transmogrifai_fleet_model_state", "gauge",
        "1 for each model's current readiness state (top-K lanes by "
        "traffic; the tail aggregates per state under model=\"_other\")",
        model_state)


def _tenancy_collectors(reg: PromRegistry, fleet) -> None:
    """Multi-tenant tiering series over a tenancy-enabled fleet: the
    residency ladder (RAM-tier bytes/budget, promotion and demotion
    counters per tier edge, cold starts) plus — when admission is on —
    the per-tenant fairness surface, top-K-capped with a
    ``tenant="_other"`` rollup exactly like the serving series."""
    store = fleet.tenancy_store
    tm = store.metrics
    reg.register("transmogrifai_tenancy_ram_bytes", "gauge",
                 "accounted host-RAM bytes of resident decoded models",
                 lambda: [({}, store.ram_bytes)])
    reg.register("transmogrifai_tenancy_ram_budget_bytes", "gauge",
                 "configured RAM-tier byte budget (0 = unbounded)",
                 lambda: [({}, store.ram_budget_bytes or 0)])
    reg.register("transmogrifai_tenancy_models_resident", "gauge",
                 "models resident in the host-RAM tier",
                 lambda: [({}, store.resident_count)])
    reg.register(
        "transmogrifai_tenancy_models_cold", "gauge",
        "registered models currently COLD (path-only; page in on "
        "first score)",
        lambda: [({}, sum(1 for d in fleet.registry.list()
                          if d.get("state") == "cold"))])
    reg.register(
        "transmogrifai_tenancy_promotions_total", "counter",
        "residency promotions, by tier edge (disk->RAM page-ins, "
        "RAM->HBM lane starts)",
        lambda: [({"tier": "ram"}, tm.promotions_disk_ram),
                 ({"tier": "hbm"}, tm.promotions_ram_hbm)])
    reg.register(
        "transmogrifai_tenancy_demotions_total", "counter",
        "residency demotions, by tier (RAM records dropped; HBM "
        "program entries evicted by a RAM demotion)",
        lambda: [({"tier": "ram"}, tm.demotions_ram),
                 ({"tier": "hbm"}, tm.demotions_hbm)])
    reg.register(
        "transmogrifai_tenancy_sheds_total", "counter",
        "pressure-rung shed passes (tier demotion under host "
        "RSS/disk pressure)",
        lambda: [({}, tm.sheds)])
    reg.register(
        "transmogrifai_tenancy_prewarms_total", "counter",
        "popularity-driven background page-ins",
        lambda: [({}, tm.prewarms)])
    reg.register(
        "transmogrifai_tenancy_cold_starts_total", "counter",
        "demand page-ins on first score (disk -> RAM -> lane)",
        lambda: [({}, tm.cold_starts)])
    reg.register(
        "transmogrifai_tenancy_cold_start_wall_seconds_total",
        "counter",
        "cumulative cold-start wall (first-score page-in latency)",
        lambda: [({}, tm.cold_start_wall_s)])
    admission = getattr(fleet, "admission", None)
    if admission is None:
        return
    topk = tenant_topk()

    def fairness(field: str):
        def collect():
            top, other = admission.metrics.topk(topk)
            out = [({"tenant": t}, row[field])
                   for t, row in sorted(top.items())]
            if other is not None:
                out.append(({"tenant": TENANT_OTHER_LABEL},
                            other[field]))
            return out
        return collect

    reg.register("transmogrifai_fairness_admitted_total", "counter",
                 "requests admitted through the tenant token bucket "
                 "(top-K tenants; tail under tenant=\"_other\")",
                 fairness("admitted"))
    reg.register("transmogrifai_fairness_throttled_total", "counter",
                 "requests throttled by the tenant token bucket "
                 "(answered 503 + Retry-After)",
                 fairness("throttled"))
    reg.register("transmogrifai_fairness_debt_seconds_total", "counter",
                 "cumulative suggested-wait seconds per tenant (how "
                 "hard each pushed past its fair share)",
                 fairness("debtSeconds"))
    reg.register(
        "transmogrifai_fairness_cold_start_waits_total", "counter",
        "requests that waited on a cold-start page-in",
        lambda: [({}, admission.metrics.cold_start_waits)])


def _router_collectors(reg: PromRegistry, router) -> None:
    """The scale-out router's series (``scaleout/router.py``): request
    outcomes, per-replica proxy counts, spillover/markdown/retry
    accounting, router-observed latency, and the routing table as a
    per-replica state gauge."""
    rm = router.metrics
    for attr, name, help_ in (
            ("completed", "requests_completed",
             "requests proxied to a 2xx reply"),
            ("failed", "requests_failed",
             "requests answered 5xx after every candidate"),
            ("client_errors", "requests_client_error",
             "4xx replies proxied back (caller errors)"),
            ("spillovers", "spillovers",
             "503-backpressured requests spilled to a ring successor"),
            ("retries", "retries",
             "requests retried on the next replica after a transport "
             "failure (replica kill = retries, not drops)"),
            ("markdowns", "markdowns",
             "replicas marked down by the router"),
            ("no_replica", "no_replica",
             "requests with no routable replica at all"),
            ("rebalances", "rebalances",
             "skew-triggered ring re-weightings applied"),
            ("refusals", "refusals",
             "connect-refused attempts spilled to the next candidate "
             "(provably undelivered; no retry budget charged)"),
            ("resets", "resets",
             "mid-request transport failures retried under the "
             "request's idempotency key"),
            ("hedges", "hedges",
             "tail-latency hedges launched past the replica's "
             "observed p99")):
        reg.register(f"transmogrifai_router_{name}_total", "counter",
                     help_, lambda a=attr: [({}, getattr(rm, a))])
    if getattr(router, "load_skew", None) is not None:
        reg.register(
            "transmogrifai_router_load_skew", "gauge",
            "max/mean primary EWMA load over ring members (1.0 = "
            "balanced; the supervisor's rebalance trigger)",
            lambda: [({}, router.load_skew())])
        reg.register(
            "transmogrifai_router_ring_weight", "gauge",
            "per-replica consistent-hash placement weight (vnode "
            "multiplier; rebalancing moves these)",
            lambda: [({"replica": rid}, w)
                     for rid, w in sorted(
                         router.ring.weights().items())]
                    or [({"replica": "none"}, 0)])
    reg.register(
        "transmogrifai_router_proxied_total", "counter",
        "requests proxied, by serving replica",
        lambda: [({"replica": rid}, n)
                 for rid, n in sorted(rm.to_json()["byReplica"]
                                      .items())]
                or [({"replica": "none"}, 0)])
    reg.register(
        "transmogrifai_router_latency_seconds", "histogram",
        "request latency through the router (proxy hop included)",
        lambda: [({}, rm.latency_histogram())])
    reg.register(
        "transmogrifai_router_replica_state", "gauge",
        "1 per replica in its current routing state (up/down/draining)",
        lambda: [({"replica": rid, "state": doc["state"]}, 1)
                 for rid, doc in sorted(router.replicas().items())])
    reg.register(
        "transmogrifai_router_replicas", "gauge",
        "replicas currently routable (state up)",
        lambda: [({}, sum(1 for d in router.replicas().values()
                          if d["state"] == "up"))])


def _scaleout_collectors(reg: PromRegistry, supervisor) -> None:
    """Supervisor lifecycle series (``scaleout/supervisor.py``):
    spawn/respawn/scale/roll counters plus desired-vs-live replica
    gauges."""
    sm = supervisor.metrics
    for attr, name, help_ in (
            ("spawns", "spawns", "replica processes spawned"),
            ("respawns", "respawns", "replica processes respawned "
                                     "after a crash"),
            ("scale_ups", "scale_ups", "fleet scale-up actions"),
            ("scale_downs", "scale_downs", "fleet scale-down actions"),
            ("rolls", "rolls", "completed rolling hot-swaps"),
            ("roll_failures", "roll_failures",
             "rolling hot-swaps halted (fleet converged on the old "
             "version)"),
            ("rollbacks", "rollbacks",
             "already-swapped replicas forced back to the old version "
             "by a halted roll"),
            ("rebalances", "rebalances",
             "skew-triggered ring rebalances the supervisor applied")):
        reg.register(f"transmogrifai_scaleout_{name}_total", "counter",
                     help_, lambda a=attr: [({}, getattr(sm, a))])
    reg.register(
        "transmogrifai_scaleout_desired_replicas", "gauge",
        "replica count the supervisor converges on",
        lambda: [({}, supervisor.desired_replicas)])
    reg.register(
        "transmogrifai_scaleout_live_replicas", "gauge",
        "replica processes currently alive",
        lambda: [({}, sum(1 for d in supervisor.to_json()["replicas"]
                          .values() if d["alive"]))])
    reg.register(
        "transmogrifai_scaleout_queue_ratio", "gauge",
        "mean replica admission-queue fill ratio (heartbeat-reported; "
        "the autoscaler's load signal)",
        lambda: [({}, supervisor.queue_ratio())])


def _continuous_collectors(reg: PromRegistry, cont) -> None:
    """The continuous-loop series over a ``ContinuousLoop``-shaped
    object: lifecycle counters from its ``metrics``
    (``ContinuousMetrics``), per-feature drift-score gauges from
    ``drift_scores()``, and window/staleness/buffer gauges."""
    cm = cont.metrics
    for attr, name, help_ in (
            ("batches", "batches", "stream micro-batches consumed"),
            ("rows", "rows", "stream rows consumed"),
            ("skipped_batches", "skipped_batches",
             "unreadable micro-batches dropped from training"),
            ("drift_triggers", "drift_triggers",
             "drift-window triggers (post hysteresis/cooldown)"),
            ("retrains", "retrains", "retrain attempts launched"),
            ("retrain_failures", "retrain_failures",
             "retrain attempts that failed (old model kept serving)"),
            ("promotions", "promotions",
             "retrained versions promoted through the hot-swap gate"),
            ("rollbacks", "rollbacks",
             "promotions rolled back by the shadow parity gate")):
        reg.register(f"transmogrifai_continuous_{name}_total", "counter",
                     help_, lambda a=attr: [({}, getattr(cm, a))])
    reg.register(
        "transmogrifai_continuous_drift_score", "gauge",
        "per-feature drift score of the last closed window (the "
        "configured metric: JS divergence or PSI; __label__ = label "
        "mean delta)",
        lambda: [({"feature": k}, v)
                 for k, v in sorted(cont.drift_scores().items())])
    reg.register(
        "transmogrifai_continuous_staleness_seconds", "gauge",
        "age of the serving model's training data (seconds since the "
        "last promotion)",
        lambda: [({}, cont.staleness_s())])
    reg.register(
        "transmogrifai_continuous_window", "gauge",
        "drift windows closed over the loop's lifetime",
        lambda: [({}, cont.window_seq())])
    reg.register(
        "transmogrifai_continuous_buffer_rows", "gauge",
        "rows accumulated in the retrain buffer",
        lambda: [({}, cont.buffer_rows())])


def build_registry(serving=None, server=None, fleet=None, continuous=None,
                   router=None, scaleout=None,
                   slo=None, include_app: bool = True) -> PromRegistry:
    """The standard registry: process-wide training/run/sweep series
    (``include_app``) plus the full serving surface — unlabeled for one
    ``ServingMetrics`` (``serving``), ``model``-labeled per lane plus the
    fleet swap/cache series for a ``FleetServer`` (``fleet``; mutually
    exclusive with ``serving``). ``continuous`` (a ``ContinuousLoop``)
    adds the ``transmogrifai_continuous_*`` drift/retrain/promotion
    series and composes with ``fleet`` — the loop's scrape endpoint
    exposes both. ``router`` (a ``scaleout.Router``) adds the
    ``transmogrifai_router_*`` proxy surface and ``scaleout`` (a
    ``scaleout.ReplicaSupervisor``) the ``transmogrifai_scaleout_*``
    lifecycle series — the scale-out control process scrapes both on
    one endpoint. ``slo`` (a ``utils.slo.SLOEngine``) adds the
    ``transmogrifai_slo_*`` burn-rate surface. ``server`` (a
    ``ScoringServer``) adds the ``transmogrifai_explain_*`` lane series
    when its explain lane is enabled (fleets get the model-labeled
    variant automatically). EVERY registry carries
    ``transmogrifai_build_info``, the
    process-uptime gauge, the flight recorder's
    ``transmogrifai_events_*`` accounting, the resource-pressure
    ``transmogrifai_resource_*`` series (degradation-ladder rungs,
    OOM/ENOSPC events, RSS/disk gauges), and the device-execution
    observatory's ``transmogrifai_device_*`` / ``transmogrifai_compile_*``
    series (watchdog stalls, in-flight dispatches, all-device HBM,
    compile walls), so any scrape is correlatable across restarts."""
    if serving is not None and fleet is not None:
        raise ValueError("pass serving= or fleet=, not both (the serving "
                         "series would collide)")
    reg = PromRegistry()
    _process_collectors(reg)
    _event_collectors(reg)
    _resource_collectors(reg)
    _net_collectors(reg)
    _devicewatch_collectors(reg)
    _ingest_collectors(reg)
    if include_app:
        _app_collectors(reg)
    if serving is not None:
        _serving_collectors(reg, lambda: [({}, serving)])
        if server is not None and \
                getattr(server, "explain_metrics", None) is not None:
            # the standalone server's explain lane (fleets wire their
            # model-labeled explain series via _fleet_collectors)
            _explain_collectors(reg, lambda: [({}, server)])
    if fleet is not None:
        _fleet_collectors(reg, fleet)
        if getattr(fleet, "tenancy_store", None) is not None:
            # multi-tenant tiering: residency-ladder + fairness series
            _tenancy_collectors(reg, fleet)
    if continuous is not None:
        _continuous_collectors(reg, continuous)
    if router is not None:
        # the scale-out front door (scaleout/router.py): the
        # transmogrifai_router_* proxy/markdown/latency surface
        _router_collectors(reg, router)
    if scaleout is not None:
        # the replica supervisor (scaleout/supervisor.py):
        # spawn/respawn/scale/roll lifecycle + replica gauges
        _scaleout_collectors(reg, scaleout)
    if slo is not None:
        _slo_collectors(reg, slo)
    return reg
