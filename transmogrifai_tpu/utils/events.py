"""Flight recorder: a bounded wide-event ring with durable JSONL spill
and dump-on-incident snapshots.

Spans (``utils/tracing.py``) answer "where did the time go"; the flight
recorder answers "what HAPPENED" — the black-box log every production
inference stack keeps so the 30 seconds before an incident can be
reconstructed after the fact. One process-global :class:`EventRing`
collects wide events from every subsystem:

==============================  =============================================
``serve.batch``                 micro-batch fan-in: one event per batch,
                                member trace ids (``traceIds``)
``serve.dispatch``              one batch dispatch (wall, rows, trace ids)
``serve.reply``                 per-batch settlement, columnar:
                                ``traceIds[i]`` <-> ``latenciesMs[i]``,
                                failures in ``failedIds`` (a member's
                                admission epoch = event ts - latencyMs;
                                queue wait = latencyMs - the batch's
                                dispatch wallMs)
``serve.expired``               queue-deadline expiries of traced requests
``serving.degraded_enter/exit`` compiled-path degradation lifecycle
``serving.backpressure_reject`` admission-queue rejections (rate-limited)
``serving.compile``             a padding bucket compiled a fused program
``fleet.swap`` / ``fleet.swap_failed`` / ``fleet.gate_rejected``
                                hot-swap lifecycle + shadow parity gate
``continuous.drift_trigger``    a drift window breached + triggered
``continuous.retrain`` / ``continuous.retrain_failed``
                                retrain attempts and their failures
``continuous.promoted``         the LINEAGE event: promoted version ->
                                drift window + retrain that produced it
``fault.injected``              a chaos-plan fault fired at a site
``http.access``                 sampled structured access log
``scaleout.replica_spawned`` / ``scaleout.replica_ready`` /
``scaleout.replica_down`` / ``scaleout.replica_stopped``
                                replica-process lifecycle (supervisor)
``scaleout.markdown`` / ``scaleout.markup``
                                router routing-table transitions
``scaleout.scale`` / ``scaleout.autoscale``
                                fleet resize (manual / signal-driven)
``scaleout.roll_started`` / ``scaleout.roll_step`` /
``scaleout.roll`` / ``scaleout.roll_failed``
                                rolling hot-swap lifecycle (a failed
                                roll's event names the halting replica,
                                the gate verdict and the rollback set)
==============================  =============================================

Design constraints (the serving hot path pays for this):

- **cheap**: ``emit`` is one ``time.time()``, a tuple build, and a
  deque append under a lock — no serialization. A disabled ring costs
  one attribute check.
- **bounded**: the ring keeps the newest ``maxlen`` events (evictions
  counted in ``dropped``); the JSONL spill is the durable record.
- **durable**: with ``configure(spill_path=...)`` every event is also
  appended to a JSONL file under the daemon's state dir, so ``grep
  <trace_id>`` reconstructs any request's path after the process is
  gone. Serialization + writes happen on a background writer thread
  (woken every ``flush_every`` pending events and on a short timer) —
  an inline flush would stall the batcher worker mid-settle and cost
  the hot path an order of magnitude more than the emit itself.
  ``flush()`` forces a synchronous drain (tests, incident dumps,
  interpreter exit).
- **incident snapshots**: :func:`dump_incident` freezes the recent event
  tail, the span-ring tail, and a metrics scrape into one JSON document
  — written automatically by the continuous loop on gate rejections,
  retrain abandonment, and unhandled loop errors.

Event documents are camelCase-keyed (the exported-JSON naming contract,
linted by ``scripts/check_metric_names.py``): ``{"ts": epoch_seconds,
"kind": ..., "traceId": ... , **attrs}``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

__all__ = ["EventRing", "events", "emit", "dump_incident"]

#: default bounded-ring capacity (a long-lived daemon keeps the newest)
DEFAULT_MAXLEN = 4096
#: spill serialization batch: events buffer in memory and hit the file
#: every this-many emits (amortizing json + write off the hot path)
DEFAULT_FLUSH_EVERY = 128


def _event_doc(ev: tuple) -> dict:
    ts, kind, trace_id, attrs = ev
    doc = {"ts": ts, "kind": kind}
    if trace_id is not None:
        doc["traceId"] = trace_id
    if attrs:
        doc.update(attrs)
    return doc


class EventRing:
    """Thread-safe bounded wide-event ring with optional JSONL spill."""

    def __init__(self, maxlen: int = DEFAULT_MAXLEN):
        self.enabled = True
        self._lock = threading.Lock()
        #: serializes actual file writes (writer thread vs sync flush)
        self._write_lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(maxlen))
        self._pending: list = []
        self._spill_path: Optional[str] = None
        self._spill_fh = None
        self._writer: Optional[threading.Thread] = None
        self._writer_wake = threading.Event()
        self._writer_stop = threading.Event()
        self.flush_every = DEFAULT_FLUSH_EVERY
        # counters (exported as transmogrifai_events_* series)
        self.emitted = 0
        self.dropped = 0
        self.spilled = 0
        self.spill_lost = 0
        self.suppressed = 0
        #: per-key state for emit_limited: key -> [last_ts, suppressed_n]
        self._limits: dict = {}

    # -- configuration -------------------------------------------------------
    def configure(self, *, spill_path: Optional[str] = None,
                  maxlen: Optional[int] = None,
                  flush_every: Optional[int] = None) -> "EventRing":
        """(Re)configure the ring. ``spill_path`` turns on the durable
        JSONL spill (parent dirs created; file appended — restarts keep
        the history) and starts the background writer; ``None`` turns
        both off. ``maxlen`` resizes the ring keeping the newest
        events."""
        self.flush()
        self._stop_writer()
        with self._lock:
            if self._spill_fh is not None:
                try:
                    self._spill_fh.close()
                except OSError:
                    pass
                self._spill_fh = None
            self._spill_path = spill_path
            if flush_every is not None:
                self.flush_every = max(int(flush_every), 1)
            if maxlen is not None and maxlen != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=int(maxlen))
        if spill_path is not None:
            self._writer_stop.clear()
            self._writer_wake.clear()
            self._writer = threading.Thread(
                target=self._writer_loop,
                name="transmogrifai-events-spill", daemon=True)
            self._writer.start()
        return self

    def _stop_writer(self) -> None:
        writer = self._writer
        if writer is None:
            return
        self._writer_stop.set()
        self._writer_wake.set()
        writer.join(timeout=5.0)
        self._writer = None

    @property
    def spill_path(self) -> Optional[str]:
        return self._spill_path

    def reset(self) -> None:
        """Drop every buffered event and counter (tests; ``configure``
        keeps history on purpose — a daemon's restart must not)."""
        with self._lock:
            self._ring.clear()
            self._pending = []
            self.emitted = self.dropped = 0
            self.spilled = self.spill_lost = self.suppressed = 0
            self._limits = {}

    # -- emission ------------------------------------------------------------
    def emit(self, kind: str, trace_id: Optional[str] = None,
             t: Optional[float] = None, **attrs) -> None:
        """Record one wide event. ``attrs`` keys are camelCase (they land
        verbatim in the JSONL). ``t`` backdates the event (epoch seconds)
        for retroactively recorded facts (e.g. admission times known only
        at batch pickup)."""
        if not self.enabled:
            return
        ev = (t if t is not None else time.time(), kind, trace_id,
              attrs or None)
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(ev)
            self.emitted += 1
            if self._spill_path is not None:
                self._pending.append(ev)
                wake = len(self._pending) >= self.flush_every
            else:
                wake = False
        if wake:
            # hand the batch to the writer thread — NEVER serialize or
            # write inline: an emit on the batcher worker would stall
            # the whole serving pipeline for the flush's duration
            self._writer_wake.set()

    def count_suppressed(self, n: int = 1) -> None:
        """Account events a caller withheld by its own rate limiting
        (e.g. the HTTP access-log per-second cap) — under the ring
        lock, so ``reset()`` and the exported counter stay coherent."""
        with self._lock:
            self.suppressed += n

    def emit_limited(self, key: str, min_interval_s: float, kind: str,
                     trace_id: Optional[str] = None, **attrs) -> bool:
        """``emit`` at most once per ``min_interval_s`` per ``key`` —
        for events a pathological regime fires at request rate (e.g.
        backpressure rejections under sustained overload). Suppressed
        occurrences are counted and reported on the next emitted event
        (``suppressedSince``), so the record shows volume, bounded."""
        now = time.monotonic()
        with self._lock:
            state = self._limits.get(key)
            if state is not None and now - state[0] < min_interval_s:
                state[1] += 1
                self.suppressed += 1
                return False
            since = state[1] if state is not None else 0
            self._limits[key] = [now, 0]
        if since:
            attrs["suppressedSince"] = since
        self.emit(kind, trace_id=trace_id, **attrs)
        return True

    # -- reading -------------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def tail(self, n: Optional[int] = None) -> list[dict]:
        """The newest ``n`` events (all retained when ``None``), oldest
        first, as JSON-able documents."""
        with self._lock:
            evs = list(self._ring)
        if n is not None:
            evs = evs[-n:]
        return [_event_doc(e) for e in evs]

    def find(self, trace_id: str) -> list[dict]:
        """Every retained event mentioning ``trace_id`` — as the event's
        own id or inside a member/id list attr (the in-memory analog of
        grepping the spill JSONL)."""
        out = []
        for doc in self.tail():
            if doc.get("traceId") == trace_id:
                out.append(doc)
                continue
            for v in doc.values():
                if isinstance(v, (list, tuple)) and any(
                        trace_id == m or (isinstance(m, (list, tuple))
                                          and trace_id in m) for m in v):
                    out.append(doc)
                    break
        return out

    def to_json(self) -> dict:
        with self._lock:
            return {"emitted": self.emitted, "dropped": self.dropped,
                    "spilled": self.spilled,
                    "spillLost": self.spill_lost,
                    "suppressed": self.suppressed,
                    "ringSize": len(self._ring),
                    "spillPath": self._spill_path}

    # -- spill ---------------------------------------------------------------
    def _writer_loop(self) -> None:
        while not self._writer_stop.is_set():
            self._writer_wake.wait(timeout=0.5)
            self._writer_wake.clear()
            self._drain()
        self._drain()

    def _drain(self) -> None:
        """Serialize + write whatever is pending. Takes the write lock
        first, the ring lock only for the list swap — serialization and
        IO never block emits."""
        with self._write_lock:
            with self._lock:
                if not self._pending:
                    return
                pending, self._pending = self._pending, []
                spill_path = self._spill_path
            if spill_path is None:
                return
            try:
                # chaos seam: the enospc kind exercises the counted
                # best-effort loss path below without a real full disk
                from transmogrifai_tpu.utils.faults import fault_point
                fault_point("events.spill")
                if self._spill_fh is None:
                    parent = os.path.dirname(spill_path)
                    if parent:
                        os.makedirs(parent, exist_ok=True)
                    self._spill_fh = open(spill_path, "a")
                # serialize one event at a time, yielding the GIL
                # between lines: a single join over a big batch would
                # hold the GIL in ~5ms slices and visibly starve the
                # batcher worker + submit loop on small hosts (the spill
                # is background work — it must LOSE every GIL race)
                write = self._spill_fh.write
                for e in pending:
                    write(json.dumps(_event_doc(e), default=str) + "\n")
                    time.sleep(0)
                self._spill_fh.flush()
                with self._lock:
                    self.spilled += len(pending)
            except OSError as e:
                # failure-ok: the spill is redundancy over the in-memory
                # ring; a full disk must not take the serving path down.
                # But the loss is ACCOUNTED — the exported counters must
                # say the JSONL has holes, not claim a complete record
                self._spill_fh = None
                with self._lock:
                    self.spill_lost += len(pending)
                from transmogrifai_tpu.utils.resources import (
                    is_disk_full, resource_counters,
                )
                if is_disk_full(e):
                    # a full disk is host pressure, not a local IO blip:
                    # count it on the resource surface too. Does NOT arm
                    # the durable-write cooldown — the spill's volume may
                    # not be the checkpoint volume, and checkpoint writes
                    # re-detect their own ENOSPC on first failure
                    resource_counters.note_enospc(arm_backoff=False)

    def flush(self) -> None:
        """Synchronously drain the pending spill (tests, incident dumps,
        shutdown)."""
        self._drain()

    def close(self) -> None:
        self._stop_writer()
        self._drain()
        with self._lock:
            if self._spill_fh is not None:
                try:
                    self._spill_fh.close()
                except OSError:
                    pass
                self._spill_fh = None


#: process-global flight recorder (like ``tracing.recorder``); the
#: continuous loop points its spill under state_dir at startup
events = EventRing()
emit = events.emit

import atexit  # noqa: E402 — after the global exists

atexit.register(events.close)


def dump_incident(dir_path: str, reason: str, *,
                  scrape_fn: Optional[Callable[[], str]] = None,
                  extra: Optional[dict] = None,
                  max_events: int = 1024,
                  max_spans: int = 512) -> Optional[str]:
    """Freeze the black box: write one JSON snapshot — the newest
    ``max_events`` flight-recorder events, the newest ``max_spans``
    closed spans, a metrics scrape (``scrape_fn()``, best-effort), the
    reason, and caller ``extra`` — under ``dir_path`` (an ``incidents/``
    subdir is created). Returns the written path, or ``None`` if the
    write failed (an incident dump must never compound the incident)."""
    from transmogrifai_tpu.utils.tracing import recorder
    events.flush()
    spans = recorder.spans[-max_spans:]
    doc = {
        "reason": reason,
        "at": time.time(),
        "events": events.tail(max_events),
        "eventCounters": events.to_json(),
        "spans": [{"spanId": s.span_id, "parentId": s.parent_id,
                   "name": s.name, "t0": s.t0, "t1": s.t1,
                   "wallSeconds": round(s.wall_s, 6),
                   "thread": s.thread, "attrs": dict(s.attrs)}
                  for s in spans],
        "extra": extra or {},
    }
    if scrape_fn is not None:
        try:
            doc["metrics"] = scrape_fn()
        except Exception as e:  # noqa: BLE001 — a broken collector must not lose the dump
            doc["metricsError"] = f"{type(e).__name__}: {e}"
    try:
        from transmogrifai_tpu.utils.durable import atomic_json_dump
        inc_dir = os.path.join(dir_path, "incidents")
        os.makedirs(inc_dir, exist_ok=True)
        slug = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason)[:60]
        path = os.path.join(
            inc_dir, f"incident_{int(time.time() * 1e3):013d}_{slug}.json")
        atomic_json_dump(doc, path, indent=1, default=str)
        return path
    except OSError:
        return None
