"""Transient device-failure retry: the framework's failure-detection seam.

Parity intent: the reference bounds and survives misbehaving distributed
work — Spark task retries plus the validator's ``maxWait`` on awaited
candidate futures (``core/.../selector/OpValidator.scala:108``). The TPU
analog of a lost executor is a transient device/tunnel error surfacing as a
``JaxRuntimeError`` with an UNAVAILABLE/ABORTED-class status (observed on
real hardware: identical programs fail then succeed on retry). Genuine
program bugs (shape errors, NaN asserts, OOM) are NOT retried.
"""

from __future__ import annotations

import time
import warnings
from typing import Callable, TypeVar

__all__ = ["is_transient_device_error", "with_device_retry"]

T = TypeVar("T")

#: status substrings treated as transient infrastructure failures
_TRANSIENT_MARKERS = (
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED", "CANCELLED",
    "infrastructure failure", "backend setup",
)


def is_transient_device_error(err: BaseException) -> bool:
    """True for runtime device errors worth retrying (flaky tunnel/device),
    False for deterministic program errors."""
    name = type(err).__name__
    if name not in ("JaxRuntimeError", "XlaRuntimeError", "RuntimeError"):
        return False
    msg = str(err)
    return any(m in msg for m in _TRANSIENT_MARKERS)


def with_device_retry(fn: Callable[..., T], *args,
                      retries: int = 2, backoff_s: float = 2.0,
                      **kwargs) -> T:
    """Call ``fn`` retrying transient device errors with linear backoff."""
    for attempt in range(retries + 1):
        try:
            return fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — filtered just below
            if attempt >= retries or not is_transient_device_error(e):
                raise
            warnings.warn(
                f"transient device error (attempt {attempt + 1}/"
                f"{retries + 1}), retrying: {str(e)[:140]}",
                RuntimeWarning)
            time.sleep(backoff_s * (attempt + 1))
    raise AssertionError("unreachable")
