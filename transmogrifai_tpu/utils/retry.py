"""Transient device-failure retry: the framework's failure-detection seam.

Parity intent: the reference bounds and survives misbehaving distributed
work — Spark task retries plus the validator's ``maxWait`` on awaited
candidate futures (``core/.../selector/OpValidator.scala:108``). The TPU
analog of a lost executor is a transient device/tunnel error surfacing as a
``JaxRuntimeError`` with an UNAVAILABLE/ABORTED-class status (observed on
real hardware: identical programs fail then succeed on retry). Genuine
program bugs (shape errors, NaN asserts, OOM) are NOT retried.

Classification walks the full ``__cause__``/``__context__`` chain: JAX and
framework layers routinely wrap the device error (``raise X from e``, or
implicitly while handling it), and a transient root cause stays transient
no matter how many wrappers ride on top.

Backoff is capped, jittered exponential — ``base * 2**attempt`` up to
``cap``, scaled by a uniform [0.5, 1) jitter so a pod's worth of hosts
retrying the same dead tunnel don't stampede in lockstep. Env-tunable
without touching call sites: ``TRANSMOGRIFAI_RETRY_MAX`` (attempts after
the first), ``TRANSMOGRIFAI_RETRY_BASE_S``, ``TRANSMOGRIFAI_RETRY_CAP_S``.
"""

from __future__ import annotations

import os
import random
import time
import warnings
from typing import Callable, Optional, TypeVar

__all__ = ["is_transient_device_error", "iter_error_chain",
           "with_device_retry", "retry_backoff_s"]

T = TypeVar("T")

#: status substrings treated as transient infrastructure failures
_TRANSIENT_MARKERS = (
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED", "CANCELLED",
    "infrastructure failure", "backend setup",
)

#: jitter source — deliberately NOT the global random state (seeding the
#: framework's RNGs for reproducible sweeps must not make every host's
#: retry schedule identical, which would defeat the jitter)
_jitter = random.Random()


def _is_transient_one(err: BaseException) -> bool:
    # exact type names, not isinstance: RuntimeError has non-infrastructure
    # subclasses (NotImplementedError, RecursionError) that must never
    # match. CollectiveTimeoutError is the one subclass admitted — a
    # timed-out collective IS transient infrastructure (a slow peer may
    # recover; a dead one fails the retry too and the run resumes from
    # checkpoints)
    name = type(err).__name__
    if name not in ("JaxRuntimeError", "XlaRuntimeError", "RuntimeError",
                    "CollectiveTimeoutError"):
        return False
    msg = str(err)
    return any(m in msg for m in _TRANSIENT_MARKERS)


def iter_error_chain(err: BaseException):
    """Yield ``err`` and every exception in its ``__cause__``/
    ``__context__`` chain, honoring ``__suppress_context__`` (``raise X
    from None`` severs the chain — the raiser judged the failure
    self-contained) and guarding against cycles.

    THE shared walker for every error classifier: the transient check
    here and the OOM/ENOSPC checks in ``utils.resources`` must see the
    same chain, or a wrapped root cause would be transient to one layer
    and invisible to another."""
    seen: set[int] = set()
    e: Optional[BaseException] = err
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        yield e
        if e.__cause__ is not None:
            e = e.__cause__
        elif not e.__suppress_context__:
            e = e.__context__
        else:
            break


def is_transient_device_error(err: BaseException) -> bool:
    """True when ``err`` — or any exception in its ``__cause__``/
    ``__context__`` chain — is a runtime device error worth retrying
    (flaky tunnel/device); False for deterministic program errors
    (which includes allocator OOMs: see ``utils.resources.
    is_resource_exhausted`` — those are handled by the degradation
    ladder, one rung down, never retried at the same shape)."""
    return any(_is_transient_one(e) for e in iter_error_chain(err))


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    try:
        return float(v) if v else default
    except ValueError:
        warnings.warn(f"{name}={v!r} is not a number; using {default}",
                      RuntimeWarning)
        return default


def retry_backoff_s(attempt: int, base_s: float,
                    cap_s: Optional[float] = None) -> float:
    """Capped, jittered exponential backoff for retry ``attempt`` (0-based):
    ``min(cap, base * 2**attempt) * uniform(0.5, 1)``."""
    if cap_s is None:
        cap_s = _env_float("TRANSMOGRIFAI_RETRY_CAP_S", 30.0)
    raw = min(cap_s, base_s * (2.0 ** attempt))
    return raw * (0.5 + 0.5 * _jitter.random())


def with_device_retry(fn: Callable[..., T], *args,
                      retries: Optional[int] = None,
                      backoff_s: Optional[float] = None,
                      site: Optional[str] = None,
                      **kwargs) -> T:
    """Call ``fn`` retrying transient device errors (chain-aware) with
    capped jittered exponential backoff.

    ``retries``/``backoff_s`` keep their historical meaning (extra attempts
    / base delay) and default from ``TRANSMOGRIFAI_RETRY_MAX`` /
    ``TRANSMOGRIFAI_RETRY_BASE_S`` when not given. ``site`` names a
    :mod:`transmogrifai_tpu.utils.faults` injection point fired before
    every attempt, so injected transient faults exercise this exact retry
    loop. Each performed retry is counted in ``utils.profiling.
    run_counters.retries`` (surfaced in run summaries)."""
    from transmogrifai_tpu.utils.faults import fault_point
    from transmogrifai_tpu.utils.profiling import run_counters
    if retries is None:
        retries = int(_env_float("TRANSMOGRIFAI_RETRY_MAX", 2.0))
    if backoff_s is None:
        backoff_s = _env_float("TRANSMOGRIFAI_RETRY_BASE_S", 2.0)
    for attempt in range(retries + 1):
        try:
            if site is not None:
                fault_point(site)
            return fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — filtered just below
            if attempt >= retries or not is_transient_device_error(e):
                raise
            run_counters.retries += 1
            warnings.warn(
                f"transient device error (attempt {attempt + 1}/"
                f"{retries + 1}), retrying: {str(e)[:140]}",
                RuntimeWarning)
            time.sleep(retry_backoff_s(attempt, backoff_s))
    raise AssertionError("unreachable")
