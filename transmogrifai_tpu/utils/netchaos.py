"""Deterministic in-process TCP fault proxy: the network failure domain.

``utils/faults.py`` injects failures INSIDE a frame — a raised
exception, a stall, a simulated preemption. But the failures a
millions-of-users serving deployment sees first live BELOW the frame:
slow clients trickling headers, connections reset mid-frame, partial
writes that truncate a binary reply, flapping upstreams refusing
connects, black-holed peers that accept bytes and never answer. None of
those can be expressed as an exception at a ``fault_point`` — they have
to happen to real sockets carrying real bytes.

:class:`ChaosProxy` is a seeded, deterministic TCP proxy that sits
between any two hops of the data plane (client -> router, router ->
replica) and delivers network faults scheduled by the SAME
:class:`~transmogrifai_tpu.utils.faults.FaultPlan` grammar the rest of
the chaos harness uses — one plan string (one
``TRANSMOGRIFAI_FAULT_PLAN`` env var) drives both layers::

    reset@net.write#3          RST the connection on the 4th reply write
    truncate@net.write#5       forward half the reply bytes, then RST
    corrupt@net.read%0.01      seeded 1% per-read byte corruption
    delay@net.read:0.05        50 ms of added latency (with seeded jitter)
    refuse@net.connect#2x2     refuse the 3rd and 4th upstream dials
    blackhole@net.read#7       swallow a request and stall the socket
    split@net.write            dribble a reply out byte-by-byte

Sites count PER PROXY-WIDE invocation under the plan lock, so with
sequential traffic the ``plan.fired`` log is exactly reproducible: same
plan + same seed + same request sequence => same fired log (the
determinism contract tests assert on).

Fault kinds (``faults.NET_KINDS``) and where each is delivered:

==============  ==============================================================
``delay``       sleep ``:delay_s`` (seeded ±50% jitter) before forwarding
                (sites: accept, connect, read, write)
``reset``       hard RST (``SO_LINGER 0`` close) of both legs — the
                mid-request reset a retrying router must treat as
                "maybe delivered" (accept, read, write)
``refuse``      close the client leg before the upstream dial — the
                flapping-upstream analog (connect, accept)
``split``       forward the chunk one byte at a time for the first 8
                bytes, then the rest — exercises short-read handling in
                every framed reader (read, write)
``truncate``    forward only the first half of the chunk, then RST —
                a mid-frame truncation the wire codec must refuse
                loudly (read, write)
``corrupt``     flip one seeded byte of the chunk — exercises magic /
                length validation (read, write)
``blackhole``   stop forwarding and hold BOTH sockets open silently
                until the peer's deadline fires or the proxy stops —
                the dead-peer stall that bounded reads/writes must
                shed (accept, connect, read, write)
==============  ==============================================================

The proxy is threads + blocking sockets on purpose: it must be able to
wrap the asyncio front without sharing its event loop (a stalled proxy
thread models a stalled NETWORK, not a stalled server), and it must be
spawnable per-test in microseconds. Every delivered fault emits a
``net.fault`` flight-recorder event and increments
``net_counters.faults_injected`` (``serving/aiohttp_core.py``) so chaos
runs are self-explaining in an incident dump.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Optional

from transmogrifai_tpu.utils.faults import (
    FaultPlan,
    NET_KINDS,
    active_plan,
)

__all__ = ["ChaosProxy", "NET_KINDS"]

#: recv chunk size — small enough that multi-KB frames span several
#: ``net.read``/``net.write`` invocations (so mid-frame faults exist)
CHUNK = 16 << 10

#: blackhole park poll interval (the stall ends when the proxy stops)
_PARK_POLL_S = 0.05


class _Abort(Exception):
    """Internal: the current connection was chaos-terminated."""


def _rst_close(sock: Optional[socket.socket]) -> None:
    """Tear ``sock`` down abruptly: SO_LINGER 0 + shutdown + close, so
    the peer sees the connection die mid-exchange (RST, or FIN-then-RST
    when the shutdown races the close) exactly like a crashed or
    NAT-expired middlebox. The ``shutdown`` is load-bearing, not
    cosmetic: another proxy thread may be blocked in ``recv`` on this
    very socket, and a bare ``close`` would leave the kernel socket
    alive (the blocked read holds a file reference) — the peer would
    then see NOTHING until its own deadline fired, turning an injected
    reset into an accidental blackhole. Best-effort: the socket may
    already be gone."""
    if sock is None:
        return
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
    except OSError:
        pass
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class ProxyStats:
    """Plain counters (GIL-atomic increments, same idiom as the serving
    metrics objects)."""

    def __init__(self) -> None:
        self.connections = 0
        self.upstream_dials = 0
        self.bytes_up = 0        # client -> upstream
        self.bytes_down = 0      # upstream -> client
        self.faults_delivered = 0
        self.by_kind: dict[str, int] = {}

    def fault(self, kind: str) -> None:
        self.faults_delivered += 1
        self.by_kind[kind] = self.by_kind.get(kind, 0) + 1

    def to_json(self) -> dict:
        return {
            "connections": self.connections,
            "upstreamDials": self.upstream_dials,
            "bytesUp": self.bytes_up,
            "bytesDown": self.bytes_down,
            "faultsDelivered": self.faults_delivered,
            "byKind": dict(self.by_kind),
        }


class ChaosProxy:
    """A TCP proxy that forwards ``host:port`` -> ``upstream`` while
    delivering the active :class:`FaultPlan`'s ``net.*`` entries at the
    socket layer.

    ::

        plan = FaultPlan.parse("reset@net.write#2;delay@net.read:0.05",
                               seed=7)
        proxy = ChaosProxy(replica_port, plan=plan).start()
        router.set_replicas([ReplicaEndpoint("r0", port=proxy.port)])
        ...
        proxy.stop()
        assert ("net.write", 2, "reset") in plan.fired

    ``plan=None`` resolves :func:`active_plan` PER CONNECTION, so a proxy
    started before ``fault_plan(...)`` enters still sees the scoped
    plan — and a proxy with no plan at all is a transparent (if
    unflattering) byte pump.
    """

    def __init__(self, upstream_port: int,
                 upstream_host: str = "127.0.0.1", *,
                 plan: Optional[FaultPlan] = None,
                 port: int = 0, host: str = "127.0.0.1",
                 name: str = "netchaos",
                 connect_timeout_s: float = 5.0):
        self.upstream = (upstream_host, int(upstream_port))
        self.host = host
        self.name = name
        self.connect_timeout_s = float(connect_timeout_s)
        self._explicit_plan = plan
        self.port = int(port)
        self.stats = ProxyStats()
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._conn_lock = threading.Lock()
        self._live: set[socket.socket] = set()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ChaosProxy":
        srv = socket.create_server((self.host, self.port))
        srv.settimeout(0.2)  # deadline-ok: accept loop polls _stopping
        self.port = srv.getsockname()[1]
        self._listener = srv
        self._stopping.clear()
        t = threading.Thread(target=self._accept_loop,
                             name=f"{self.name}-accept", daemon=True)
        self._accept_thread = t
        t.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._conn_lock:
            live = list(self._live)
            self._live.clear()
        for s in live:
            _rst_close(s)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
            self._accept_thread = None

    def __enter__(self) -> "ChaosProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- plan plumbing -------------------------------------------------------

    def _plan(self) -> Optional[FaultPlan]:
        return self._explicit_plan if self._explicit_plan is not None \
            else active_plan()

    def _check(self, plan: Optional[FaultPlan], site: str) -> list:
        if plan is None:
            return []
        specs = plan.net_check(site)
        for s in specs:
            self._record(plan, site, s)
        return specs

    def _record(self, plan: FaultPlan, site: str, spec) -> None:
        self.stats.fault(spec.kind)
        # lazy imports: netchaos must stay importable from the jax-free
        # conformance stub without dragging anything heavy in
        from transmogrifai_tpu.serving.aiohttp_core import net_counters
        from transmogrifai_tpu.utils.events import events
        from transmogrifai_tpu.utils.profiling import run_counters
        net_counters.faults_injected += 1
        run_counters.faults_injected += 1
        events.emit("net.fault", proxy=self.name, site=site,
                    faultKind=spec.kind, upstreamPort=self.upstream[1])

    def _jittered(self, plan: Optional[FaultPlan], delay_s: float) -> float:
        # ±50% seeded jitter so two delay faults never beat in lockstep;
        # drawn from the PLAN's rng (under its lock) to stay reproducible
        if plan is None:
            return delay_s
        with plan._lock:
            return delay_s * (0.5 + plan._rng.random())

    # -- accept / connect ----------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                client, _addr = self._listener.accept()  # deadline-ok: 0.2s settimeout armed in start()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed under us: stop() ran
            self.stats.connections += 1
            t = threading.Thread(target=self._serve, args=(client,),
                                 name=f"{self.name}-conn", daemon=True)
            t.start()

    def _park(self, *socks: Optional[socket.socket]) -> None:
        """Blackhole: hold the sockets open, forward nothing, until the
        proxy stops. The PEER's armed deadline is what ends the stall —
        that is the point."""
        while not self._stopping.is_set():
            time.sleep(_PARK_POLL_S)
        for s in socks:
            _rst_close(s)

    def _serve(self, client: socket.socket) -> None:
        plan = self._plan()
        upstream: Optional[socket.socket] = None
        try:
            client.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            for spec in self._check(plan, "net.accept"):
                if spec.kind == "delay":
                    time.sleep(self._jittered(plan, spec.delay_s))
                elif spec.kind in ("reset", "refuse", "truncate",
                                   "corrupt", "split"):
                    _rst_close(client)
                    return
                elif spec.kind == "blackhole":
                    self._park(client)
                    return
            for spec in self._check(plan, "net.connect"):
                if spec.kind == "delay":
                    time.sleep(self._jittered(plan, spec.delay_s))
                elif spec.kind == "blackhole":
                    self._park(client)
                    return
                else:  # refuse / reset / anything else: no upstream dial
                    _rst_close(client)
                    return
            self.stats.upstream_dials += 1
            upstream = socket.create_connection(
                self.upstream, timeout=self.connect_timeout_s)
            upstream.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            upstream.settimeout(None)
            client.settimeout(None)
            with self._conn_lock:
                self._live.add(client)
                self._live.add(upstream)
            # reply pump runs beside us; request pump runs in this thread
            down = threading.Thread(
                target=self._pump, name=f"{self.name}-down",
                args=(upstream, client, "net.write", plan), daemon=True)
            down.start()
            self._pump(client, upstream, "net.read", plan)
            down.join(timeout=5.0)
        except (_Abort, OSError):
            pass
        finally:
            with self._conn_lock:
                self._live.discard(client)
                if upstream is not None:
                    self._live.discard(upstream)
            for s in (client, upstream):
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass

    # -- the byte pump -------------------------------------------------------

    def _pump(self, src: socket.socket, dst: socket.socket, site: str,
              plan: Optional[FaultPlan]) -> None:
        """Forward ``src`` -> ``dst`` chunk by chunk, delivering the
        plan's faults for ``site`` on each chunk."""
        try:
            while not self._stopping.is_set():
                try:
                    chunk = src.recv(CHUNK)  # deadline-ok: peers armed
                except OSError:
                    break
                if not chunk:
                    try:  # propagate half-close so HTTP EOF semantics hold
                        dst.shutdown(socket.SHUT_WR)
                    except OSError:
                        pass
                    break
                chunk = self._mangle(plan, site, chunk, src, dst)
                if chunk is None:
                    break
                try:
                    dst.sendall(chunk)
                except OSError:
                    break
                if site == "net.read":
                    self.stats.bytes_up += len(chunk)
                else:
                    self.stats.bytes_down += len(chunk)
        except _Abort:
            pass

    def _mangle(self, plan: Optional[FaultPlan], site: str, chunk: bytes,
                src: socket.socket,
                dst: socket.socket) -> Optional[bytes]:
        """Apply scheduled faults to one forwarded chunk. Returns the
        (possibly corrupted) bytes to forward, or ``None`` when the
        connection was chaos-terminated."""
        for spec in self._check(plan, site):
            if spec.kind == "delay":
                time.sleep(self._jittered(plan, spec.delay_s))
            elif spec.kind == "reset" or spec.kind == "refuse":
                _rst_close(dst)
                _rst_close(src)
                return None
            elif spec.kind == "truncate":
                half = chunk[: max(1, len(chunk) // 2)]
                try:
                    dst.sendall(half)
                except OSError:
                    pass
                _rst_close(dst)
                _rst_close(src)
                return None
            elif spec.kind == "corrupt":
                if plan is not None:
                    with plan._lock:
                        i = plan._rng.randrange(len(chunk))
                else:
                    i = len(chunk) // 2
                chunk = chunk[:i] + bytes([chunk[i] ^ 0xFF]) + chunk[i + 1:]
            elif spec.kind == "split":
                head = chunk[:8]
                try:
                    for b in head:
                        dst.sendall(bytes([b]))
                        time.sleep(0.001)
                except OSError:
                    return None
                chunk = chunk[8:]
            elif spec.kind == "blackhole":
                self._park(src, dst)
                return None
        return chunk
