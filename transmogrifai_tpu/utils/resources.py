"""Resource-exhaustion resilience: the adaptive degradation ladder.

The stacked sweep, the serving fleet's compiled-program cache, and the
continuous retrain loop all size their device programs against
*estimates* (``TRANSMOGRIFAI_SWEEP_HBM_BUDGET``, ``tree_stack_bytes``,
``layer_entry_bytes``). On hardware where an estimate is wrong, the
allocator answers with a real ``RESOURCE_EXHAUSTED`` ``XlaRuntimeError``
— which ``utils.retry`` correctly refuses to retry (the identical
program would OOM identically). Before this layer existed, that one
error killed a 4000-second run or poisoned a live scoring lane. The
Spark reference survives the analogous executor memory pressure by
spilling and retrying the stage; the TPU analog is to retry the failing
unit **one rung down a degradation ladder**:

==========================  ================================================
subsystem                   rungs (largest shape first)
==========================  ================================================
sweep, stacked family       fold-stacked program -> per-fold loop
sweep, tree depth-group     k x L lanes -> halved lane chunks -> ... ->
                            per-fold loop
winner refit                warm-started stacked refit -> cold refit
serving dispatch            evict cold shared-cache entries + shed the
                            largest padding bucket -> ... -> row path
continuous retrain          full buffer window -> halved row window +
                            backoff (the old model keeps serving)
durable writes              normal -> counted best-effort skip window on
                            ``ENOSPC`` (never raises mid-train)
==========================  ================================================

This module owns the pieces every subsystem shares:

- **classification**: :func:`is_resource_exhausted` recognizes genuine
  allocator OOMs (``RESOURCE_EXHAUSTED:``-status ``XlaRuntimeError``,
  allocator messages, host ``MemoryError``) by walking the SAME
  ``__cause__``/``__context__`` chain ``utils.retry`` walks
  (:func:`~transmogrifai_tpu.utils.retry.iter_error_chain` — one walker,
  two classifiers, they cannot drift). :func:`is_disk_full` does the
  errno-based equivalent for ``ENOSPC``/``EDQUOT``. These are THE
  classifiers: ad-hoc ``"RESOURCE_EXHAUSTED" in str(e)`` checks anywhere
  else fail the ``scripts/check_failure_paths.py`` lint.
- **accounting**: every rung taken counts in the process-global
  :data:`resource_counters` (per-site), emits a ``resource.degrade``
  flight-recorder event carrying the failing shape and the rung chosen,
  and exports as ``transmogrifai_resource_*`` Prometheus series (every
  registry carries them).
- **host watchdogs**: :func:`rss_bytes` / :func:`disk_free_bytes`
  samplers, budget envs (``TRANSMOGRIFAI_RSS_BUDGET``,
  ``TRANSMOGRIFAI_DISK_MIN_FREE``), :func:`pressure_state` for
  ``/healthz``, and the background :class:`ResourceWatchdog` the
  continuous daemon runs.

Gating: ``TRANSMOGRIFAI_RESOURCE_LADDER=0`` disables every rung — the
same faults then fail exactly as they always did (family failure
isolation, serving row-path degradation, retrain backoff), so the
ladder is an additive behavior, never a silent change.

Deterministic ``oom``/``enospc`` fault kinds (``utils/faults.py``) make
every rung exercisable on CPU; see docs/ROBUSTNESS.md "Resource
exhaustion".
"""

from __future__ import annotations

import errno
import os
import threading
import time
import warnings
from typing import Optional

from transmogrifai_tpu.utils.retry import iter_error_chain

__all__ = ["is_resource_exhausted", "is_disk_full", "ladder_enabled",
           "ResourceCounters", "resource_counters", "record_degradation",
           "rss_bytes", "disk_free_bytes", "rss_budget_bytes",
           "disk_min_free_bytes", "pressure_state", "set_watch_path",
           "watch_path", "ResourceWatchdog"]

#: master switch for every degradation rung (default ON)
LADDER_ENV = "TRANSMOGRIFAI_RESOURCE_LADDER"
#: host-RSS budget in bytes (0/unset = no RSS pressure reporting)
RSS_BUDGET_ENV = "TRANSMOGRIFAI_RSS_BUDGET"
#: minimum free disk in bytes before writes report pressure (0/unset =
#: no disk pressure reporting)
DISK_MIN_FREE_ENV = "TRANSMOGRIFAI_DISK_MIN_FREE"
#: after an observed ENOSPC, durable best-effort writes short-circuit
#: (counted) for this long instead of hammering a full disk
ENOSPC_COOLDOWN_ENV = "TRANSMOGRIFAI_ENOSPC_COOLDOWN_S"

#: allocator-OOM message markers. "RESOURCE_EXHAUSTED" is the XLA status
#: prefix observed on real TPU allocator failures; the rest cover the
#: BFC-allocator and PJRT host phrasings that surface without the status
#: prefix. Deliberately DISJOINT from utils.retry._TRANSIENT_MARKERS:
#: an OOM retried at the same shape OOMs again.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory",
                "out of memory", "Failed to allocate")

#: exception type NAMES eligible for message-based OOM classification —
#: same exact-name discipline as utils.retry (RuntimeError subclasses
#: like NotImplementedError must never match)
_OOM_TYPES = ("JaxRuntimeError", "XlaRuntimeError", "RuntimeError")


def ladder_enabled() -> bool:
    """True unless ``TRANSMOGRIFAI_RESOURCE_LADDER=0`` — the one gate
    every degradation rung checks before acting."""
    return os.environ.get(LADDER_ENV, "1") != "0"


def _is_oom_one(err: BaseException) -> bool:
    if isinstance(err, MemoryError):
        return True  # host allocation failure: unambiguous
    if type(err).__name__ not in _OOM_TYPES:
        return False
    msg = str(err)
    return any(m in msg for m in _OOM_MARKERS)


def is_resource_exhausted(err: BaseException) -> bool:
    """True when ``err`` — or any exception in its ``__cause__``/
    ``__context__`` chain (``raise ... from None`` severs it, exactly as
    the transient classifier honors) — is a genuine device/host
    allocation failure: the error class worth retrying ONE RUNG DOWN the
    degradation ladder, never at the same shape."""
    return any(_is_oom_one(e) for e in iter_error_chain(err))


def is_disk_full(err: BaseException) -> bool:
    """True when the chain contains an ``OSError`` whose errno is
    ``ENOSPC`` (or the quota twin ``EDQUOT``) — the write-side analog of
    :func:`is_resource_exhausted`."""
    return any(isinstance(e, OSError)
               and getattr(e, "errno", None) in (errno.ENOSPC,
                                                 getattr(errno, "EDQUOT",
                                                         errno.ENOSPC))
               for e in iter_error_chain(err))


class ResourceCounters:
    """Process-global resource-pressure accounting (the
    ``transmogrifai_resource_*`` Prometheus feed and the
    ``appMetrics.resourceCounters`` block). Thread-safe: serving lanes,
    the sweep, and the spill writer all report concurrently.

    ``enospc`` events additionally arm a cooldown window
    (:meth:`enospc_backoff_active`): once a disk reports full, durable
    best-effort writes short-circuit (counted in ``writes_skipped``)
    until the window expires instead of paying a failing syscall +
    warning per checkpoint on a disk that cannot have recovered."""

    def __init__(self):
        self._lock = threading.Lock()
        self.degradations = 0
        self.oom_events = 0
        self.enospc_events = 0
        self.writes_skipped = 0
        #: site -> rungs taken there (the labeled counter series)
        self.degradations_by_site: dict[str, int] = {}
        self._enospc_until = 0.0

    def reset(self) -> None:
        with self._lock:
            self.degradations = 0
            self.oom_events = 0
            self.enospc_events = 0
            self.writes_skipped = 0
            self.degradations_by_site = {}
            self._enospc_until = 0.0

    def note_degradation(self, site: str) -> None:
        with self._lock:
            self.degradations += 1
            self.degradations_by_site[site] = \
                self.degradations_by_site.get(site, 0) + 1

    def note_oom(self) -> None:
        with self._lock:
            self.oom_events += 1

    def note_enospc(self, cooldown_s: Optional[float] = None,
                    arm_backoff: bool = True) -> None:
        """Count one full-disk event. ``arm_backoff`` additionally opens
        the durable-write skip window — pass False from writers on a
        DIFFERENT filesystem than the checkpoints (e.g. the event
        spill): a full data volume must not silence checkpoint writes
        on a healthy checkpoint disk (those re-detect their own ENOSPC
        and arm from there)."""
        if cooldown_s is None:
            try:
                cooldown_s = float(os.environ.get(ENOSPC_COOLDOWN_ENV,
                                                  "30"))
            except ValueError:
                cooldown_s = 30.0
        with self._lock:
            self.enospc_events += 1
            if arm_backoff:
                self._enospc_until = max(self._enospc_until,
                                         time.monotonic() + cooldown_s)

    def note_write_skipped(self) -> None:
        with self._lock:
            self.writes_skipped += 1

    def enospc_backoff_active(self) -> bool:
        with self._lock:
            return time.monotonic() < self._enospc_until

    def to_json(self) -> dict:
        with self._lock:
            return {"degradations": self.degradations,
                    "oomEvents": self.oom_events,
                    "enospcEvents": self.enospc_events,
                    "writesSkipped": self.writes_skipped,
                    "degradationsBySite": dict(self.degradations_by_site)}


resource_counters = ResourceCounters()


def record_degradation(site: str, rung: str, *,
                       error: Optional[BaseException] = None,
                       **shape) -> None:
    """The ONE bookkeeping call every rung makes: count (per site), emit
    the ``resource.degrade`` flight-recorder event carrying the failing
    shape and the rung chosen, and warn — an operator watching either
    surface sees every step the ladder took. ``shape`` attrs are
    camelCase (they land verbatim in the event JSONL); ``kind``/
    ``trace_id``/``t`` are reserved by ``emit`` itself."""
    reserved = {"kind", "trace_id", "t", "site", "rung", "error"} \
        & set(shape)
    if reserved:
        raise ValueError(
            f"record_degradation: shape attrs {sorted(reserved)} "
            "collide with reserved event fields")
    from transmogrifai_tpu.utils.events import events
    resource_counters.note_degradation(site)
    if error is not None and is_disk_full(error):
        resource_counters.note_enospc()
    elif error is not None:
        resource_counters.note_oom()
    events.emit("resource.degrade", site=site, rung=rung,
                error=(f"{type(error).__name__}: {str(error)[:200]}"
                       if error is not None else None),
                **shape)
    warnings.warn(
        f"resource pressure at {site}: degrading to rung {rung!r}"
        + (f" after {type(error).__name__}: {str(error)[:140]}"
           if error is not None else ""),
        RuntimeWarning)


# -- host watchdogs ----------------------------------------------------------

def rss_bytes() -> int:
    """Current resident set size of this process in bytes (0 when the
    platform exposes neither ``/proc/self/statm`` nor ``getrusage``)."""
    try:
        with open("/proc/self/statm") as fh:
            pages = int(fh.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource as _res
        ru = _res.getrusage(_res.RUSAGE_SELF)
        # ru_maxrss is KiB on Linux (bytes on macOS); a peak, not the
        # current RSS — the degraded-platform fallback, not the contract
        return int(ru.ru_maxrss) * 1024
    except Exception:  # failure-ok: platform without rusage — sampler reports 0
        return 0


def disk_free_bytes(path: Optional[str] = None) -> int:
    """Free bytes on the filesystem holding ``path`` (default: the
    process watch path); -1 when the probe itself fails —
    distinguishable from a genuinely full disk."""
    try:
        import shutil
        return int(shutil.disk_usage(path if path is not None
                                     else watch_path()).free)
    except OSError:
        return -1


#: the directory whose filesystem the default pressure probes watch —
#: daemons point it at their WRITE root (state dir / spill dir): a
#: /healthz or scrape reporting free space on the cwd's roomy rootfs
#: while the data volume the daemon writes is full watches the wrong
#: disk
_watch_path = "."


def set_watch_path(path: str) -> None:
    """Point the default pressure probes (``pressure_state()``, the
    ``transmogrifai_resource_disk_*`` gauges, ``/healthz``) at the
    filesystem the process actually writes."""
    global _watch_path
    _watch_path = path


def watch_path() -> str:
    return _watch_path


def _env_bytes(name: str) -> int:
    v = os.environ.get(name)
    if not v:
        return 0
    try:
        return int(float(v))
    except ValueError:
        warnings.warn(f"{name}={v!r} is not a byte count; ignoring",
                      RuntimeWarning)
        return 0


def rss_budget_bytes() -> int:
    return _env_bytes(RSS_BUDGET_ENV)


def disk_min_free_bytes() -> int:
    return _env_bytes(DISK_MIN_FREE_ENV)


#: HBM fill fraction beyond which device-side caches (the round-14
#: device-frame cache) release their entries
HBM_PRESSURE_FRAC_ENV = "TRANSMOGRIFAI_HBM_PRESSURE_FRAC"


def hbm_pressure_state() -> dict:
    """Device-memory pressure snapshot for HBM-resident caches: bytes in
    use vs the backend's per-device limit (``utils/devicewatch.py``
    census). ``pressured`` is True when usage exceeds the configured
    fraction (``TRANSMOGRIFAI_HBM_PRESSURE_FRAC``, default 0.85) of a
    KNOWN limit — backends that expose no memory stats (CPU) report no
    pressure, and the RSS budget (``pressure_state``) stands in for them."""
    from transmogrifai_tpu.utils.devicewatch import device_memory_census
    census = device_memory_census()  # ONE all-device walk per call
    in_use, limit = census["bytesInUse"], census["bytesLimit"]
    try:
        frac = float(os.environ.get(HBM_PRESSURE_FRAC_ENV, "") or 0.85)
    except ValueError:
        warnings.warn(f"{HBM_PRESSURE_FRAC_ENV} is not a float; using 0.85",
                      RuntimeWarning)
        frac = 0.85
    return {
        "hbmBytesInUse": int(in_use),
        "hbmBytesLimit": int(limit),
        "hbmPressureFrac": frac,
        "pressured": bool(limit > 0 and in_use > frac * limit),
    }


def pressure_state(path: Optional[str] = None) -> dict:
    """One JSON-able snapshot of host resource pressure — the block
    ``/healthz`` folds in and the incident dumps freeze. ``path``
    defaults to the process watch path (``set_watch_path``).
    ``rssPressure`` / ``diskPressure`` are False when no budget is
    configured (pressure is a judgment against a stated budget, not an
    absolute)."""
    rss = rss_bytes()
    free = disk_free_bytes(path)
    rss_budget = rss_budget_bytes()
    min_free = disk_min_free_bytes()
    return {
        "ladderEnabled": ladder_enabled(),
        "rssBytes": rss,
        "rssBudgetBytes": rss_budget,
        "rssPressure": bool(rss_budget and rss > rss_budget),
        "diskFreeBytes": free,
        "diskMinFreeBytes": min_free,
        "diskPressure": bool(min_free and 0 <= free < min_free),
        "enospcBackoffActive": resource_counters.enospc_backoff_active(),
        "counters": resource_counters.to_json(),
    }


class ResourceWatchdog:
    """Background host-pressure sampler for long-running daemons: every
    ``interval_s`` it samples RSS and free disk under ``path`` and, on a
    budget crossing, emits a rate-limited ``resource.pressure``
    flight-recorder event + warning (once per crossing, not per tick).
    Purely observational — the rungs react to real failures, the
    watchdog gives operators the lead time."""

    def __init__(self, path: Optional[str] = None,
                 interval_s: float = 5.0):
        self.path = path  # None = the process watch path
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._was_pressured = False
        #: last sampled values (scrape gauges read these when the
        #: watchdog runs; otherwise the collectors sample inline)
        self.last_sample: Optional[dict] = None

    def start(self) -> "ResourceWatchdog":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="transmogrifai-resource-watchdog",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def tick(self) -> dict:
        """One sample (also the test seam). Returns the pressure
        state."""
        from transmogrifai_tpu.utils.events import events
        state = pressure_state(self.path)
        try:
            # the watchdog's cadence doubles as the HBM-timeline sampler
            # (utils/devicewatch.py): one all-device census per tick,
            # merged into the chrome-trace export as a counter track
            from transmogrifai_tpu.utils.devicewatch import sample_hbm
            state["deviceHbmBytes"] = sample_hbm()
        except Exception:  # failure-ok: the device census is optional telemetry
            state["deviceHbmBytes"] = 0
        self.last_sample = state
        pressured = state["rssPressure"] or state["diskPressure"]
        if pressured and not self._was_pressured:
            events.emit("resource.pressure",
                        rssBytes=state["rssBytes"],
                        rssBudgetBytes=state["rssBudgetBytes"],
                        diskFreeBytes=state["diskFreeBytes"],
                        diskMinFreeBytes=state["diskMinFreeBytes"])
            warnings.warn(
                "host resource pressure: rss "
                f"{state['rssBytes']}/{state['rssBudgetBytes'] or '-'}B, "
                f"disk free {state['diskFreeBytes']}B (min "
                f"{state['diskMinFreeBytes'] or '-'}B)", RuntimeWarning)
        self._was_pressured = pressured
        return state

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — a broken probe must not kill the daemon
                warnings.warn(
                    f"resource watchdog sample failed "
                    f"({type(e).__name__}: {e})", RuntimeWarning)
            self._stop.wait(self.interval_s)
