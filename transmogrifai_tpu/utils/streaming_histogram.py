"""Streaming decision-tree histogram (Ben-Haim & Tom-Tov).

Parity: reference ``utils/src/main/java/.../stats/StreamingHistogram.java``
(builder with spool + closest-centroid merge, interpolated ``sum``) and
``RichStreamingHistogram.scala`` (padded bins + density estimator). Used for
bounded-memory label/score distributions in ModelInsights.

Backend: native C++ (``native/streaming_histogram.cpp``) via ctypes when a
toolchain is present, with a faithful pure-Python fallback. Both share the
exact merge semantics, so shard-built histograms combine deterministically —
this is the monoid the reference reduces over RDD partitions, reduced here
over host shards.
"""

from __future__ import annotations

import bisect
import ctypes
from typing import Iterable, Optional

import numpy as np

__all__ = ["StreamingHistogram", "padded_bins", "density"]

_LIB = None
_LIB_TRIED = False


def _lib():
    global _LIB, _LIB_TRIED
    if not _LIB_TRIED:
        _LIB_TRIED = True
        from transmogrifai_tpu import native
        lib = native.build_and_load("streaming_histogram.cpp", "shist")
        if lib is not None:
            lib.shist_new.restype = ctypes.c_void_p
            lib.shist_new.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int]
            lib.shist_free.argtypes = [ctypes.c_void_p]
            lib.shist_update.argtypes = [ctypes.c_void_p, ctypes.c_double,
                                         ctypes.c_int64]
            lib.shist_update_bulk.argtypes = [
                ctypes.c_void_p,
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                ctypes.c_int64]
            lib.shist_size.restype = ctypes.c_int
            lib.shist_size.argtypes = [ctypes.c_void_p]
            lib.shist_get.argtypes = [
                ctypes.c_void_p,
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")]
            lib.shist_sum.restype = ctypes.c_double
            lib.shist_sum.argtypes = [ctypes.c_void_p, ctypes.c_double]
            lib.shist_merge.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        _LIB = lib
    return _LIB


class _PyHist:
    """Pure-Python twin of the C++ histogram (same flush/merge order)."""

    __slots__ = ("centers", "counts", "spool", "max_bins", "max_spool",
                 "round_seconds")

    def __init__(self, max_bins: int, max_spool: int, round_seconds: int):
        self.centers: list = []
        self.counts: list = []
        self.spool: dict = {}
        self.max_bins = max_bins
        self.max_spool = max_spool
        self.round_seconds = max(1, round_seconds)

    def update(self, p: float, m: int = 1) -> None:
        if self.round_seconds > 1:
            # C-style truncated modulo (sign of dividend), matching the C++
            # backend and the reference's Java %: negatives never round up
            lp = int(p)
            d = lp - (abs(lp) // self.round_seconds) * self.round_seconds * (
                1 if lp >= 0 else -1)
            if d > 0:
                p = float(lp + (self.round_seconds - d))
        self.spool[p] = self.spool.get(p, 0) + m
        if len(self.spool) > self.max_spool:
            self.flush()

    def flush(self) -> None:
        if not self.spool:
            return
        for key in sorted(self.spool):
            i = bisect.bisect_left(self.centers, key)
            if i < len(self.centers) and self.centers[i] == key:
                self.counts[i] += self.spool[key]
            else:
                self.centers.insert(i, key)
                self.counts.insert(i, self.spool[key])
            while len(self.centers) > self.max_bins:
                diffs = np.diff(self.centers)
                j = int(np.argmin(diffs))
                k1, k2 = self.counts[j], self.counts[j + 1]
                c = (self.centers[j] * k1 + self.centers[j + 1] * k2) / (k1 + k2)
                self.centers[j: j + 2] = [c]
                self.counts[j: j + 2] = [k1 + k2]
        self.spool.clear()

    def get(self):
        self.flush()
        return (np.asarray(self.centers, np.float64),
                np.asarray(self.counts, np.int64))

    def sum_below(self, b: float) -> float:
        self.flush()
        centers, counts = self.centers, self.counts
        nxt = bisect.bisect_right(centers, b)
        if nxt >= len(centers):
            return float(sum(counts))
        if nxt == 0:
            return 0.0
        pi = nxt - 1
        ki, knext = counts[pi], counts[nxt]
        weight = (b - centers[pi]) / (centers[nxt] - centers[pi])
        mb = ki + (knext - ki) * weight
        return (ki + mb) * weight / 2.0 + ki / 2.0 + float(sum(counts[:pi]))


class StreamingHistogram:
    """Bounded-bin mergeable histogram.

    >>> h = StreamingHistogram(max_bins=10)
    >>> h.update_all(values); centers, counts = h.bins()
    """

    def __init__(self, max_bins: int = 100, max_spool: int = 500,
                 round_seconds: int = 1):
        if max_bins < 2:
            raise ValueError("max_bins must be >= 2")
        self.max_bins = max_bins
        self.max_spool = max_spool
        self.round_seconds = round_seconds
        lib = _lib()
        if lib is not None:
            self._ptr = lib.shist_new(max_bins, max_spool, round_seconds)
            self._py: Optional[_PyHist] = None
        else:
            self._ptr = None
            self._py = _PyHist(max_bins, max_spool, round_seconds)

    @property
    def is_native(self) -> bool:
        return self._ptr is not None

    def __del__(self):
        if getattr(self, "_ptr", None) is not None and _LIB is not None:
            _LIB.shist_free(self._ptr)
            self._ptr = None

    def update(self, p: float, m: int = 1) -> None:
        p = float(p)
        if not np.isfinite(p):
            return  # NaN/inf keys would corrupt the ordered-bin invariant
        if self._ptr is not None:
            _LIB.shist_update(self._ptr, p, int(m))
        else:
            self._py.update(p, int(m))

    def update_all(self, values: Iterable[float]) -> "StreamingHistogram":
        arr = np.ascontiguousarray(np.asarray(values, np.float64).ravel())
        arr = arr[np.isfinite(arr)]
        if self._ptr is not None:
            _LIB.shist_update_bulk(self._ptr, arr, arr.shape[0])
        else:
            for v in arr:
                self._py.update(float(v))
        return self

    def merge(self, other: "StreamingHistogram") -> "StreamingHistogram":
        """Fold ``other``'s bins into this histogram (monoid combine)."""
        if self._ptr is not None and other._ptr is not None:
            _LIB.shist_merge(self._ptr, other._ptr)
        else:
            centers, counts = other.bins()
            for c, k in zip(centers, counts):
                self.update(float(c), int(k))
        return self

    def bins(self):
        """(centers f64[k], counts i64[k]) sorted by center, post-flush."""
        if self._ptr is not None:
            k = _LIB.shist_size(self._ptr)
            centers = np.empty(k, np.float64)
            counts = np.empty(k, np.int64)
            if k:
                _LIB.shist_get(self._ptr, centers, counts)
            return centers, counts
        return self._py.get()

    def sum_below(self, b: float) -> float:
        """Interpolated count of mass at points <= b."""
        if self._ptr is not None:
            return float(_LIB.shist_sum(self._ptr, float(b)))
        return self._py.sum_below(b)

    def quantiles(self, qs) -> np.ndarray:
        """Approximate quantiles by inverting the Ben-Haim/Tom-Tov
        interpolated CDF (mass at a bin center = half its count plus all
        earlier counts — the sum-procedure's trapezoid model). The ingest
        sketch's answer to np.percentile over the full column."""
        centers, counts = self.bins()
        qs = np.atleast_1d(np.asarray(qs, np.float64))
        if centers.size == 0:
            return np.full(qs.shape, np.nan)
        total = float(counts.sum())
        cum = np.cumsum(counts, dtype=np.float64) - counts / 2.0
        return np.interp(np.clip(qs, 0.0, 1.0) * total, cum, centers)

    def to_json(self) -> dict:
        centers, counts = self.bins()
        return {"maxBins": self.max_bins, "centers": centers.tolist(),
                "counts": counts.tolist()}


def padded_bins(centers: np.ndarray, counts: np.ndarray,
                padding: float = 0.1):
    """Zero-mass guard bins beyond min/max (RichStreamingHistogram.getBins)."""
    if centers.size == 0:
        return centers, counts.astype(np.float64)
    c = np.concatenate([[centers.min() - padding], centers,
                        [centers.max() + padding]])
    k = np.concatenate([[0.0], counts.astype(np.float64), [0.0]])
    return c, k


def density(centers: np.ndarray, counts: np.ndarray, padding: float = 0.1):
    """Piecewise-constant density estimator over padded trapezoid bins
    (RichStreamingHistogram.density)."""
    c, k = padded_bins(centers, counts, padding)
    if c.size < 2:
        return lambda x: 0.0
    seg = (k[:-1] + k[1:]) / 2.0
    total = float(seg.sum())

    def f(x: float) -> float:
        if total == 0.0:
            return 0.0
        mass = float(seg[(x >= c[:-1]) & (x < c[1:])].sum())
        return mass / total

    return f
