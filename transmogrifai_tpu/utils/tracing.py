"""Hierarchical run-wide span tracing — the Dapper-style host timeline.

Where ``utils/profiling.py`` buckets wall/device time into the eight
coarse ``OpStep`` phases (the reference's OpSparkListener granularity),
this module records the *tree*: every DAG stage fit, every fused layer
apply, every sweep family, every reader ingest, checkpoint write and
serving dispatch opens a :func:`span` whose parent is whatever span is
open on the same logical call context. The result answers "which
vectorizer is slow" the way the Spark UI's per-stage drill-down does —
and because each span also wraps a ``jax.profiler.TraceAnnotation`` (host
plane) and device dispatches run under ``jax.named_scope``, a
``jax.profiler`` run trace can be fused with this host tree into one
Perfetto/chrome://tracing JSON (``AppMetrics.export_chrome_trace``).

Design constraints:

- **cheap when idle**: a disabled recorder costs one attribute check per
  instrumented call; an enabled one costs two ``time.time()`` calls and
  one list append per span. No locks on the hot enter path — the parent
  stack is a ``contextvars.ContextVar`` (thread- and task-local), and the
  finished-span list append holds a lock only briefly.
- **thread-safe by construction**: each thread/context gets its own
  parent stack, so serving worker spans interleave with a concurrent
  training run without corrupting either tree. Closed spans land in one
  shared, locked list.
- **bounded**: at most ``max_spans`` closed spans are retained in a ring
  — overflow evicts the OLDEST and counts ``dropped``, so a long-lived
  serving process (which records spans per batch with no consumer until
  someone exports a trace) holds bounded memory and always keeps its
  most recent activity.

The module-level :data:`recorder` is process-global like ``profiler``;
``profiler.reset()`` resets it so a run's span tree covers exactly that
run.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

__all__ = ["Span", "SpanRecorder", "recorder", "span", "device_scope",
           "new_trace_id"]

#: request-scoped trace ids: a process-random prefix + a monotonic
#: counter. Collision-safe across processes (48 random bits) and ~0.2us
#: to mint — cheap enough for every admitted serving request (the
#: uuid module costs ~10x and the hot path pays per request).
_TRACE_PREFIX = None
_trace_ids = itertools.count(1)
_TRACE_RE = None


def new_trace_id() -> str:
    """Mint a request trace id (22 lowercase hex chars). Minted at HTTP
    ingress for requests without an inbound ``X-Trace-Id`` and carried
    through admission -> batch fan-in -> dispatch -> reply (see
    docs/OBSERVABILITY.md "Request-scoped tracing")."""
    global _TRACE_PREFIX
    if _TRACE_PREFIX is None:
        import os
        _TRACE_PREFIX = os.urandom(6).hex()
    return f"{_TRACE_PREFIX}{next(_trace_ids):010x}"


def sanitize_trace_id(raw) -> Optional[str]:
    """An inbound trace header is attacker-controlled text that lands in
    log lines and response headers: accept only modest [A-Za-z0-9._-]
    tokens, else ``None`` (the caller mints a fresh id)."""
    global _TRACE_RE
    if not isinstance(raw, str):
        return None
    if _TRACE_RE is None:
        import re
        _TRACE_RE = re.compile(r"^[A-Za-z0-9._\-]{1,64}$")
    raw = raw.strip()
    return raw if _TRACE_RE.match(raw) else None


@dataclass
class Span:
    """One closed span: a named wall interval with attributes and lineage."""
    span_id: int
    parent_id: Optional[int]
    name: str
    t0: float                   # epoch seconds (aligned with device events)
    t1: float
    thread: str
    attrs: dict = field(default_factory=dict)
    device_s: float = 0.0       # attributed at finalize (device plane)
    peak_hbm_bytes: int = 0     # device peak growth while open (hbm=True)

    @property
    def wall_s(self) -> float:
        return self.t1 - self.t0


#: per-context stack of open span ids — contextvars give each thread (and
#: each asyncio task, if one ever hosts spans) an isolated parent chain
_stack: contextvars.ContextVar[tuple[int, ...]] = contextvars.ContextVar(
    "transmogrifai_span_stack", default=())


@contextlib.contextmanager
def device_scope(name: str):
    """Best-effort ``jax.named_scope`` so ops staged out inside the block
    carry ``name`` in their XLA metadata (and thus in the device plane of
    a profiler trace). A plain no-op when jax is unavailable."""
    try:
        import jax
        cm = jax.named_scope(name)
    except Exception:  # failure-ok: naming device ops is optional polish
        cm = contextlib.nullcontext()
    with cm:
        yield


class SpanRecorder:
    """Thread-safe hierarchical span recorder (see module docstring)."""

    def __init__(self, max_spans: int = 200_000):
        self.max_spans = int(max_spans)
        self.enabled = True
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._spans: collections.deque = collections.deque(
            maxlen=self.max_spans)
        self.dropped = 0

    # -- lifecycle -----------------------------------------------------------
    def reset(self) -> None:
        with self._lock:
            self._spans = collections.deque(maxlen=self.max_spans)
            self._ids = itertools.count(1)
            self.dropped = 0

    def enable(self, on: bool = True) -> None:
        self.enabled = bool(on)

    @property
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    # -- recording -----------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, hbm: bool = False, **attrs):
        """Open a span around the block. Attributes are arbitrary JSON-able
        values (stage uid, class, fold index, ...). Also opens a
        ``jax.profiler.TraceAnnotation`` so the host plane of a device
        trace shows the same interval. ``hbm=True`` additionally samples
        the device peak-memory high-water mark at enter/exit and records
        growth the block caused (used by per-stage spans; off by default —
        the serving hot path shouldn't pay the memory_stats probe)."""
        if not self.enabled:
            yield None
            return
        parent_stack = _stack.get()
        sid = next(self._ids)
        token = _stack.set(parent_stack + (sid,))
        annotation = self._annotation(name)
        peak_before = self._device_peak() if hbm else 0
        t0 = time.time()
        try:
            yield sid
        finally:
            t1 = time.time()
            if annotation is not None:
                try:
                    annotation.__exit__(None, None, None)
                except Exception:  # failure-ok: annotation teardown is best-effort
                    pass
            _stack.reset(token)
            grew = 0
            if hbm:
                peak_after = self._device_peak()
                # the peak is a process-lifetime high-water mark: charge
                # it to this span only when THIS span raised it
                grew = peak_after if peak_after > peak_before else 0
            self._store(Span(
                span_id=sid,
                parent_id=parent_stack[-1] if parent_stack else None,
                name=name, t0=t0, t1=t1,
                thread=threading.current_thread().name, attrs=attrs,
                peak_hbm_bytes=grew))

    @staticmethod
    def _device_peak() -> int:
        # the shared ALL-device census (utils/devicewatch.py): a sharded
        # span's memory lives on every mesh device, not device 0
        from transmogrifai_tpu.utils.devicewatch import device_memory
        return device_memory()[1]

    def add(self, name: str, t0: float, t1: float, *,
            parent_id: Optional[int] = None, thread: Optional[str] = None,
            **attrs) -> None:
        """Record a span retroactively from explicit epoch timestamps —
        for intervals measured elsewhere (e.g. a request's queue wait,
        which only becomes known when the batch picks it up)."""
        if not self.enabled:
            return
        self._store(Span(
            span_id=next(self._ids), parent_id=parent_id, name=name,
            t0=float(t0), t1=float(t1),
            thread=thread or threading.current_thread().name, attrs=attrs))

    def _annotation(self, name: str):
        try:
            import jax
            ann = jax.profiler.TraceAnnotation(name)
            ann.__enter__()
            return ann
        except Exception:  # failure-ok: host-plane annotation is optional
            return None

    def _store(self, s: Span) -> None:
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self.dropped += 1  # ring: the oldest span is evicted
            self._spans.append(s)

    # -- device attribution ---------------------------------------------------
    def attribute_device_events(
            self, events: list[tuple[float, float, str]]) -> float:
        """Bucket device-op events into the innermost containing span
        (latest-started span whose wall window contains the op midpoint —
        the same ownership rule ``AppMetrics.attribute_device_time`` uses
        for phases). Returns total attributed device seconds.

        Sweep-line, not scan-per-event: a real accelerator trace carries
        1e5+ device ops against 1e4+ host spans, and the naive
        O(events x spans) product is minutes of post-run Python for a run
        that took seconds. Events and spans both sort by time; spans
        become "active" as the sweep passes their start and are removed
        for good once their end precedes the current midpoint (a dead
        span can never own a later event), so the whole attribution is
        O((E + S) log (E + S)) from the sorts plus an amortized-linear
        active-list walk."""
        spans = sorted(self.spans, key=lambda s: s.t0)
        mids = sorted((start + dur / 2.0, dur, i)
                      for i, (start, dur, _name) in enumerate(events))
        total = 0.0
        active: list[Span] = []   # t0-ascending; innermost = rightmost live
        si = 0
        for mid, dur, _i in mids:
            while si < len(spans) and spans[si].t0 <= mid:
                active.append(spans[si])
                si += 1
            owner = None
            j = len(active) - 1
            while j >= 0:
                s = active[j]
                if s.t1 < mid:
                    active.pop(j)   # expired: no future mid is smaller
                else:
                    owner = s
                    break
                j -= 1
            if owner is not None:
                owner.device_s += dur
                total += dur
        return total

    # -- aggregation ----------------------------------------------------------
    def aggregate(self, key: str = "name") -> dict[str, dict]:
        """Roll closed spans up by ``key`` (``"name"`` or any attr name).
        Returns ``{group: {"wallSeconds", "deviceSeconds", "count",
        "maxWallSeconds"}}`` — wall here is INCLUSIVE (each span's own
        window), the right units for a top-K slowest-stages table."""
        out: dict[str, dict] = {}
        for s in self.spans:
            group = s.name if key == "name" else s.attrs.get(key)
            if group is None:
                continue
            g = out.setdefault(str(group), {
                "wallSeconds": 0.0, "deviceSeconds": 0.0, "count": 0,
                "maxWallSeconds": 0.0})
            g["wallSeconds"] += s.wall_s
            g["deviceSeconds"] += s.device_s
            g["count"] += 1
            g["maxWallSeconds"] = max(g["maxWallSeconds"], s.wall_s)
        return out

    def stage_table(self) -> dict[str, dict]:
        """Per-DAG-stage rollup: spans carrying a ``stage_uid`` attr,
        keyed ``"<operation> (<uid>)"`` so two instances of the same
        vectorizer stay distinguishable.

        Wall/count/HBM come only from spans with no ANCESTOR span carrying
        the same uid — the selector's ``selector.sweep``/``selector.refit``
        nest inside its ``stage.fit`` span, and summing parent and children
        would double-count the stage's wall. Device seconds sum over every
        span of the uid: each device event attributes to exactly one
        (innermost) span, so nesting cannot double-count them."""
        by_id = {s.span_id: s for s in self.spans}

        def has_same_uid_ancestor(s: Span, uid) -> bool:
            pid = s.parent_id
            while pid is not None:
                parent = by_id.get(pid)
                if parent is None:
                    return False
                if parent.attrs.get("stage_uid") == uid:
                    return True
                pid = parent.parent_id
            return False

        out: dict[str, dict] = {}
        for s in by_id.values():
            uid = s.attrs.get("stage_uid")
            if uid is None:
                continue
            label = f"{s.attrs.get('stage_cls', s.name)} ({uid})"
            g = out.setdefault(label, {
                "wallSeconds": 0.0, "deviceSeconds": 0.0, "count": 0,
                "peakHbmBytes": 0, "phase": s.attrs.get("phase", "")})
            g["deviceSeconds"] += s.device_s
            if has_same_uid_ancestor(s, uid):
                continue
            g["wallSeconds"] += s.wall_s
            g["count"] += 1
            g["peakHbmBytes"] = max(g["peakHbmBytes"], s.peak_hbm_bytes)
            if s.attrs.get("phase"):
                g["phase"] = s.attrs["phase"]
        return out

    # -- export ----------------------------------------------------------------
    def chrome_trace_events(self, pid: int = 1) -> list[dict]:
        """Closed spans as chrome://tracing complete ('X') events.
        Timestamps are epoch microseconds; one tid per recording thread."""
        tids: dict[str, int] = {}
        events: list[dict] = []
        for s in self.spans:
            tid = tids.setdefault(s.thread, len(tids) + 1)
            args = {k: v for k, v in s.attrs.items()}
            if s.device_s:
                args["device_s"] = round(s.device_s, 6)
            events.append({
                "name": s.name, "ph": "X", "pid": pid, "tid": tid,
                "ts": s.t0 * 1e6, "dur": max(s.t1 - s.t0, 0.0) * 1e6,
                "args": args})
        for thread, tid in tids.items():
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": thread}})
        return events


#: process-global recorder; ``profiler.reset()`` resets it per run
recorder = SpanRecorder()
span = recorder.span
