"""Platform selection helper.

Site accelerator plugins (axon) re-register the JAX backend at interpreter
start and OVERRIDE the ``JAX_PLATFORMS`` environment variable; a script run
with ``JAX_PLATFORMS=cpu`` that relies on the env var alone will still try
to initialize the plugin's TPU backend — and hang if its tunnel is down.
Every entry-point script (examples, generated run.py, benches) calls
``respect_jax_platforms()`` before any JAX API use; tests do the same dance
inline in ``tests/conftest.py`` (which must not import the package first).
"""

from __future__ import annotations

import os
import sys

__all__ = ["respect_jax_platforms"]


def respect_jax_platforms() -> None:
    """Re-assert ``JAX_PLATFORMS`` at jax-config level (no-op when unset).
    Must run before the first backend initialization; if the backend is
    already up the failure is LOUD — proceeding silently would hand the run
    to the possibly-hung platform this helper exists to avoid."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax
    try:
        jax.config.update("jax_platforms", want)
    except RuntimeError as e:
        print(f"WARNING: could not apply JAX_PLATFORMS={want!r} "
              f"({e}); the backend was already initialized and this run "
              "may target a different platform", file=sys.stderr)
