"""Precision-ladder primitives for compiled serving and explain programs.

The ladder has three rungs, widest first::

    f32  ──gate──▶  bf16  ──gate──▶  int8

* **f32** is the master format: fitted parameters are always stored f32
  and the default serving path is byte-identical to the pre-ladder code.
* **bf16** is an *activation* variant: inside the traced program the
  input environment and the per-stage float parameters are cast to
  bfloat16, matmuls/accumulations run in bf16, and every float output
  leaf is cast back to f32 before leaving the program. Parameters on the
  host stay f32 (master weights) — the cast happens in-trace.
* **int8** keeps the bf16 activation scheme and additionally swaps
  stage weights for :class:`QuantizedTensor` (int8 payload +
  per-output-channel f32 scale) where a stage opts in via
  ``quantize_device_params`` — linear/GLM/MLP/NB matmul weights, and
  exact int16 index/threshold arrays for tree ensembles (integer
  comparisons are bitwise-safe, so the tree *structure* path is exact).

Advancing a rung is either a gated **promotion** (shadow-scored against
the live f32 lane, ``score_diff`` tolerance as the acceptance test) or a
pressure-forced **demotion** (the resource-ladder rung above
bucket-shedding). Both move toward fewer bits; only the gate proves
parity.

Leaf wrappers (:class:`QuantizedTensor`, :class:`ExactTensor`) are
registered pytrees so they flow through ``jax.jit`` argument flattening
unchanged; :func:`materialize_tree` turns them back into plain arrays
inside the trace.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

#: Ladder rungs in order, widest (master) first.
PRECISIONS = ("f32", "bf16", "int8")

#: Logical bits per rung — exported as the per-lane precision gauge.
PRECISION_BITS = {"f32": 32, "bf16": 16, "int8": 8}

#: Resident-bytes factor vs f32 used by ``ProgramCache`` HBM accounting
#: (``layer_entry_bytes``): bf16 halves IO/param bytes, int8 quarters
#: the dominant weight payload.
PRECISION_BYTE_FACTOR = {"f32": 1.0, "bf16": 0.5, "int8": 0.25}

#: Accepted spellings for the precision knobs (CLI / config). ``auto``
#: means "the full ladder, promote stepwise as far as the gate allows".
PRECISION_CHOICES = ("auto",) + PRECISIONS


def normalize_precision(precision: Optional[str]) -> str:
    """Validate and canonicalize a concrete rung name (not ``auto``)."""
    p = "f32" if precision is None else str(precision).lower()
    if p not in PRECISIONS:
        raise ValueError(
            f"unknown precision {precision!r}: expected one of {PRECISIONS}")
    return p


def ladder_for(target: Optional[str]) -> tuple[str, ...]:
    """The rung sequence a server configured with ``target`` walks,
    starting at the f32 master rung. ``auto`` walks the whole ladder."""
    t = "f32" if target is None else str(target).lower()
    if t == "auto":
        return PRECISIONS
    p = normalize_precision(t)
    return PRECISIONS[:PRECISIONS.index(p) + 1]


def compute_dtype(precision: str):
    """In-trace compute dtype for a rung — ``None`` for f32 (the builder
    must not touch anything on the master rung)."""
    p = normalize_precision(precision)
    if p == "f32":
        return None
    return jnp.bfloat16


# ---------------------------------------------------------------------------
# Leaf wrappers
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """int8 weight payload + per-output-channel f32 scale.

    ``q`` holds round-to-nearest int8 codes, ``scale`` the per-last-axis
    f32 scales (a scalar for 1-D weights). ``materialize(dtype)``
    dequantizes in-trace: ``q * scale`` cast to the rung's compute
    dtype, so stage ``device_apply`` methods stay unchanged.
    """

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def materialize(self, dtype=jnp.float32):
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)

    @property
    def nbytes(self) -> int:
        return int(getattr(self.q, "nbytes", 0)) + int(
            getattr(self.scale, "nbytes", 0))

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"QuantizedTensor(q={getattr(self.q, 'shape', ())}, " \
               f"scale={getattr(self.scale, 'shape', ())})"


@jax.tree_util.register_pytree_node_class
class ExactTensor:
    """A parameter leaf pinned to its stored dtype at EVERY rung.

    Tree-ensemble bin edges ride in one of these: binning must compare
    f32 inputs against f32 edges bit-exactly or the int-threshold claim
    of the int8 tree path evaporates. ``cast_float_leaves`` skips these;
    ``materialize`` unwraps to the untouched array.
    """

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value

    def tree_flatten(self):
        return (self.value,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0])

    def materialize(self, dtype=None):
        return self.value

    @property
    def nbytes(self) -> int:
        return int(getattr(self.value, "nbytes", 0))


def _is_wrapper(x: Any) -> bool:
    return isinstance(x, (QuantizedTensor, ExactTensor))


# ---------------------------------------------------------------------------
# Tree helpers (used INSIDE traced programs)
# ---------------------------------------------------------------------------

def _is_float_leaf(x: Any) -> bool:
    dt = getattr(x, "dtype", None)
    if dt is None:
        return False
    try:
        return bool(jnp.issubdtype(dt, jnp.floating))
    except TypeError:  # pragma: no cover - exotic non-array leaf
        return False


def cast_float_leaves(tree: Any, dtype) -> Any:
    """Cast every floating leaf of ``tree`` to ``dtype``; integer, bool
    and wrapped (:class:`QuantizedTensor`/:class:`ExactTensor`) leaves
    pass through untouched."""
    def cast(x):
        if _is_wrapper(x) or not _is_float_leaf(x):
            return x
        return jnp.asarray(x, dtype)
    return jax.tree_util.tree_map(cast, tree, is_leaf=_is_wrapper)


def materialize_tree(tree: Any, dtype) -> Any:
    """Unwrap precision leaf wrappers: quantized leaves dequantize to
    ``dtype``, exact leaves keep their stored dtype, everything else is
    returned as-is."""
    def mat(x):
        if _is_wrapper(x):
            return x.materialize(dtype)
        return x
    return jax.tree_util.tree_map(mat, tree, is_leaf=_is_wrapper)


def params_nbytes(tree: Any) -> int:
    """Resident bytes of a (possibly wrapped) parameter tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree, is_leaf=_is_wrapper):
        total += int(getattr(leaf, "nbytes", 0))
    return total


# ---------------------------------------------------------------------------
# Host-side quantization (fit once per (stage, rung), memoized by callers)
# ---------------------------------------------------------------------------

def quantize_weights(w) -> QuantizedTensor:
    """Symmetric round-to-nearest int8 quantization with per-output-channel
    (last axis) f32 scales; 1-D weights get a single scalar scale.

    The scale is ``amax / 127`` with a zero-column guard, so all-zero
    channels quantize to exact zeros instead of NaN.
    """
    w = np.asarray(w, dtype=np.float32)
    if w.ndim >= 2:
        amax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)))
    else:
        amax = np.max(np.abs(w)) if w.size else np.float32(0.0)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return QuantizedTensor(jnp.asarray(q), jnp.asarray(scale, jnp.float32))


def fits_int16(arr) -> bool:
    """True when an integer array's values survive an int16 cast exactly."""
    a = np.asarray(arr)
    if a.size == 0:
        return True
    return bool(a.min() >= np.iinfo(np.int16).min
                and a.max() <= np.iinfo(np.int16).max)
