"""Pretty ASCII tables (reference ``utils/.../Table.scala``)."""

from __future__ import annotations

from typing import Optional, Sequence

__all__ = ["Table"]


class Table:
    def __init__(self, headers: Sequence[str], rows: Sequence[Sequence],
                 title: Optional[str] = None):
        self.headers = [str(h) for h in headers]
        self.rows = [[str(c) for c in r] for r in rows]
        self.title = title

    def __str__(self) -> str:
        widths = [len(h) for h in self.headers]
        for r in self.rows:
            for i, c in enumerate(r):
                widths[i] = max(widths[i], len(c))
        sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"

        def fmt(cells):
            return "| " + " | ".join(
                c.ljust(w) for c, w in zip(cells, widths)) + " |"

        out = []
        if self.title:
            total = len(sep)
            out.append("+" + "-" * (total - 2) + "+")
            out.append("|" + self.title.center(total - 2) + "|")
        out.append(sep)
        out.append(fmt(self.headers))
        out.append(sep)
        for r in self.rows:
            out.append(fmt(r))
        out.append(sep)
        return "\n".join(out)
