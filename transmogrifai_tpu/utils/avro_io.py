"""Pure-Python Avro Object Container File codec (read + write).

Parity: reference ``utils/src/main/scala/com/salesforce/op/utils/io/avro/
AvroInOut.scala`` (read/write Avro datasets) and ``RichDataset.saveAvro``.
The environment ships no avro library, so this implements the Avro 1.x
binary spec directly: zigzag-varint longs, little-endian float/double,
length-prefixed bytes/strings, records/arrays/maps/unions/enums/fixed, and
container files with ``null`` or ``deflate`` codecs.

Supports the schema subset TransmogrifAI uses (GenericRecord rows of
primitive/union[null,...] fields plus nested arrays/maps/records), which is
also everything our ``HostFrame`` ingest needs.
"""

from __future__ import annotations

import io
import json
import struct
import zlib
from typing import Any, Iterable, Iterator, Optional

__all__ = ["read_avro", "iter_avro", "write_avro", "avro_schema_of_records"]

_MAGIC = b"Obj\x01"


# ---------------------------------------------------------------------------
# Binary primitives
# ---------------------------------------------------------------------------

def _read_long(buf: io.BufferedIOBase) -> int:
    shift = 0
    acc = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("unexpected EOF in varint")
        byte = b[0]
        acc |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)  # zigzag decode


def _write_long(out: io.BufferedIOBase, n: int) -> None:
    n = (n << 1) ^ (n >> 63)  # zigzag encode
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes((b | 0x80,)))
        else:
            out.write(bytes((b,)))
            break


def _read_bytes(buf: io.BufferedIOBase) -> bytes:
    n = _read_long(buf)
    data = buf.read(n)
    if len(data) != n:
        raise EOFError("unexpected EOF in bytes")
    return data


def _write_bytes(out: io.BufferedIOBase, data: bytes) -> None:
    _write_long(out, len(data))
    out.write(data)


# ---------------------------------------------------------------------------
# Schema-driven datum codec
# ---------------------------------------------------------------------------

def _norm_schema(schema: Any, named: dict[str, Any]) -> Any:
    """Resolve named-type references and normalize {"type": "x"} wrappers."""
    if isinstance(schema, str):
        return named.get(schema, schema)
    if isinstance(schema, dict):
        t = schema.get("type")
        if t in ("record", "enum", "fixed") and "name" in schema:
            named[schema["name"]] = schema
            ns = schema.get("namespace")
            if ns:
                named[f"{ns}.{schema['name']}"] = schema
        return schema
    return schema


def _read_datum(buf: io.BufferedIOBase, schema: Any, named: dict[str, Any]) -> Any:
    schema = _norm_schema(schema, named)
    if isinstance(schema, list):  # union
        idx = _read_long(buf)
        return _read_datum(buf, schema[idx], named)
    t = schema if isinstance(schema, str) else schema["type"]
    if t == "null":
        return None
    if t == "boolean":
        b = buf.read(1)
        return b[0] != 0
    if t in ("int", "long"):
        return _read_long(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t == "bytes":
        return _read_bytes(buf)
    if t == "string":
        return _read_bytes(buf).decode("utf-8")
    if t == "record":
        return {f["name"]: _read_datum(buf, f["type"], named)
                for f in schema["fields"]}
    if t == "enum":
        return schema["symbols"][_read_long(buf)]
    if t == "fixed":
        return buf.read(schema["size"])
    if t == "array":
        out = []
        while True:
            n = _read_long(buf)
            if n == 0:
                break
            if n < 0:
                _read_long(buf)  # block byte size, unused
                n = -n
            for _ in range(n):
                out.append(_read_datum(buf, schema["items"], named))
        return out
    if t == "map":
        out = {}
        while True:
            n = _read_long(buf)
            if n == 0:
                break
            if n < 0:
                _read_long(buf)
                n = -n
            for _ in range(n):
                k = _read_bytes(buf).decode("utf-8")
                out[k] = _read_datum(buf, schema["values"], named)
        return out
    raise ValueError(f"unsupported Avro type: {t!r}")


def _union_branch(schema: list, value: Any) -> int:
    """Pick the union branch for a python value (null-vs-one-type unions and
    simple primitive discrimination — the shapes TransmogrifAI writes)."""
    for i, s in enumerate(schema):
        t = s if isinstance(s, str) else s.get("type")
        if value is None and t == "null":
            return i
        if value is not None and t != "null":
            if isinstance(value, bool) and t == "boolean":
                return i
            if isinstance(value, bool):
                continue
            if isinstance(value, int) and t in ("int", "long"):
                return i
            if isinstance(value, float) and t in ("float", "double"):
                return i
            if isinstance(value, str) and t in ("string", "enum"):
                return i
            if isinstance(value, bytes) and t in ("bytes", "fixed"):
                return i
            if isinstance(value, dict) and t in ("record", "map"):
                return i
            if isinstance(value, (list, tuple)) and t == "array":
                return i
    # fallback: first non-null branch for non-null values
    for i, s in enumerate(schema):
        t = s if isinstance(s, str) else s.get("type")
        if (t == "null") == (value is None):
            return i
    raise ValueError(f"no union branch for {value!r} in {schema}")


def _write_datum(out: io.BufferedIOBase, schema: Any, value: Any,
                 named: dict[str, Any]) -> None:
    schema = _norm_schema(schema, named)
    if isinstance(schema, list):
        idx = _union_branch(schema, value)
        _write_long(out, idx)
        _write_datum(out, schema[idx], value, named)
        return
    t = schema if isinstance(schema, str) else schema["type"]
    if t == "null":
        return
    if t == "boolean":
        out.write(b"\x01" if value else b"\x00")
    elif t in ("int", "long"):
        _write_long(out, int(value))
    elif t == "float":
        out.write(struct.pack("<f", float(value)))
    elif t == "double":
        out.write(struct.pack("<d", float(value)))
    elif t == "bytes":
        _write_bytes(out, bytes(value))
    elif t == "string":
        _write_bytes(out, str(value).encode("utf-8"))
    elif t == "record":
        for f in schema["fields"]:
            _write_datum(out, f["type"], value.get(f["name"]), named)
    elif t == "enum":
        _write_long(out, schema["symbols"].index(value))
    elif t == "fixed":
        out.write(bytes(value))
    elif t == "array":
        if value:
            _write_long(out, len(value))
            for v in value:
                _write_datum(out, schema["items"], v, named)
        _write_long(out, 0)
    elif t == "map":
        if value:
            _write_long(out, len(value))
            for k, v in value.items():
                _write_bytes(out, str(k).encode("utf-8"))
                _write_datum(out, schema["values"], v, named)
        _write_long(out, 0)
    else:
        raise ValueError(f"unsupported Avro type: {t!r}")


# ---------------------------------------------------------------------------
# Snappy block format (no python-snappy in the image; the format is simple:
# varint uncompressed length + literal/copy tagged elements). Avro frames
# snappy blocks with a trailing big-endian CRC32 of the uncompressed data.
# ---------------------------------------------------------------------------

def _snappy_decompress(data: bytes) -> bytes:
    pos = 0
    # preamble: little-endian varint of uncompressed length
    ulen = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        ulen |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            n = tag >> 2
            if n >= 60:
                extra = n - 59
                n = int.from_bytes(data[pos:pos + extra], "little")
                pos += extra
            n += 1
            out += data[pos:pos + n]
            pos += n
        else:  # copy
            if kind == 1:
                n = ((tag >> 2) & 0x7) + 4
                off = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif kind == 2:
                n = (tag >> 2) + 1
                off = int.from_bytes(data[pos:pos + 2], "little")
                pos += 2
            else:
                n = (tag >> 2) + 1
                off = int.from_bytes(data[pos:pos + 4], "little")
                pos += 4
            if off == 0:
                raise ValueError("snappy: zero copy offset")
            start = len(out) - off
            for i in range(n):  # may self-overlap; byte-wise per spec
                out.append(out[start + i])
    if len(out) != ulen:
        raise ValueError(f"snappy: length mismatch {len(out)} != {ulen}")
    return bytes(out)


def _snappy_compress(data: bytes) -> bytes:
    """All-literal snappy encoding (valid per spec, no matching)."""
    out = bytearray()
    n = len(data)
    v = n
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | 0x80 if v else b)
        if not v:
            break
    pos = 0
    while pos < n:
        chunk = min(n - pos, 0x10000)  # literal length fits in 2 extra bytes
        if chunk <= 60:
            out.append((chunk - 1) << 2)
        else:
            out.append(61 << 2)  # literal with 2-byte little-endian length
            out += (chunk - 1).to_bytes(2, "little")
        out += data[pos:pos + chunk]
        pos += chunk
    return bytes(out)


# ---------------------------------------------------------------------------
# Container files
# ---------------------------------------------------------------------------

def iter_avro(path: str) -> Iterator[dict]:
    """Stream records from an Avro container file."""
    with open(path, "rb") as f:
        if f.read(4) != _MAGIC:
            raise ValueError(f"{path}: not an Avro container file")
        meta: dict[str, bytes] = {}
        while True:
            n = _read_long(f)
            if n == 0:
                break
            if n < 0:
                _read_long(f)
                n = -n
            for _ in range(n):
                k = _read_bytes(f).decode("utf-8")
                meta[k] = _read_bytes(f)
        schema = json.loads(meta["avro.schema"].decode("utf-8"))
        codec = meta.get("avro.codec", b"null").decode("utf-8")
        if codec not in ("null", "deflate", "snappy"):
            raise ValueError(f"unsupported Avro codec {codec!r}")
        sync = f.read(16)
        named: dict[str, Any] = {}
        while True:
            first = f.read(1)
            if not first:
                return
            f.seek(-1, 1)
            count = _read_long(f)
            size = _read_long(f)
            block = f.read(size)
            if codec == "deflate":
                block = zlib.decompress(block, -15)
            elif codec == "snappy":
                body, crc = block[:-4], block[-4:]
                block = _snappy_decompress(body)
                if zlib.crc32(block) != int.from_bytes(crc, "big"):
                    raise ValueError(f"{path}: snappy block CRC mismatch")
            buf = io.BytesIO(block)
            for _ in range(count):
                yield _read_datum(buf, schema, named)
            if f.read(16) != sync:
                raise ValueError(f"{path}: sync marker mismatch")


def read_avro_schema(path: str) -> dict:
    """Read only the schema from an Avro container file's header."""
    with open(path, "rb") as f:
        if f.read(4) != _MAGIC:
            raise ValueError(f"{path}: not an Avro container file")
        while True:
            n = _read_long(f)
            if n == 0:
                break
            if n < 0:
                _read_long(f)
                n = -n
            for _ in range(n):
                k = _read_bytes(f).decode("utf-8")
                v = _read_bytes(f)
                if k == "avro.schema":
                    return json.loads(v.decode("utf-8"))
    raise ValueError(f"{path}: no avro.schema in header")


def read_avro(path: str) -> tuple[dict, list[dict]]:
    """Read an Avro container file -> (schema, records)."""
    with open(path, "rb") as f:
        if f.read(4) != _MAGIC:
            raise ValueError(f"{path}: not an Avro container file")
        meta: dict[str, bytes] = {}
        while True:
            n = _read_long(f)
            if n == 0:
                break
            if n < 0:
                _read_long(f)
                n = -n
            for _ in range(n):
                k = _read_bytes(f).decode("utf-8")
                meta[k] = _read_bytes(f)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    return schema, list(iter_avro(path))


def write_avro(path: str, schema: dict, records: Iterable[dict],
               codec: str = "deflate", sync_interval: int = 4000) -> None:
    """Write records to an Avro container file."""
    if codec not in ("null", "deflate", "snappy"):
        raise ValueError(f"unsupported Avro codec {codec!r}")
    # deterministic sync marker from the schema (no RNG needed)
    sync = zlib.crc32(json.dumps(schema, sort_keys=True).encode("utf-8"))
    sync_marker = struct.pack("<IIII", sync, ~sync & 0xFFFFFFFF,
                              sync ^ 0xA5A5A5A5, sync ^ 0x5A5A5A5A)
    named: dict[str, Any] = {}
    with open(path, "wb") as f:
        f.write(_MAGIC)
        meta = {"avro.schema": json.dumps(schema).encode("utf-8"),
                "avro.codec": codec.encode("utf-8")}
        _write_long(f, len(meta))
        for k, v in meta.items():
            _write_bytes(f, k.encode("utf-8"))
            _write_bytes(f, v)
        _write_long(f, 0)
        f.write(sync_marker)

        block = io.BytesIO()
        count = 0

        def flush():
            nonlocal count
            if count == 0:
                return
            data = block.getvalue()
            if codec == "deflate":
                c = zlib.compressobj(wbits=-15)
                data = c.compress(data) + c.flush()
            elif codec == "snappy":
                data = (_snappy_compress(data)
                        + zlib.crc32(data).to_bytes(4, "big"))
            _write_long(f, count)
            _write_long(f, len(data))
            f.write(data)
            f.write(sync_marker)
            block.seek(0)
            block.truncate()
            count = 0

        for rec in records:
            _write_datum(block, schema, rec, named)
            count += 1
            if count >= sync_interval:
                flush()
        flush()


def avro_schema_of_records(records: list[dict], name: str = "Row",
                           namespace: str = "transmogrifai_tpu") -> dict:
    """Infer a union[null, T] record schema from python dict records
    (the shape ``saveAvro`` needs for score/frame output). Handles scalars,
    numeric/string maps and arrays; anything else stringifies."""
    fields: dict[str, set] = {}
    for rec in records:
        for k, v in rec.items():
            fields.setdefault(k, set()).add(json.dumps(_avro_type_of(v)))
    out_fields = []
    for k, types in fields.items():
        types.discard('"null"')
        loaded = [json.loads(t) for t in sorted(types)]
        if not loaded:
            t: Any = ["null", "string"]
        elif len(loaded) == 1:
            t = ["null", loaded[0]]
        elif all(isinstance(x, str) for x in loaded) and \
                set(loaded) <= {"int", "long", "double"}:
            t = ["null", "double"]
        elif all(isinstance(x, dict) and x.get("type") == "array"
                 for x in loaded):
            items = {json.dumps(x["items"]) for x in loaded}
            merged = ("double" if items <= {'"double"', '"long"'}
                      else "string")
            t = ["null", {"type": "array", "items": merged}]
        elif all(isinstance(x, dict) and x.get("type") == "map"
                 for x in loaded):
            vals = {json.dumps(x["values"]) for x in loaded}
            merged_v = (["null", "double"]
                        if vals <= {'["null", "double"]'} else
                        ["null", "string"])
            t = ["null", {"type": "map", "values": merged_v}]
        else:
            t = ["null", "string"]
        out_fields.append({"name": k, "type": t})
    return {"type": "record", "name": name, "namespace": namespace,
            "fields": out_fields}


def _avro_type_of(v: Any) -> Any:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, int):
        return "long"
    if isinstance(v, float):
        return "double"
    if isinstance(v, str):
        return "string"
    if isinstance(v, dict):
        vals = set()
        for x in v.values():
            t = _avro_type_of(x)
            vals.add(t if isinstance(t, str) else "string")
        if vals <= {"long", "double", "null"}:
            return {"type": "map", "values": ["null", "double"]}
        if vals <= {"boolean", "null"}:
            return {"type": "map", "values": ["null", "boolean"]}
        return {"type": "map", "values": ["null", "string"]}
    if isinstance(v, (list, tuple)) or type(v).__name__ == "ndarray":
        items = set()
        for x in v:
            t = _avro_type_of(x)
            items.add(t if isinstance(t, str) else "string")
        if items <= {"long", "double", "null"}:
            return {"type": "array", "items": "double"}
        return {"type": "array", "items": "string"}
    return "string"


def plain_value(v: Any) -> Any:
    """Coerce numpy scalars/arrays/sets into Avro-encodable python values."""
    tname = type(v).__name__
    if tname in ("float32", "float64"):
        return float(v)
    if tname in ("int32", "int64", "bool_"):
        return bool(v) if tname == "bool_" else int(v)
    if tname == "ndarray":
        return [plain_value(x) for x in v.tolist()]
    if isinstance(v, (set, frozenset, tuple)):
        return [plain_value(x) for x in v]
    if isinstance(v, list):
        return [plain_value(x) for x in v]
    if isinstance(v, dict):
        return {str(k): plain_value(x) for k, x in v.items()}
    return v
