"""Build/version info embedded in summaries and generated projects.

Parity: reference ``utils/.../version/VersionInfo.scala`` — surfaces the
framework version plus build provenance (git commit/branch when available)
so model artifacts record what produced them.
"""

from __future__ import annotations

import functools
import os
import subprocess

__all__ = ["VersionInfo"]


@functools.lru_cache(maxsize=1)
def _git_info() -> dict:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    out = {}
    for key, args in (("commit", ["rev-parse", "HEAD"]),
                      ("branch", ["rev-parse", "--abbrev-ref", "HEAD"])):
        try:
            out[key] = subprocess.run(
                ["git", "-C", repo] + args, capture_output=True, text=True,
                timeout=5, check=True).stdout.strip()
        except (OSError, subprocess.SubprocessError):
            out[key] = None
    return out


class VersionInfo:
    @staticmethod
    def to_json() -> dict:
        from transmogrifai_tpu import __version__
        import jax

        git = _git_info()
        return {
            "version": __version__,
            "gitCommit": git["commit"],
            "gitBranch": git["branch"],
            "jaxVersion": jax.__version__,
            "backend": _backend_or_none(),
        }


def _backend_or_none():
    try:
        import jax
        return jax.default_backend()
    except Exception:  # failure-ok: backend probe; None when jax absent
        return None
