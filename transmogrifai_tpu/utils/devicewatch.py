"""Device-execution observatory: dispatch watchdog, hang autopsy, and
compile/HBM telemetry.

PR 9 made *requests* legible (trace ids, flight recorder, SLO burn
rates); device execution stayed a black box — BENCH_r05's accelerator
probes each died with one stderr line (``hung > 240s``) and nothing to
say WHICH dispatch stalled, what was compiling, or which buffers held
HBM. This module is the accelerator-side analog of the flight recorder,
three instruments over one shared device census:

- :class:`DispatchWatchdog` — every blocking device wait (the one-sync
  sweep settle, collectives, serving batch dispatch, checkpoint
  restores) arms a deadline via :meth:`~DispatchWatchdog.guard`. A wait
  that outlives its deadline fires ONE **autopsy**: all Python thread
  stacks (faulthandler-style), the :data:`dispatch_ledger` inventory of
  in-flight device work, a live-buffer + per-device ``memory_stats``
  HBM census, compile-in-progress state, and the recent flight-recorder
  tail — emitted as a ``device.stall`` event and frozen via
  ``events.dump_incident`` when an incident dir is configured.
  Recoverable waits keep waiting (the guard never raises); expired
  *deadlines* stay the caller's contract (``run_with_deadline`` still
  raises ``CollectiveTimeoutError`` — now with an autopsy attached).
- :class:`CompileTelemetry` — every XLA backend compile (observed via
  the ``jax.monitoring`` duration listener) records wall attributed to
  the active :meth:`~CompileTelemetry.building` site as a
  ``compile.program`` span + ``transmogrifai_compile_*`` Prometheus
  series, with a slow-compile threshold event — a compile storm or a
  pathological HLO is visible *before* it looks like a hang.
  :func:`analyze_program` adds HLO size + cost-analysis FLOPs/bytes at
  cold seams (serving warmup) where a program handle exists.
- an **HBM timeline** — low-rate all-device census samples
  (:func:`sample_hbm`, driven by ``ResourceWatchdog.tick`` and the
  watchdog's own poll while waits are armed) merged into the
  chrome-trace export as a counter track.

The census (:func:`device_memory_census`) sums across EVERY local
device — the one shared probe behind the per-phase and per-span
peak-HBM samplers and the sweep's HBM budget, replacing three ad-hoc
``jax.local_devices()[0]`` shortcuts (a sharded run's memory lives on
all mesh devices, not device 0).

Cost discipline: a guard is two dict ops under a lock per blocking wait
(batch/settle granularity, never per row); the monitor thread polls
only while waits are armed and exits when idle; the census and
``jax.live_arrays()`` walk run only inside an autopsy — each behind its
own small deadline, because an autopsy probe that blocks on the very
hang it is diagnosing would never report. Gated by
``TRANSMOGRIFAI_DEVICEWATCH`` (default on);
``TRANSMOGRIFAI_STALL_TIMEOUT_S`` sets the default stall deadline and
``TRANSMOGRIFAI_DEVICEWATCH_DIR`` the incident directory (unset = emit
events only, write nothing).
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import sys
import threading
import time
import traceback
import warnings
from collections import deque
from typing import Any, Callable, Optional

__all__ = ["device_memory_census", "device_memory_census_bounded",
           "device_memory", "device_memory_bounded", "device_bytes_limit",
           "live_buffer_census", "thread_stacks", "DispatchLedger",
           "dispatch_ledger", "CompileTelemetry", "compile_telemetry",
           "analyze_program", "DispatchWatchdog", "watchdog", "guard",
           "configure", "stall_autopsy", "build_autopsy", "sample_hbm",
           "hbm_timeline", "reset_run"]

#: master switch for the watchdog (default ON; guards become no-ops off)
ENABLE_ENV = "TRANSMOGRIFAI_DEVICEWATCH"
#: default stall deadline for guarded waits (seconds; <= 0 disables;
#: default 600 — see DispatchWatchdog.default_timeout_s)
STALL_TIMEOUT_ENV = "TRANSMOGRIFAI_STALL_TIMEOUT_S"
#: incident directory for autopsy dumps (unset = events only, no files)
INCIDENT_DIR_ENV = "TRANSMOGRIFAI_DEVICEWATCH_DIR"
#: backend compiles slower than this emit a ``compile.slow`` event
SLOW_COMPILE_ENV = "TRANSMOGRIFAI_SLOW_COMPILE_S"

#: how long an autopsy probe (census, live-arrays walk) may itself block
#: before the autopsy proceeds without it — a probe that needs the hung
#: backend must not hang the diagnosis
_PROBE_DEADLINE_S = 5.0


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    if not v:
        return default
    try:
        return float(v)
    except ValueError:
        warnings.warn(f"{name}={v!r} is not a number; using {default}",
                      RuntimeWarning)
        return default


# -- the shared device census -------------------------------------------------

def device_memory_census() -> dict:
    """``memory_stats`` summed across EVERY local device, plus the
    per-device breakdown: ``{"bytesInUse", "peakBytesInUse",
    "bytesLimit", "devices": [{"device", "bytesInUse", "peakBytesInUse",
    "bytesLimit"}, ...]}``. All zeros when the backend exposes no memory
    stats (CPU, some plugins). THE probe behind per-phase/per-span peak
    HBM and the sweep's HBM budget — a mesh-sharded batch lives on every
    device, so a device-0-only sample undercounts by the device count."""
    out: dict = {"bytesInUse": 0, "peakBytesInUse": 0, "bytesLimit": 0,
                 "devices": []}
    try:
        import jax
        devices = jax.local_devices()
    except Exception:  # failure-ok: no jax backend -> empty census
        return out
    for dev in devices:
        try:
            stats = dev.memory_stats() or {}
        except Exception:  # failure-ok: backend exposes no memory stats
            stats = {}
        in_use = int(stats.get("bytes_in_use", 0))
        peak = int(stats.get("peak_bytes_in_use", 0))
        limit = int(stats.get("bytes_limit", 0))
        out["bytesInUse"] += in_use
        out["peakBytesInUse"] += peak
        out["bytesLimit"] += limit
        out["devices"].append({"device": str(dev), "bytesInUse": in_use,
                               "peakBytesInUse": peak,
                               "bytesLimit": limit})
    return out


def device_memory() -> tuple[int, int]:
    """``(bytes_in_use, peak_bytes_in_use)`` summed across all local
    devices — the signature ``utils.profiling`` and ``utils.tracing``
    share for their HBM high-water probes."""
    c = device_memory_census()
    return c["bytesInUse"], c["peakBytesInUse"]


def device_bytes_limit() -> int:
    """Total reported device memory limit across all local devices
    (0 when the backend exposes none) — the sweep's HBM-budget base."""
    return device_memory_census()["bytesLimit"]


def live_buffer_census(top_k: int = 10) -> dict:
    """``jax.live_arrays()`` bucketed by (shape, dtype): who is actually
    holding device memory. Returns ``{"arrays", "totalBytes",
    "buckets": [{"shape", "dtype", "count", "bytes"}, ...]}`` with the
    ``top_k`` heaviest buckets. Autopsy-time only — the walk touches
    every live buffer."""
    out: dict = {"arrays": 0, "totalBytes": 0, "buckets": []}
    try:
        import jax
        arrays = jax.live_arrays()
    except Exception:  # failure-ok: live-array introspection is optional
        return out
    buckets: dict[tuple, dict] = {}
    total = 0
    for a in arrays:
        try:
            shape = tuple(a.shape)
            dtype = str(a.dtype)
            nbytes = int(getattr(a, "nbytes", 0))
        except Exception:  # failure-ok: a deleted buffer mid-walk is skipped
            continue
        b = buckets.setdefault((shape, dtype), {
            "shape": str(shape), "dtype": dtype, "count": 0, "bytes": 0})
        b["count"] += 1
        b["bytes"] += nbytes
        total += nbytes
    out["arrays"] = len(arrays)
    out["totalBytes"] = total
    out["buckets"] = sorted(buckets.values(),
                            key=lambda b: -b["bytes"])[:top_k]
    return out


def thread_stacks(max_frames: int = 40) -> list[dict]:
    """Every Python thread's current stack (faulthandler-style, but
    structured): ``[{"threadName", "threadId", "daemon", "frames":
    ["file:line fn: code", ...]}, ...]`` innermost frame LAST. Pure
    interpreter introspection — safe to call while the process is wedged
    on a device wait."""
    names = {t.ident: (t.name, t.daemon) for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        name, daemon = names.get(ident, (str(ident), True))
        frames = [
            f"{os.path.basename(fs.filename)}:{fs.lineno} {fs.name}: "
            f"{(fs.line or '').strip()}"
            for fs in traceback.extract_stack(frame)]
        out.append({"threadName": name, "threadId": int(ident),
                    "daemon": bool(daemon),
                    "frames": frames[-max_frames:]})
    return out


def _bounded_probe(fn: Callable[[], Any], default: Any,
                   timeout_s: float = _PROBE_DEADLINE_S) -> Any:
    """Run an autopsy probe on a side thread with a deadline: if the
    probe itself blocks on the hung backend (e.g. ``jax.local_devices``
    waiting on the initialization that is the hang), report ``default``
    instead of hanging the diagnosis."""
    box: dict[str, Any] = {}

    def work() -> None:
        try:
            box["v"] = fn()
        except Exception as e:  # noqa: BLE001 — a broken probe must not lose the autopsy
            box["v"] = {"probeError": f"{type(e).__name__}: {e}"}

    t = threading.Thread(target=work, daemon=True,
                         name="transmogrifai-autopsy-probe")
    t.start()
    t.join(timeout_s)
    return box.get("v", default)


# -- bounded census (safe from monitors and scrape collectors) ---------------

_census_lock = threading.Lock()
_census_state: dict = {"census": None, "t": 0.0, "next_probe": 0.0}
#: after a census probe times out (hung backend), don't re-probe for
#: this long — each retry parks one daemon thread on the hung call, and
#: a 0.5s-cadence monitor must not accumulate them unboundedly
_CENSUS_BACKOFF_S = 30.0


def _empty_census() -> dict:
    return {"bytesInUse": 0, "peakBytesInUse": 0, "bytesLimit": 0,
            "devices": []}


def device_memory_census_bounded(max_age_s: float = 2.0,
                                 timeout_s: float = 2.0) -> dict:
    """The census through a small cache + side-thread deadline: safe to
    call from the stall monitor, the ResourceWatchdog tick, and scrape
    collectors — paths that must never block on the hung backend they
    exist to observe. A fresh cache entry is served directly; a probe
    that times out serves the last good census (zeros before any
    succeeded) and backs off ``_CENSUS_BACKOFF_S`` before probing again,
    so a wedged backend costs at most one parked daemon thread per
    backoff window."""
    now = time.monotonic()
    with _census_lock:
        cached = _census_state["census"]
        if cached is not None and now - _census_state["t"] <= max_age_s:
            return cached
        if now < _census_state["next_probe"]:
            return cached if cached is not None else _empty_census()
    probed = _bounded_probe(device_memory_census, None,
                            timeout_s=timeout_s)
    with _census_lock:
        if isinstance(probed, dict) and "probeError" not in probed:
            _census_state["census"] = probed
            _census_state["t"] = time.monotonic()
            _census_state["next_probe"] = 0.0
            return probed
        _census_state["next_probe"] = time.monotonic() + _CENSUS_BACKOFF_S
        return _census_state["census"] or _empty_census()


def device_memory_bounded() -> tuple[int, int]:
    """``(bytes_in_use, peak)`` from the bounded census — the scrape
    collectors' probe (``device_memory`` stays live/unbounded for the
    in-band per-phase/per-span samplers, which run on the thread doing
    the device work anyway)."""
    c = device_memory_census_bounded()
    return c["bytesInUse"], c["peakBytesInUse"]


# -- the dispatch ledger ------------------------------------------------------

class DispatchLedger:
    """Inventory of in-flight device work: dispatch/settle seams
    ``register`` a labeled entry when they start blocking on device
    futures and ``complete`` it when the wait resolves (or is
    abandoned). The autopsy's answer to "what was the device supposed to
    be doing" — family/group labels from the sweep's pending queue, rows
    for serving batches, names for collectives. Attrs are camelCase
    (they land verbatim in incident JSON). Disabled
    (``TRANSMOGRIFAI_DEVICEWATCH=0`` / ``configure(enabled=False)``)
    ``register`` returns ``None`` and the hot paths pay nothing — the
    whole observatory switches off together."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._entries: dict[int, dict] = {}
        self.enabled = os.environ.get(ENABLE_ENV, "1") != "0"
        self.registered = 0
        self.completed = 0

    def register(self, site: str, **attrs) -> Optional[int]:
        if not self.enabled:
            return None
        entry = {"site": site, "since": time.time()}
        entry.update(attrs)
        with self._lock:
            eid = next(self._ids)
            self._entries[eid] = entry
            self.registered += 1
        return eid

    def complete(self, eid: Optional[int]) -> None:
        if eid is None:
            return
        with self._lock:
            if self._entries.pop(eid, None) is not None:
                self.completed += 1

    def inventory(self) -> list[dict]:
        """The in-flight entries, oldest first, with ages."""
        now = time.time()
        with self._lock:
            entries = sorted(self._entries.values(),
                             key=lambda e: e["since"])
        out = []
        for e in entries:
            doc = {k: v for k, v in e.items() if k != "since"}
            doc["ageSeconds"] = round(now - e["since"], 3)
            out.append(doc)
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def reset(self) -> None:
        with self._lock:
            self._entries = {}
            self.registered = 0
            self.completed = 0


dispatch_ledger = DispatchLedger()


# -- compile telemetry --------------------------------------------------------

class CompileTelemetry:
    """XLA compile observability: wall per backend compile (from the
    ``jax.monitoring`` duration listener, attributed to the active
    :meth:`building` site), recorded as a retroactive ``compile.program``
    span and the ``transmogrifai_compile_*`` series; compiles slower
    than the ``TRANSMOGRIFAI_SLOW_COMPILE_S`` threshold (default 10s)
    additionally emit a ``compile.slow`` flight-recorder event + warning.
    Persistent-cache hits don't fire the monitoring event — by design, a
    warm re-run reports 0 compiles (same contract as ``SweepCounters``).
    ``record_program_cost`` stores :func:`analyze_program` results
    (FLOPs, bytes, HLO size) from cold seams that hold a program
    handle."""

    def __init__(self, max_records: int = 512):
        self._lock = threading.Lock()
        self._listening = False
        self._site: contextvars.ContextVar[Optional[str]] = \
            contextvars.ContextVar("transmogrifai_compile_site",
                                   default=None)
        self.records: deque = deque(maxlen=int(max_records))
        self.programs = 0
        self.wall_s = 0.0
        self.max_wall_s = 0.0
        self.slow = 0
        self.in_progress = 0
        self.by_site: dict[str, dict] = {}
        self.program_costs: dict[str, dict] = {}

    def reset(self) -> None:
        with self._lock:
            self.records.clear()
            self.programs = 0
            self.wall_s = 0.0
            self.max_wall_s = 0.0
            self.slow = 0
            self.by_site = {}
            self.program_costs = {}

    @staticmethod
    def slow_threshold_s() -> float:
        return _env_float(SLOW_COMPILE_ENV, 10.0)

    def ensure_listener(self) -> None:
        """Register the process-wide monitoring listener once. Compiles
        stay 0 when the API is absent (never retried — same contract as
        ``SweepCounters``). The check-and-set runs under the lock:
        listeners can never unregister, so a double registration would
        double-count every compile for the process lifetime."""
        with self._lock:
            if self._listening:
                return
            self._listening = True
        try:
            import jax.monitoring as monitoring
            monitoring.register_event_duration_secs_listener(
                self._on_event)
        except Exception:  # failure-ok: monitoring API absent — compiles stay 0
            pass

    @contextlib.contextmanager
    def building(self, site: str):
        """Attribute backend compiles to ``site`` while the block runs
        (thread/task-local), and mark a program build in progress — the
        autopsy's "what was compiling" answer."""
        self.ensure_listener()
        token = self._site.set(site)
        with self._lock:
            self.in_progress += 1
        try:
            yield
        finally:
            with self._lock:
                self.in_progress -= 1
            self._site.reset(token)

    def _on_event(self, event: str, duration: float, **kw) -> None:
        if event != "/jax/core/compile/backend_compile_duration":
            return
        site = self._site.get() or "unattributed"
        now = time.time()
        wall = float(duration)
        with self._lock:
            self.programs += 1
            self.wall_s += wall
            self.max_wall_s = max(self.max_wall_s, wall)
            per = self.by_site.setdefault(
                site, {"programs": 0, "wallSeconds": 0.0})
            per["programs"] += 1
            per["wallSeconds"] += wall
            self.records.append({"site": site, "wallSeconds": wall,
                                 "ts": now})
            slow = wall >= self.slow_threshold_s()
            if slow:
                self.slow += 1
        try:
            from transmogrifai_tpu.utils.tracing import recorder
            recorder.add("compile.program", now - wall, now, site=site)
        except Exception:  # failure-ok: span recording is optional telemetry
            pass
        if slow:
            try:
                from transmogrifai_tpu.utils.events import events
                events.emit("compile.slow", site=site,
                            wallSeconds=round(wall, 3),
                            thresholdSeconds=self.slow_threshold_s())
            except Exception:  # failure-ok: event emission is optional telemetry
                pass
            warnings.warn(
                f"slow XLA compile at {site}: {wall:.1f}s (threshold "
                f"{self.slow_threshold_s():g}s) — a compile storm or a "
                "pathological HLO shape", RuntimeWarning)

    def record_program_cost(self, site: str, cost: dict) -> None:
        """Store one program's :func:`analyze_program` result and emit
        the ``compile.program`` event carrying it (cold seams only)."""
        if not cost:
            return
        with self._lock:
            self.program_costs[site] = dict(cost)
        try:
            from transmogrifai_tpu.utils.events import events
            events.emit("compile.program", site=site, **cost)
        except Exception:  # failure-ok: event emission is optional telemetry
            pass

    def to_json(self) -> dict:
        with self._lock:
            return {"programs": self.programs,
                    "wallSeconds": round(self.wall_s, 4),
                    "maxWallSeconds": round(self.max_wall_s, 4),
                    "slowCompiles": self.slow,
                    "inProgress": self.in_progress,
                    "bySite": {k: dict(v)
                               for k, v in sorted(self.by_site.items())},
                    "programCosts": {k: dict(v) for k, v
                                     in sorted(self.program_costs.items())}}


compile_telemetry = CompileTelemetry()


def analyze_program(fn, *args, **kwargs) -> dict:
    """Best-effort static cost report for a jitted callable at concrete
    args: ``{"flops", "bytesAccessed", "hloTextBytes"}`` (whichever are
    available; ``{}`` when the callable exposes no ``lower``). Lowering
    re-traces on host (no backend compile) — call from cold seams
    (warmup, program build), never per dispatch."""
    lower = getattr(fn, "lower", None)
    if lower is None:
        return {}
    try:
        lowered = lower(*args, **kwargs)
    except Exception:  # failure-ok: cost analysis is optional telemetry
        return {}
    out: dict = {}
    try:
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            if "flops" in ca:
                out["flops"] = float(ca["flops"])
            if "bytes accessed" in ca:
                out["bytesAccessed"] = float(ca["bytes accessed"])
    except Exception:  # failure-ok: cost analysis is version-dependent
        pass
    try:
        out["hloTextBytes"] = len(lowered.as_text())
    except Exception:  # failure-ok: HLO text rendering is optional
        pass
    return out


# -- the HBM timeline ---------------------------------------------------------

_timeline_lock = threading.Lock()
_timeline: deque = deque(maxlen=4096)


def sample_hbm(t: Optional[float] = None) -> int:
    """One all-device bytes-in-use sample appended to the bounded HBM
    timeline (merged into the chrome-trace export as a counter track).
    Low-rate by construction: callers are the ResourceWatchdog tick and
    the stall monitor's poll — never a hot path. Routed through the
    BOUNDED census: a monitor sampling a hung backend must serve the
    last good value, not wedge on the hang it is watching."""
    used = device_memory_census_bounded()["bytesInUse"]
    with _timeline_lock:
        _timeline.append((t if t is not None else time.time(), used))
    return used


def hbm_timeline() -> list[tuple[float, int]]:
    with _timeline_lock:
        return list(_timeline)


def reset_run() -> None:
    """Per-run state reset (called by ``profiler.reset``): the HBM
    timeline covers exactly one run's chrome trace. Watchdog/ledger/
    compile counters are process-lifetime (Prometheus monotonicity)."""
    with _timeline_lock:
        _timeline.clear()


# -- the autopsy --------------------------------------------------------------

def build_autopsy(wait: Optional[dict] = None) -> dict:
    """Assemble the autopsy document (pure — no events, no counters, no
    files; the watchdog and the metric-name lint both call this).
    Thread stacks and the dispatch ledger are pure interpreter state;
    the HBM/live-buffer probes run behind their own small deadlines so a
    hung backend cannot hang its own diagnosis."""
    doc: dict = {
        "at": time.time(),
        "threadStacks": thread_stacks(),
        "pendingDispatches": dispatch_ledger.inventory(),
        "hbmCensus": _bounded_probe(device_memory_census,
                                    {"unavailable": True}),
        "liveBuffers": _bounded_probe(live_buffer_census,
                                      {"unavailable": True}),
        "compile": compile_telemetry.to_json(),
    }
    if wait is not None:
        doc["wait"] = {
            "name": wait.get("name"),
            "site": wait.get("site"),
            "timeoutSeconds": wait.get("timeoutS"),
            "elapsedSeconds": round(time.time() - wait.get("t0",
                                                           time.time()), 3),
            "thread": wait.get("thread"),
            "attrs": dict(wait.get("attrs") or {}),
        }
    return doc


# -- the dispatch watchdog ----------------------------------------------------

class DispatchWatchdog:
    """Deadline monitor for blocking device waits (module docstring).

    One monitor thread polls the armed-wait registry; an expired wait
    fires ONE autopsy (``device.stall`` event + optional incident dump)
    and the wait keeps waiting — raising stays the caller's own deadline
    logic. Exiting a :meth:`guard` block, normally OR via an exception
    (an OOM-rung retry re-dispatching down the degradation ladder),
    disarms its deadline. Per-wait cost: two dict ops under a lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._waits: dict[int, dict] = {}
        self._monitor: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self.enabled = os.environ.get(ENABLE_ENV, "1") != "0"
        self.incident_dir: Optional[str] = \
            os.environ.get(INCIDENT_DIR_ENV) or None
        self.poll_interval_s = 0.5
        self._default_timeout_s: Optional[float] = None
        self.scrape_fn: Optional[Callable[[], str]] = None
        # counters (exported as transmogrifai_device_* series)
        self.guards = 0
        self.stalls = 0
        self.stalls_by_site: dict[str, int] = {}
        self.autopsies = 0
        self.last_autopsy: Optional[dict] = None

    # -- configuration -------------------------------------------------------
    def configure(self, *, enabled: Optional[bool] = None,
                  incident_dir: Optional[str] = None,
                  stall_timeout_s: Optional[float] = None,
                  poll_interval_s: Optional[float] = None,
                  scrape_fn: Optional[Callable[[], str]] = None
                  ) -> "DispatchWatchdog":
        if enabled is not None:
            self.enabled = bool(enabled)
        if incident_dir is not None:
            self.incident_dir = incident_dir or None
        if stall_timeout_s is not None:
            self._default_timeout_s = float(stall_timeout_s)
        if poll_interval_s is not None:
            self.poll_interval_s = max(float(poll_interval_s), 0.01)
            # interrupt a monitor mid-sleep so a shortened interval
            # takes effect now, not after the previous (longer) wait
            self._wake.set()
        if scrape_fn is not None:
            self.scrape_fn = scrape_fn
        return self

    def default_timeout_s(self) -> float:
        """Default stall deadline: 600s, deliberately matched to the
        collective deadline default (``TRANSMOGRIFAI_COLLECTIVE_TIMEOUT_S``)
        — a healthy large-shape settle on a slow CPU fallback can block
        for minutes, and a fired autopsy on a merely-slow wait is
        misleading evidence. Accelerator deployments (where a settle is
        seconds) should LOWER it via ``TRANSMOGRIFAI_STALL_TIMEOUT_S``;
        note expiry only observes — the wait always continues."""
        if self._default_timeout_s is not None:
            return self._default_timeout_s
        return _env_float(STALL_TIMEOUT_ENV, 600.0)

    def reset_counters(self) -> None:
        with self._lock:
            self.guards = 0
            self.stalls = 0
            self.stalls_by_site = {}
            self.autopsies = 0
            self.last_autopsy = None

    def active_waits(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._waits.values()]

    # -- arming --------------------------------------------------------------
    @contextlib.contextmanager
    def guard(self, name: str, *, timeout_s: Optional[float] = None,
              site: Optional[str] = None, **attrs):
        """Arm a stall deadline around a blocking device wait. Expiry
        fires one autopsy and the block keeps waiting; exit (normal or
        exceptional) disarms. ``attrs`` are camelCase labels for the
        autopsy's wait record."""
        if not self.enabled:
            yield None
            return
        timeout = (timeout_s if timeout_s is not None
                   else self.default_timeout_s())
        if timeout <= 0:
            yield None
            return
        entry = {"name": name, "site": site or name,
                 "timeoutS": float(timeout), "t0": time.time(),
                 "deadline": time.monotonic() + timeout,
                 "thread": threading.current_thread().name,
                 "fired": False, "attrs": attrs}
        with self._lock:
            wid = next(self._ids)
            self._waits[wid] = entry
            self.guards += 1
        self._ensure_monitor()
        try:
            yield wid
        finally:
            with self._lock:
                self._waits.pop(wid, None)

    # -- the monitor ---------------------------------------------------------
    def _ensure_monitor(self) -> None:
        # unlocked fast path: at batch-dispatch rate the monitor is
        # almost always already alive, and waking it per guard arm would
        # make it iterate per BATCH instead of per poll interval (a
        # deadline is seconds-scale; the 0.5s poll covers a fresh wait).
        # The benign race falls through to the locked re-check.
        m = self._monitor
        if m is not None and m.is_alive():
            return
        with self._lock:
            if self._monitor is not None and self._monitor.is_alive():
                return
            self._monitor = threading.Thread(
                target=self._monitor_loop,
                name="transmogrifai-dispatch-watchdog", daemon=True)
            self._monitor.start()

    def _monitor_loop(self) -> None:
        idle_since: Optional[float] = None
        while True:
            self._wake.wait(timeout=self.poll_interval_s)
            self._wake.clear()
            now = time.monotonic()
            to_fire: list[dict] = []
            with self._lock:
                if not self._waits:
                    if idle_since is None:
                        idle_since = now
                    elif now - idle_since > 60.0:
                        # nothing armed for a minute: the thread exits;
                        # the next guard restarts it lazily
                        self._monitor = None
                        return
                    continue
                idle_since = None
                for e in self._waits.values():
                    if not e["fired"] and now >= e["deadline"]:
                        e["fired"] = True
                        to_fire.append(e)
            # autopsies fire BEFORE the timeline sample: on a hung
            # backend even the bounded sample spends its deadline, and
            # the diagnosis must never queue behind telemetry
            for e in to_fire:
                try:
                    self._fire(e)
                except Exception as ex:  # noqa: BLE001 — a broken autopsy must not kill the monitor
                    warnings.warn(
                        f"devicewatch autopsy failed "
                        f"({type(ex).__name__}: {ex})", RuntimeWarning)
            # low-rate HBM timeline while waits are armed (autopsy-free
            # runs still get the counter track around their settles)
            try:
                sample_hbm()
            except Exception:  # failure-ok: the timeline is optional telemetry
                pass

    def _fire(self, entry: dict) -> None:
        self.stall_autopsy(
            f"device.stall:{entry['site']}", site=entry["site"],
            wait=entry)

    # -- the autopsy surface -------------------------------------------------
    def stall_autopsy(self, reason: str, *, site: str,
                      wait: Optional[dict] = None,
                      extra: Optional[dict] = None) -> dict:
        """Fire one autopsy for a stalled/expired wait: count the stall,
        emit the ``device.stall`` event, warn, and freeze an incident
        dump when an incident dir is configured. Called by the monitor
        on guard expiry and by ``run_with_deadline`` before raising
        ``CollectiveTimeoutError``. Returns the autopsy document (with
        ``incidentPath`` when one was written)."""
        doc = build_autopsy(wait=wait)
        doc["reason"] = reason
        if extra:
            doc.update(extra)
        with self._lock:
            self.stalls += 1
            self.stalls_by_site[site] = self.stalls_by_site.get(site, 0) + 1
            self.autopsies += 1
            self.last_autopsy = doc
        census = doc.get("hbmCensus") or {}
        try:
            from transmogrifai_tpu.utils.events import events
            events.emit(
                "device.stall", site=site,
                waitName=(wait or {}).get("name"),
                elapsedSeconds=(doc.get("wait") or {}).get(
                    "elapsedSeconds"),
                pendingDispatches=len(doc.get("pendingDispatches") or []),
                hbmBytesInUse=census.get("bytesInUse"),
                threads=len(doc.get("threadStacks") or []))
        except Exception:  # failure-ok: event emission is optional telemetry
            pass
        warnings.warn(
            f"device stall at {site}: blocking wait exceeded its "
            f"deadline ({reason}); autopsy captured "
            f"{len(doc.get('pendingDispatches') or [])} pending "
            "dispatch(es)", RuntimeWarning)
        if self.incident_dir:
            from transmogrifai_tpu.utils.events import dump_incident
            path = dump_incident(self.incident_dir, reason,
                                 scrape_fn=self.scrape_fn,
                                 extra={"autopsy": doc})
            doc["incidentPath"] = path
        return doc

    def to_json(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "guards": self.guards,
                    "stalls": self.stalls,
                    "stallsBySite": dict(self.stalls_by_site),
                    "autopsies": self.autopsies,
                    "activeWaits": len(self._waits),
                    "incidentDir": self.incident_dir}


watchdog = DispatchWatchdog()


def guard(name: str, *, timeout_s: Optional[float] = None,
          site: Optional[str] = None, **attrs):
    """Module-level convenience over the process-global watchdog."""
    return watchdog.guard(name, timeout_s=timeout_s, site=site, **attrs)


def configure(**kw) -> DispatchWatchdog:
    """Configure the process-global observatory. ``enabled`` flips the
    watchdog AND the dispatch ledger together — off means the hot paths
    pay nothing at all."""
    if kw.get("enabled") is not None:
        dispatch_ledger.enabled = bool(kw["enabled"])
    return watchdog.configure(**kw)


def stall_autopsy(reason: str, *, site: str,
                  wait: Optional[dict] = None,
                  extra: Optional[dict] = None) -> dict:
    return watchdog.stall_autopsy(reason, site=site, wait=wait,
                                  extra=extra)
