"""Deterministic fault injection: the failure-domain test harness.

The reference inherits Spark's chaos-resilience for free and tests it on
real clusters; our failure paths (transient-device retry, checkpoint
resume, streaming re-read, serving degradation, collective timeouts) must
instead be *deterministically* exercisable in CI. A :class:`FaultPlan`
names WHERE (an instrumented site), WHEN (the Nth invocation of that
site), WHAT (transient device error, host-IO error, slow call, simulated
preemption) and HOW OFTEN (a consecutive count, or a seeded probability),
so a test — or an operator reproducing an incident — replays the exact
same failure sequence every run.

Instrumented sites (grep ``fault_point(`` for the authoritative list):

========================  ====================================================
``dag.apply_layer``       fused device program of a DAG layer (via retry)
``sweep.fit``             one ModelSelector (fold, family) fit/score unit
``selector.refit``        after the winner refit's checkpoint write, before
                          train/holdout evaluation — a preemption here must
                          resume from the refit checkpoint without
                          retraining the winner
``train.layer``           start of each Workflow.train layer (preemption)
``ingest.read``           one streaming micro-batch file read
``ingest.fuse``           one fused FE segment dispatch (an injected OOM
                          takes the stagewise degradation rung)
``ingest.prefetch``       one double-buffered ingest chunk decode (the
                          background prefetch thread's work unit)
``checkpoint.write``      any durable checkpoint write (train/sweep/stream)
``collective``            multihost barrier / global-array assembly
``serving.dispatch``      one compiled serving batch dispatch
``serving.explain``       one compiled explain-lane batch dispatch (OOM
                          here takes the mask-chunk-halving ladder rung)
``serving.precision``     the precision shadow gate's candidate scoring
                          (between the f32 reference and the candidate
                          rung) — any non-harness kind here forces a
                          counted gate REJECTION: the batch serves the
                          f32 results bit-identically, never degrades
``serving.swap``          mid-fleet-hot-swap (candidate warm, alias not
                          yet flipped — the abort path must leave the old
                          version serving with zero drops)
``continuous.ingest``     one continuous-loop micro-batch consumption
``continuous.trigger``    a drift-window close / trigger evaluation
``continuous.retrain``    after the pendingRetrain manifest write, before
                          the retrain's train() — a preemption here must
                          resume the SAME retrain from its checkpoints
``continuous.promote``    before the retrained model's registration /
                          hot-swap — the abort path must leave the old
                          version serving with zero drops
``events.spill``          one flight-recorder JSONL spill batch write (the
                          ``enospc`` kind exercises the counted
                          best-effort loss path)
``scaleout.route``        one router proxy attempt (transient/io faults
                          retry the next replica candidate, bounded)
``scaleout.heartbeat``    one supervisor liveness-monitor tick (faults
                          must be survived — warn and keep monitoring)
``scaleout.roll``         one replica step of a rolling hot-swap (a fault
                          here halts the roll and rolls already-swapped
                          replicas back to the old version)
``net.accept``            one accepted client connection at a netchaos
                          proxy (``utils/netchaos.py``)
``net.connect``           one upstream dial by a netchaos proxy
``net.read``              one request-direction socket read at a proxy
``net.write``             one reply-direction socket write at a proxy
========================  ====================================================

The four ``net.*`` sites take the NETWORK fault kinds (``delay`` |
``reset`` | ``refuse`` | ``split`` | ``truncate`` | ``corrupt`` |
``blackhole``) and are delivered at the socket layer by
:class:`transmogrifai_tpu.utils.netchaos.ChaosProxy` rather than raised
in-frame — one plan string (one env var) drives both layers, e.g.
``transient@scaleout.route#1;reset@net.write#3``.

Plan syntax (env ``TRANSMOGRIFAI_FAULT_PLAN`` or programmatic), entries
separated by ``;``::

    kind@site[#at][xtimes][:delay_s][%prob]

    transient@sweep.fit#1        fail the 2nd sweep unit with a transient
                                 (retryable) XlaRuntimeError, once
    transient@dag.apply_layer#0x2  fail the first TWO layer dispatches
    preempt@train.layer#2        kill the process at layer 2 (SIGKILL analog)
    io@checkpoint.write          OSError on the first checkpoint write
    slow@collective:30           a 30s stall (dead-host analog) on the first
                                 collective
    transient@serving.dispatch%0.5  seeded coin-flip per dispatch

``kind``: ``transient`` | ``io`` | ``slow`` | ``preempt`` | ``oom`` |
``enospc``. ``oom`` raises a realistic ``RESOURCE_EXHAUSTED:``-prefixed
``XlaRuntimeError`` (classified by ``utils.resources.
is_resource_exhausted``, NOT transient — it exercises the degradation
ladder); ``enospc`` raises ``OSError(ENOSPC)`` (the full-disk path:
counted best-effort writes, never a crashed run). ``#at`` is the
0-based invocation index the entry starts firing at (default 0);
``xtimes`` the number of consecutive firings (default 1, ``x*`` forever);
``:delay_s`` the stall for ``slow``; ``%prob`` replaces the #at/xtimes
window with a per-invocation Bernoulli draw from the plan's seeded RNG.

Injection is a no-op (one dict lookup) when no plan is installed.
"""

from __future__ import annotations

import os
import random
import threading
import warnings
from contextlib import contextmanager
from typing import Optional

__all__ = ["FaultPlan", "FaultSpec", "FaultHarnessError",
           "SimulatedPreemption", "XlaRuntimeError", "fault_point",
           "install_plan", "clear_plan", "active_plan", "fault_plan",
           "NET_KINDS", "NET_SITES"]

#: the instrumented site names (documentation + parse-time validation)
KNOWN_SITES = frozenset({
    "dag.apply_layer", "sweep.fit", "selector.refit", "train.layer",
    "ingest.read", "ingest.fuse", "ingest.prefetch",
    "checkpoint.write", "collective", "serving.dispatch",
    "serving.explain", "serving.precision", "serving.swap",
    "continuous.ingest",
    "continuous.trigger",
    "continuous.retrain", "continuous.promote", "events.spill",
    "scaleout.route", "scaleout.heartbeat", "scaleout.roll",
    "net.accept", "net.connect", "net.read", "net.write",
})

#: the socket-layer sites (delivered by utils/netchaos.py, never raised
#: in-frame by fault_point)
NET_SITES = frozenset({"net.accept", "net.connect", "net.read",
                       "net.write"})

KINDS = ("transient", "io", "slow", "preempt", "oom", "enospc")

#: network fault kinds — only valid at NET_SITES, and NET_SITES only
#: take these: the pairing is enforced at parse time so a typo'd plan
#: fails loudly instead of silently never firing
NET_KINDS = ("delay", "reset", "refuse", "split", "truncate", "corrupt",
             "blackhole")


class FaultHarnessError(Exception):
    """Base of errors the harness itself must surface — never swallowed.

    Every failure-isolation handler in the framework (sweep candidate
    isolation, streaming read retry, checkpoint best-effort writes,
    serving degradation) re-raises this type: a harness-originated error
    converted into graceful degradation would report a chaos run green
    without exercising anything. Deliberately NOT a RuntimeError so
    ``utils.retry`` never classifies it as transient."""


class SimulatedPreemption(FaultHarnessError):
    """An injected crash/preemption: the in-process analog of SIGKILL.
    A preempted process does not retry or degrade — it dies and resumes
    from its checkpoints."""


class XlaRuntimeError(RuntimeError):
    """Injected stand-in for ``jaxlib``'s XlaRuntimeError: same type NAME
    and UNAVAILABLE-class status text, so ``utils.retry.
    is_transient_device_error`` classifies it exactly like the real thing
    observed on flaky TPU tunnels."""


class FaultSpec:
    """One parsed plan entry. See module docstring for the syntax."""

    def __init__(self, kind: str, site: str, at: int = 0, times: int = 1,
                 delay_s: float = 1.0, prob: Optional[float] = None):
        if kind not in KINDS and kind not in NET_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; one of "
                             f"{KINDS + NET_KINDS}")
        if site not in KNOWN_SITES:
            raise ValueError(
                f"unknown fault site {site!r}; one of {sorted(KNOWN_SITES)}")
        if (site in NET_SITES) != (kind in NET_KINDS):
            raise ValueError(
                f"fault kind {kind!r} does not pair with site {site!r}: "
                f"net.* sites take {NET_KINDS}, framework sites take "
                f"{KINDS}")
        self.kind = kind
        self.site = site
        self.at = int(at)
        self.times = times  # -1 == forever
        self.delay_s = float(delay_s)
        self.prob = prob

    def should_fire(self, invocation: int, rng: random.Random) -> bool:
        if self.prob is not None:
            return rng.random() < self.prob
        if invocation < self.at:
            return False
        return self.times < 0 or invocation < self.at + self.times

    @classmethod
    def parse(cls, entry: str) -> "FaultSpec":
        text = entry.strip()
        kind, sep, rest = text.partition("@")
        if not sep or not rest:
            raise ValueError(f"bad fault entry {entry!r}: expected kind@site")
        prob = None
        if "%" in rest:
            rest, _, p = rest.partition("%")
            prob = float(p)
        delay_s = 1.0
        if ":" in rest:
            rest, _, d = rest.partition(":")
            delay_s = float(d)
        at, times = 0, 1
        if "#" in rest:
            rest, _, window = rest.partition("#")
            if "x" in window:
                a, _, t = window.partition("x")
                at = int(a) if a else 0
                times = -1 if t == "*" else int(t)
            else:
                at = int(window)
        return cls(kind.strip(), rest.strip(), at=at, times=times,
                   delay_s=delay_s, prob=prob)

    def __repr__(self) -> str:
        win = f"%{self.prob}" if self.prob is not None else \
            f"#{self.at}x{'*' if self.times < 0 else self.times}"
        return f"FaultSpec({self.kind}@{self.site}{win})"


class FaultPlan:
    """A seeded, deterministic schedule of injected faults.

    Per-site invocation counters make deterministic entries exactly
    reproducible; probabilistic entries draw from one ``random.Random``
    seeded at construction, so the same plan + seed produces the same
    fault sequence run after run. ``fired`` records every injection as
    ``(site, invocation, kind)`` for post-hoc assertions."""

    def __init__(self, specs, seed: int = 0):
        self.specs = [FaultSpec.parse(s) if isinstance(s, str) else s
                      for s in specs]
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self.invocations: dict[str, int] = {}
        self.fired: list[tuple[str, int, str]] = []

    @classmethod
    def parse(cls, text: str, seed: int = 0) -> "FaultPlan":
        entries = [e for e in text.split(";") if e.strip()]
        return cls(entries, seed=seed)

    def reset(self) -> None:
        self._rng = random.Random(self.seed)
        self.invocations = {}
        self.fired = []

    def check(self, site: str) -> None:
        """Count one invocation of ``site`` and inject whatever the plan
        schedules for it. Raises / stalls in the CALLER's frame. ``fired``
        records each injection as it is DELIVERED — when one spec raises,
        later matching specs are neither delivered nor recorded."""
        with self._lock:
            inv = self.invocations.get(site, 0)
            self.invocations[site] = inv + 1
            to_fire = [s for s in self.specs if s.site == site
                       and s.kind not in NET_KINDS
                       and s.should_fire(inv, self._rng)]
        for s in to_fire:
            self.fired.append((site, inv, s.kind))
            _inject(s, site, inv)

    def net_check(self, site: str) -> list:
        """Count one invocation of a ``net.*`` site and return the
        network fault specs scheduled for it. Nothing is raised here —
        the netchaos proxy DELIVERS the returned specs at the socket
        layer (reset, truncation, corruption, ...). Each returned spec
        is recorded in ``fired`` exactly like a framework injection, so
        determinism assertions cover both layers."""
        with self._lock:
            inv = self.invocations.get(site, 0)
            self.invocations[site] = inv + 1
            to_fire = [s for s in self.specs if s.site == site
                       and s.kind in NET_KINDS
                       and s.should_fire(inv, self._rng)]
            for s in to_fire:
                self.fired.append((site, inv, s.kind))
        return to_fire


def _inject(spec: FaultSpec, site: str, inv: int) -> None:
    from transmogrifai_tpu.utils.events import events
    from transmogrifai_tpu.utils.profiling import run_counters
    run_counters.faults_injected += 1
    # the flight recorder marks injections so an incident dump produced
    # DURING a chaos run is self-explaining: the fault event sits right
    # before the failure cascade it caused
    events.emit("fault.injected", site=site, invocation=inv,
                faultKind=spec.kind)
    tag = f"injected fault at {site}#{inv}"
    if spec.kind == "slow":
        import time
        time.sleep(spec.delay_s)
        return
    if spec.kind == "transient":
        raise XlaRuntimeError(f"UNAVAILABLE: {tag} (simulated flaky device)")
    if spec.kind == "io":
        raise OSError(f"{tag} (simulated host-IO failure)")
    if spec.kind == "oom":
        # the real allocator's phrasing: RESOURCE_EXHAUSTED status + an
        # allocation message, so utils.resources.is_resource_exhausted
        # classifies it exactly like a genuine HBM OOM (and utils.retry
        # correctly refuses to retry it at the same shape)
        raise XlaRuntimeError(
            f"RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
            f"1073741824 bytes ({tag})")
    if spec.kind == "enospc":
        import errno
        raise OSError(errno.ENOSPC, f"No space left on device ({tag})")
    if spec.kind == "preempt":
        raise SimulatedPreemption(f"{tag} (simulated preemption)")


# -- global plan registry -----------------------------------------------------

_plan: Optional[FaultPlan] = None
#: (env string, parsed plan) cache so an unset/unchanged env costs one lookup
_env_cache: tuple[Optional[str], Optional[FaultPlan]] = (None, None)


def install_plan(plan: FaultPlan) -> FaultPlan:
    """Install ``plan`` process-wide (programmatic alternative to the
    ``TRANSMOGRIFAI_FAULT_PLAN`` env var, which it overrides)."""
    global _plan
    _plan = plan
    return plan


def clear_plan() -> None:
    global _plan
    _plan = None


def active_plan() -> Optional[FaultPlan]:
    """The installed plan, else one parsed from the env var (cached)."""
    if _plan is not None:
        return _plan
    global _env_cache
    env = os.environ.get("TRANSMOGRIFAI_FAULT_PLAN")
    if env == _env_cache[0]:
        return _env_cache[1]
    parsed: Optional[FaultPlan] = None
    if env:
        try:
            seed = int(os.environ.get("TRANSMOGRIFAI_FAULT_SEED", "0"))
            parsed = FaultPlan.parse(env, seed=seed)
        except Exception as e:
            # a typo'd plan must not silently run fault-free (a chaos run
            # would report green without injecting anything) — and because
            # fault_point sits inside instrumented try-blocks, the error
            # must be a FaultHarnessError so failure-isolation handlers
            # re-raise it instead of degrading gracefully around it
            raise FaultHarnessError(
                f"TRANSMOGRIFAI_FAULT_PLAN={env!r} failed to parse") from e
    _env_cache = (env, parsed)
    return parsed


@contextmanager
def fault_plan(plan_or_text, seed: int = 0):
    """Scoped plan installation for tests::

        with fault_plan("transient@dag.apply_layer#0x2"):
            model = wf.train()
    """
    global _plan
    plan = (FaultPlan.parse(plan_or_text, seed=seed)
            if isinstance(plan_or_text, str) else plan_or_text)
    prev = _plan
    install_plan(plan)
    try:
        yield plan
    finally:
        _plan = prev


def fault_point(site: str) -> None:
    """Injection hook compiled into the framework's failure seams. No-op
    (one global read) unless a plan is active."""
    plan = active_plan()
    if plan is not None:
        plan.check(site)
