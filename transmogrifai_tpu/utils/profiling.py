"""Phase-scoped profiling & run metrics.

Parity: reference ``utils/.../spark/OpSparkListener.scala`` (AppMetrics) +
``core/.../utils/spark/JobGroupUtil.scala`` (OpStep job-group taxonomy) —
every workflow phase is attributed to an ``OpStep``, wall/(optional) device
trace collected, and the aggregate ``AppMetrics`` is queryable/serializable
at the end of the run.

TPU-first: phases can additionally emit ``jax.profiler`` traces
(``trace_dir``) for XProf timeline analysis — the analog of drilling into
the Spark UI from a job group.
"""

from __future__ import annotations

import contextlib
import json
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

__all__ = ["OpStep", "AppMetrics", "profiler", "phase"]


class OpStep(Enum):
    DATA_READING_AND_FILTERING = "DataReadingAndFiltering"
    FEATURE_ENGINEERING = "FeatureEngineering"
    CROSS_VALIDATION = "CrossValidation"
    MODEL_TRAINING = "ModelTraining"
    SCORING = "Scoring"
    EVALUATION = "Evaluation"
    RESULTS_SAVING = "ResultsSaving"
    OTHER = "Other"


@dataclass
class PhaseMetrics:
    step: str
    wall_s: float = 0.0
    count: int = 0
    peak_hbm_bytes: int = 0   # device peak_bytes_in_use high-water mark


def _device_memory() -> tuple[int, int]:
    """(bytes_in_use, peak_bytes_in_use) of device 0, or zeros when the
    backend doesn't expose memory_stats (CPU, some plugins)."""
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
        return (int(stats.get("bytes_in_use", 0)),
                int(stats.get("peak_bytes_in_use", 0)))
    except Exception:
        return 0, 0


@dataclass
class AppMetrics:
    app_name: str = "transmogrifai_tpu"
    start_time: float = field(default_factory=time.time)
    phases: dict = field(default_factory=dict)  # step -> PhaseMetrics

    def record(self, step: OpStep, wall_s: float,
               peak_hbm: int = 0) -> None:
        pm = self.phases.setdefault(step.value, PhaseMetrics(step.value))
        pm.wall_s += wall_s
        pm.count += 1
        pm.peak_hbm_bytes = max(pm.peak_hbm_bytes, peak_hbm)

    @property
    def total_wall_s(self) -> float:
        return time.time() - self.start_time

    def to_json(self) -> dict:
        return {
            "appName": self.app_name,
            "totalWallSeconds": self.total_wall_s,
            "phases": {k: {"wallSeconds": p.wall_s, "count": p.count,
                           "peakHbmBytes": p.peak_hbm_bytes}
                       for k, p in self.phases.items()},
        }

    def save(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_json(), fh, indent=2)

    def pretty(self) -> str:
        from transmogrifai_tpu.utils.table import Table
        rows = [(k, f"{p.wall_s:.2f}", p.count,
                 f"{p.peak_hbm_bytes / 1e6:.0f}" if p.peak_hbm_bytes
                 else "-")
                for k, p in sorted(self.phases.items())]
        return str(Table(["Phase", "Wall (s)", "Count", "Peak HBM (MB)"],
                         rows, title=f"{self.app_name} metrics"))


class _Profiler:
    def __init__(self):
        self.metrics = AppMetrics()
        self.trace_dir: Optional[str] = None

    def reset(self, app_name: str = "transmogrifai_tpu",
              trace_dir: Optional[str] = None) -> AppMetrics:
        self.metrics = AppMetrics(app_name=app_name)
        self.trace_dir = trace_dir
        return self.metrics

    @contextlib.contextmanager
    def phase(self, step: OpStep):
        t0 = time.time()
        _, peak_before = _device_memory()
        ctx = contextlib.nullcontext()
        if self.trace_dir is not None:
            import jax
            ctx = jax.profiler.trace(self.trace_dir)
        try:
            with ctx:
                yield
        finally:
            # record on the error path too — a failed run's post-mortem
            # must still account the time spent before the failure
            _, peak_after = _device_memory()
            # peak_bytes_in_use is a process-lifetime high-water mark:
            # attribute it to this phase only when THIS phase raised it
            grew = peak_after if peak_after > peak_before else 0
            self.metrics.record(step, time.time() - t0, peak_hbm=grew)


profiler = _Profiler()
phase = profiler.phase
