"""Phase-scoped profiling & run metrics with device-time attribution.

Parity: reference ``utils/.../spark/OpSparkListener.scala:52-418``
(AppMetrics) + ``core/.../utils/spark/JobGroupUtil.scala`` (OpStep
job-group taxonomy) — every workflow phase is attributed to an ``OpStep``,
wall time collected, and the aggregate ``AppMetrics`` is queryable/
serializable at the end of the run.

TPU-first: where the reference attributes *executor* time to phases via
Spark job groups, this attributes *device* time via one ``jax.profiler``
trace spanning the run. Phase enter/exit wall timestamps are recorded; at
``finalize()`` the trace's XSpace protobuf is parsed directly (the device
plane's XLA-op timeline) and every device op interval is bucketed into the
innermost phase whose wall interval contains its midpoint. One trace, no
nesting restrictions, true device seconds per phase — the drill-down the
Spark UI gives a job group.
"""

from __future__ import annotations

import contextlib
import glob
import json
import os
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

__all__ = ["OpStep", "AppMetrics", "profiler", "phase",
           "trace_device_intervals", "trace_device_events",
           "aggregate_across_hosts", "SweepCounters", "sweep_counters",
           "ServingCounters", "RunCounters", "run_counters",
           "IngestCounters", "ingest_counters"]


class OpStep(Enum):
    DATA_READING_AND_FILTERING = "DataReadingAndFiltering"
    FEATURE_ENGINEERING = "FeatureEngineering"
    CROSS_VALIDATION = "CrossValidation"
    MODEL_TRAINING = "ModelTraining"
    SCORING = "Scoring"
    EVALUATION = "Evaluation"
    RESULTS_SAVING = "ResultsSaving"
    OTHER = "Other"


@dataclass
class PhaseMetrics:
    step: str
    wall_s: float = 0.0
    count: int = 0
    peak_hbm_bytes: int = 0   # device peak_bytes_in_use high-water mark
    device_s: float = 0.0     # attributed device busy seconds (finalize())


def _device_memory() -> tuple[int, int]:
    """(bytes_in_use, peak_bytes_in_use) summed across EVERY local
    device (the shared ``utils/devicewatch.py`` census — a mesh-sharded
    phase's memory lives on all devices, not device 0), or zeros when
    the backend exposes no memory_stats (CPU, some plugins)."""
    from transmogrifai_tpu.utils.devicewatch import device_memory
    return device_memory()


def trace_device_events(trace_dir: str) -> list[tuple[float, float, str]]:
    """Parse a ``jax.profiler`` trace directory into NAMED device-op events
    ``[(start_epoch_s, duration_s, op_name), ...]``.

    Reads the XSpace protobuf directly (``tensorflow.tsl`` proto bindings;
    the tensorboard-plugin converter is not required). Only accelerator
    planes (``/device:...``) count; per plane the busiest line is used so
    module- and op-level timelines aren't double-counted. Op names come
    from the plane's event-metadata table — ``jax.named_scope`` prefixes
    (the per-stage scopes ``dag.fuse_layer_program`` opens) survive into
    them, which is what lets the merged chrome trace label device slices
    with stage names. Returns [] when no trace/proto support is available
    (e.g. pure-CPU backends expose no device plane).
    """
    try:
        os.environ.setdefault(
            "PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except Exception:  # failure-ok: proto bindings optional; no trace parsed
        return []
    out: list[tuple[float, float, str]] = []
    for path in glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                          recursive=True):
        try:
            xs = xplane_pb2.XSpace()
            with open(path, "rb") as fh:
                xs.ParseFromString(fh.read())
        except Exception:  # failure-ok: unparseable trace file is skipped
            continue
        for plane in xs.planes:
            if not plane.name.startswith("/device:"):
                continue
            meta = {mid: m.name for mid, m in plane.event_metadata.items()}
            best: list[tuple[float, float, str]] = []
            best_busy = 0.0
            for line in plane.lines:
                ivals = [(line.timestamp_ns / 1e9 + ev.offset_ps / 1e12,
                          ev.duration_ps / 1e12,
                          meta.get(ev.metadata_id, ""))
                         for ev in line.events]
                busy = sum(d for _, d, _n in ivals)
                if busy > best_busy:
                    best, best_busy = ivals, busy
            out.extend(best)
    return out


def trace_device_intervals(trace_dir: str) -> list[tuple[float, float]]:
    """Unnamed device-op intervals ``[(start_epoch_s, duration_s), ...]``
    — the pre-existing surface; see :func:`trace_device_events` for the
    named variant the chrome-trace export fuses with host spans."""
    return [(s, d) for s, d, _ in trace_device_events(trace_dir)]


@dataclass
class AppMetrics:
    app_name: str = "transmogrifai_tpu"
    start_time: float = field(default_factory=time.time)
    #: frozen at ``profiler.finalize()`` — a saved run json must report the
    #: run's wall, not the wall at whatever moment ``to_json`` was called
    end_time: Optional[float] = None
    phases: dict = field(default_factory=dict)  # step -> PhaseMetrics
    #: phase occurrence intervals [(step, t0, t1)], enter order — the
    #: timeline device events are attributed against at finalize()
    spans: list = field(default_factory=list)
    #: per-DAG-stage rollup (tracing span aggregation, finalize()):
    #: label -> {"wallSeconds", "deviceSeconds", "count", "phase"}
    stages: dict = field(default_factory=dict)
    #: named device-plane events retained at finalize() for trace export
    device_events: list = field(default_factory=list)

    def record(self, step: OpStep, wall_s: float,
               peak_hbm: int = 0) -> None:
        pm = self.phases.setdefault(step.value, PhaseMetrics(step.value))
        pm.wall_s += wall_s
        pm.count += 1
        pm.peak_hbm_bytes = max(pm.peak_hbm_bytes, peak_hbm)

    def attribute_device_time(self,
                              intervals: list[tuple[float, float]]) -> float:
        """Bucket device-op intervals into the innermost containing phase
        span (latest-started span whose wall window contains the op's
        midpoint). Returns total attributed device seconds."""
        total = 0.0
        for start, dur in intervals:
            mid = start + dur / 2.0
            owner = None
            for step, t0, t1 in self.spans:
                if t0 <= mid <= t1 and (owner is None or t0 >= owner[1]):
                    owner = (step, t0)
            if owner is not None:
                pm = self.phases.setdefault(owner[0], PhaseMetrics(owner[0]))
                pm.device_s += dur
                total += dur
        return total

    @property
    def total_wall_s(self) -> float:
        return (self.end_time if self.end_time is not None
                else time.time()) - self.start_time

    def top_stages(self, k: int = 10) -> list[tuple[str, dict]]:
        """The K slowest DAG stages by inclusive wall (finalize() fills
        ``stages`` from the tracing recorder's per-stage spans)."""
        return sorted(self.stages.items(),
                      key=lambda kv: -kv[1].get("wallSeconds", 0.0))[:k]

    def to_json(self) -> dict:
        return {
            "appName": self.app_name,
            "totalWallSeconds": self.total_wall_s,
            "phases": {k: {"wallSeconds": p.wall_s, "count": p.count,
                           "peakHbmBytes": p.peak_hbm_bytes,
                           "deviceSeconds": p.device_s}
                       for k, p in self.phases.items()},
            "stages": {k: dict(v) for k, v in self.stages.items()},
            # fault-tolerance counters ride in every run summary — resume
            # and retry behavior is asserted from the same json operators
            # already collect (module global: one run's counters, reset
            # alongside the profiler)
            "runCounters": run_counters.to_json(),
            # resource-pressure accounting (utils/resources.py): every
            # degradation rung the run took, OOM/ENOSPC events, skipped
            # best-effort writes — the ladder's ground truth in the same
            # json
            "resourceCounters": _resource_counters_json(),
            # fused-ingest/FE accounting (round 14): fused vs host-side
            # FE stage-rows, prefetch overlap, frame-cache hits
            "ingestCounters": ingest_counters.to_json(),
        }

    def save(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_json(), fh, indent=2)

    def pretty(self, top_k: int = 10) -> str:
        from transmogrifai_tpu.utils.table import Table
        rows = [(k, f"{p.wall_s:.2f}",
                 f"{p.device_s:.2f}" if p.device_s else "-", p.count,
                 f"{p.peak_hbm_bytes / 1e6:.0f}" if p.peak_hbm_bytes
                 else "-")
                for k, p in sorted(self.phases.items())]
        out = str(Table(["Phase", "Wall (s)", "Device (s)", "Count",
                         "Peak HBM (MB)"],
                        rows, title=f"{self.app_name} metrics"))
        if self.stages:
            srows = [(label, f"{v['wallSeconds']:.3f}",
                      f"{v['deviceSeconds']:.3f}"
                      if v.get("deviceSeconds") else "-",
                      f"{v['peakHbmBytes'] / 1e6:.0f}"
                      if v.get("peakHbmBytes") else "-",
                      v.get("count", 0), v.get("phase", "") or "-")
                     for label, v in self.top_stages(top_k)]
            out += "\n" + str(Table(
                ["Stage", "Wall (s)", "Device (s)", "Peak HBM (MB)",
                 "Count", "Phase"],
                srows, title=f"top {len(srows)} slowest stages"))
        return out

    def export_chrome_trace(self, path: str) -> dict:
        """Write one Perfetto/chrome://tracing-compatible JSON merging the
        host span tree (``utils.tracing.recorder``), the coarse OpStep
        phase timeline, and (when a device plane was traced) the named
        device slices retained at ``finalize()``. Returns a small summary
        {"hostSpans": n, "deviceSlices": n, "phases": n}. Open the file at
        chrome://tracing or https://ui.perfetto.dev."""
        from transmogrifai_tpu.utils.tracing import recorder
        events: list[dict] = [
            {"name": "process_name", "ph": "M", "pid": 1,
             "args": {"name": f"{self.app_name} host"}},
            {"name": "process_name", "ph": "M", "pid": 2,
             "args": {"name": "device"}},
        ]
        for step, t0, t1 in self.spans:
            events.append({"name": step, "ph": "X", "pid": 1, "tid": 0,
                           "ts": t0 * 1e6, "dur": (t1 - t0) * 1e6,
                           "args": {"kind": "phase"}})
        events.append({"name": "thread_name", "ph": "M", "pid": 1,
                       "tid": 0, "args": {"name": "phases"}})
        host_events = recorder.chrome_trace_events(pid=1)
        events.extend(host_events)
        for start, dur, name in self.device_events:
            events.append({"name": name or "device-op", "ph": "X",
                           "pid": 2, "tid": 0, "ts": start * 1e6,
                           "dur": dur * 1e6, "args": {"kind": "device"}})
        # the HBM timeline (utils/devicewatch.py low-rate census) renders
        # as a chrome-trace counter track on the device process
        from transmogrifai_tpu.utils.devicewatch import hbm_timeline
        hbm = hbm_timeline()
        for ts, used in hbm:
            events.append({"name": "hbm_bytes_in_use", "ph": "C",
                           "pid": 2, "tid": 0, "ts": ts * 1e6,
                           "args": {"bytesInUse": used}})
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"appName": self.app_name,
                             "totalWallSeconds": self.total_wall_s}}
        from transmogrifai_tpu.utils.durable import atomic_json_dump
        atomic_json_dump(doc, path)
        n_host = sum(1 for e in host_events if e["ph"] == "X")
        return {"hostSpans": n_host,
                "deviceSlices": len(self.device_events),
                "phases": len(self.spans),
                "hbmSamples": len(hbm)}


def _resource_counters_json() -> dict:
    """Lazy import seam: profiling is imported by nearly everything, and
    resources imports retry — keep the module graph acyclic."""
    from transmogrifai_tpu.utils.resources import resource_counters
    return resource_counters.to_json()


class _CompileAttribution:
    """Shared ``jax.monitoring`` backend-compile listener: while a
    ``tracking(key)`` block runs, every XLA backend compile is attributed
    to ``key`` via the subclass's ``_record_compile``. Counts stay 0 when
    the monitoring API is unavailable; persistent-cache hits don't fire
    the event — by design, a warm re-run reports 0 compiles."""

    def __init__(self):
        self._active = None
        self._listening = False

    def _record_compile(self, key) -> None:
        raise NotImplementedError

    def _on_compile(self, event: str, duration: float, **kw) -> None:
        if (self._active is not None
                and event == "/jax/core/compile/backend_compile_duration"):
            self._record_compile(self._active)

    def _ensure_listener(self) -> None:
        if self._listening:
            return
        try:
            import jax.monitoring as monitoring
            monitoring.register_event_duration_secs_listener(self._on_compile)
            self._listening = True
        except Exception:  # failure-ok: monitoring API absent
            self._listening = True  # API absent: compiles stay 0, don't retry

    @contextlib.contextmanager
    def tracking(self, key):
        """Attribute compile events to ``key`` while the block runs."""
        self._ensure_listener()
        prev = self._active
        self._active = key
        try:
            yield
        finally:
            self._active = prev


@dataclass
class SweepFamilyCounters:
    """Per-candidate-family sweep observability (see ``SweepCounters``)."""
    #: "fold_stacked" | "tree_stacked" | "fold_loop" | "resumed"
    mode: str = ""
    compiles: int = 0           # XLA backend compiles while family active
    device_dispatches: int = 0  # train/score/metric program invocations
    host_syncs: int = 0         # device->host materializations (metric pulls)
    #: tree depth-groups dispatched fold x grid-stacked (round 8): on the
    #: tree fast path a group costs <= 1 dispatch + 1 sync per lane chunk
    stacked_groups: int = 0
    #: HBM-guard lane chunks dispatched (== stacked_groups unless the
    #: guard split a too-wide group; each chunk is one dispatch + sync)
    lane_chunks: int = 0


class SweepCounters(_CompileAttribution):
    """ModelSelector sweep observability: per family, how many XLA
    compiles, device program dispatches, and host syncs the sweep paid.

    Dispatches/syncs are counted at the SELECTOR's call granularity (one
    ``grid_fit_arrays*`` / scoring call = one dispatch; one metric
    ``np.asarray`` pull = one sync) — the contract the stacked fast
    paths optimize: k folds x |grid| points in one dispatch and ONE host
    sync per family (linear fold-stacking), or per depth-group/lane
    chunk (tree fold x grid stacking, ``stacked_groups``/``lane_chunks``),
    vs k (or k x L) of each on the per-fold loop. Compiles come from
    a ``jax.monitoring`` backend-compile listener attributed to whichever
    family is active inside ``tracking()`` (0 when the monitoring API is
    unavailable; cache hits from the persistent XLA cache don't count —
    by design, a warm re-run should report 0 compiles).

    Surfaced by ``bench.py`` under ``device_time_breakdown.sweep`` and
    asserted in tests (fast path == 1 sync per family).

    Run-level fields (round 9, the one-sync sweep): per-family
    ``host_syncs`` counts each family's metric PULL (the materialization
    that family paid for), while ``sweep_host_syncs`` counts blocking
    device->host settle BARRIERS for the whole sweep — on the async
    overlapped path every family's metrics settle behind ONE
    ``jax.block_until_ready``, so the run-level count stays 1 however
    many families/depth-groups dispatched (the tentpole assertion:
    O(1) syncs per ``train()``, not O(families + depth-groups)).
    ``async_families`` counts families whose metrics were held as device
    futures past their dispatch; ``refit_warm_starts`` counts winner
    refits that reused sweep state (stacked fold parameters or the
    dataset-level tree bin codes) instead of cold-starting. The O(1)
    scalar label-stat pull at dispatch start (max/mean of y, shared by
    every family) stays uncounted, as the per-family lnb pulls always
    were."""

    def __init__(self):
        super().__init__()
        self.families: dict = {}  # family name -> SweepFamilyCounters
        self.sweep_host_syncs = 0   # blocking settle barriers, whole sweep
        self.async_families = 0     # families overlapped past dispatch
        self.refit_warm_starts = 0  # winner refits reusing sweep state

    def reset(self) -> None:
        self.families = {}
        self.sweep_host_syncs = 0
        self.async_families = 0
        self.refit_warm_starts = 0
        self._active = None

    def family(self, name: str) -> SweepFamilyCounters:
        return self.families.setdefault(name, SweepFamilyCounters())

    def count(self, name: str, *, dispatches: int = 0,
              host_syncs: int = 0, stacked_groups: int = 0,
              lane_chunks: int = 0, mode: Optional[str] = None) -> None:
        fc = self.family(name)
        fc.device_dispatches += dispatches
        fc.host_syncs += host_syncs
        fc.stacked_groups += stacked_groups
        fc.lane_chunks += lane_chunks
        if mode is not None:
            fc.mode = mode

    def count_run(self, *, host_syncs: int = 0, async_families: int = 0,
                  refit_warm_starts: int = 0) -> None:
        """Run-level accounting (see class docstring): settle barriers,
        overlapped families, warm-started refits."""
        self.sweep_host_syncs += host_syncs
        self.async_families += async_families
        self.refit_warm_starts += refit_warm_starts

    def _record_compile(self, key) -> None:
        self.family(key).compiles += 1

    def to_json(self) -> dict:
        return {name: {"mode": fc.mode, "compiles": fc.compiles,
                       "deviceDispatches": fc.device_dispatches,
                       "hostSyncs": fc.host_syncs,
                       "stackedGroups": fc.stacked_groups,
                       "laneChunks": fc.lane_chunks}
                for name, fc in self.families.items()}

    def run_to_json(self) -> dict:
        """The run-level one-sync counters (separate from the per-family
        ``to_json`` map so existing consumers keep their shape)."""
        return {"sweepHostSyncs": self.sweep_host_syncs,
                "asyncFamilies": self.async_families,
                "refitWarmStarts": self.refit_warm_starts}


sweep_counters = SweepCounters()


@dataclass
class RunCounters:
    """Fault-tolerance observability for one run (reset with the profiler).

    The resumable-training and retry contracts are asserted through these:
    a checkpoint-resumed ``Workflow.train`` reports how many DAG layers it
    replayed from disk instead of refitting (``layers_resumed`` /
    ``stages_resumed``) vs fit live (``layers_fitted``), every transient
    device retry performed by ``utils.retry.with_device_retry`` counts in
    ``retries``, and every fault injected by an active ``utils.faults``
    plan counts in ``faults_injected``. Surfaced in ``AppMetrics.to_json``
    (runner result jsons) — the chaos suite's ground truth for "resumed
    without refitting".

    Process-global, like ``sweep_counters``: a ScoringServer retrying on
    its worker thread while a training run executes lands in the same
    ``retries`` total (serving has its own exact per-server retry metric,
    ``ServingMetrics.dispatch_retries`` — use that for serving). One
    runner/workflow run per process is the accounting model."""

    layers_fitted: int = 0
    layers_resumed: int = 0
    stages_resumed: int = 0
    retries: int = 0
    faults_injected: int = 0

    def reset(self) -> None:
        self.layers_fitted = 0
        self.layers_resumed = 0
        self.stages_resumed = 0
        self.retries = 0
        self.faults_injected = 0

    def to_json(self) -> dict:
        return {"layersFitted": self.layers_fitted,
                "layersResumed": self.layers_resumed,
                "stagesResumed": self.stages_resumed,
                "retries": self.retries,
                "faultsInjected": self.faults_injected}


run_counters = RunCounters()


@dataclass
class IngestCounters:
    """Fused-ingest/FE observability for one run (round 14; reset with the
    profiler, process-global like ``run_counters``).

    The device-resident FE contract is asserted through these: with
    ``TRANSMOGRIFAI_FE_FUSED=1`` every all-device DAG segment runs as one
    fused program (``fe_fused_programs``/``fe_fused_stages``; OFF must
    leave both at exactly 0 — the byte-for-byte pre-fusion path), an OOM
    inside a segment takes the stagewise rung (``fe_host_fallbacks``, rows
    re-applied stage-by-stage land in ``fe_host_rows``), the streaming
    double buffer prefetches chunk N+1 while chunk N computes
    (``chunks_prefetched``, blocked-consumer seconds in
    ``prefetch_wait_s``, background decode seconds in ``decode_s``), the
    fingerprint-keyed device-frame cache skips identical host->device
    re-transfers (``frame_cache_reuses``/``stores``; pressure drops in
    ``frame_cache_drops``), and mesh placement skips device_puts whose
    operand already carries the target sharding (``presharded_skips`` —
    the "sweep consumes pre-partitioned operands" handoff).

    Row counts are stage-rows (rows x stages applied), so fused vs
    host-side FE shares compare directly however segments split."""

    fe_fused_programs: int = 0
    fe_fused_stages: int = 0
    fe_fused_rows: int = 0
    fe_host_rows: int = 0
    fe_host_fallbacks: int = 0
    chunks_prefetched: int = 0
    prefetch_wait_s: float = 0.0
    decode_s: float = 0.0
    frame_cache_reuses: int = 0
    frame_cache_stores: int = 0
    frame_cache_drops: int = 0
    presharded_skips: int = 0

    def reset(self) -> None:
        self.fe_fused_programs = 0
        self.fe_fused_stages = 0
        self.fe_fused_rows = 0
        self.fe_host_rows = 0
        self.fe_host_fallbacks = 0
        self.chunks_prefetched = 0
        self.prefetch_wait_s = 0.0
        self.decode_s = 0.0
        self.frame_cache_reuses = 0
        self.frame_cache_stores = 0
        self.frame_cache_drops = 0
        self.presharded_skips = 0

    def to_json(self) -> dict:
        return {"feFusedPrograms": self.fe_fused_programs,
                "feFusedStages": self.fe_fused_stages,
                "feFusedRows": self.fe_fused_rows,
                "feHostRows": self.fe_host_rows,
                "feHostFallbacks": self.fe_host_fallbacks,
                "chunksPrefetched": self.chunks_prefetched,
                "prefetchWaitSeconds": self.prefetch_wait_s,
                "decodeSeconds": self.decode_s,
                "frameCacheReuses": self.frame_cache_reuses,
                "frameCacheStores": self.frame_cache_stores,
                "frameCacheDrops": self.frame_cache_drops,
                "preshardedSkips": self.presharded_skips}


ingest_counters = IngestCounters()


@dataclass
class ServingBucketCounters:
    """Per-padding-bucket online-serving observability (``ServingCounters``)."""
    compiles: int = 0    # XLA backend compiles while this bucket dispatched
    dispatches: int = 0  # fused-program invocations padded to this bucket
    #: shared-cache entries for this bucket dropped by the fleet cache's
    #: HBM-budget LRU (serving/fleet.ProgramCache) — a nonzero steady
    #: state means the budget is too small for the working set and the
    #: next dispatch at this bucket pays a recompile
    evictions: int = 0


class ServingCounters:
    """Online-serving compile observability per padding bucket.

    The serving compile-cache contract (``serving/compiled.py``): batches
    pad to power-of-two buckets, so after one warmup dispatch per bucket
    the fused layer programs are all jit-cache hits — steady-state serving
    never recompiles. Counters here make that assertable: the bench and
    tests snapshot per-bucket compiles after warmup and require 0 new ones
    under traffic. Dispatches are counted at the batch granularity (one
    ``score_batch`` = one dispatch, however many fused layers it runs).

    One instance per ``CompiledScorer``, fed by the SCORER measuring its
    own fused programs' jit-cache growth per dispatch — NOT the global
    ``jax.monitoring`` compile listener ``SweepCounters`` uses: monitoring
    events are process-wide, so two servers dispatching concurrently would
    cross-attribute each other's compiles (and per-instance listeners can
    never unregister). Cache-entry deltas are exact, per-program, and
    leak-free; "compiles" here means new fused-program instantiations
    (shape-keyed traces), the thing steady-state serving must not do."""

    def __init__(self):
        self.buckets: dict[int, ServingBucketCounters] = {}

    def reset(self) -> None:
        self.buckets = {}

    def bucket(self, size: int) -> ServingBucketCounters:
        return self.buckets.setdefault(int(size), ServingBucketCounters())

    def count(self, size: int, *, dispatches: int = 0,
              compiles: int = 0, evictions: int = 0) -> None:
        c = self.bucket(size)
        c.dispatches += dispatches
        c.compiles += compiles
        c.evictions += evictions

    def compiles_by_bucket(self) -> dict:
        return {b: c.compiles for b, c in sorted(self.buckets.items())}

    def evictions_by_bucket(self) -> dict:
        return {b: c.evictions for b, c in sorted(self.buckets.items())}

    def to_json(self) -> dict:
        return {str(b): {"compiles": c.compiles, "dispatches": c.dispatches,
                         "evictions": c.evictions}
                for b, c in sorted(self.buckets.items())}


def aggregate_across_hosts(metrics: AppMetrics, ctx=None,
                           timeout_s: Optional[float] = None) -> dict:
    """One run summary from per-host metrics: phase and stage wall /
    device / count totals summed across every host of the mesh through
    ``parallel.collectives.reduce_host_metrics`` (the same deadline-guarded
    all-reduce training statistics ride). Each host calls this with ITS
    ``AppMetrics`` after ``finalize()``; the returned json carries the
    pod-wide sums plus ``hosts``. With no mesh context the local summary
    returns unchanged (``hosts`` reflects ``jax.process_count()``) —
    single-host runs pay nothing."""
    doc = metrics.to_json()
    try:
        import jax
        doc["hosts"] = int(jax.process_count())
    except Exception:  # failure-ok: no jax backend -> single host
        doc["hosts"] = 1
    if ctx is None:
        return doc
    from transmogrifai_tpu.parallel.collectives import reduce_host_metrics
    flat: dict[str, float] = {}
    for ph, p in metrics.phases.items():
        flat[f"phase\t{ph}\twallSeconds"] = p.wall_s
        flat[f"phase\t{ph}\tdeviceSeconds"] = p.device_s
        flat[f"phase\t{ph}\tcount"] = float(p.count)
    for st, v in metrics.stages.items():
        flat[f"stage\t{st}\twallSeconds"] = v.get("wallSeconds", 0.0)
        flat[f"stage\t{st}\tdeviceSeconds"] = v.get("deviceSeconds", 0.0)
        flat[f"stage\t{st}\tcount"] = float(v.get("count", 0))
    reduced = reduce_host_metrics(ctx, flat, timeout_s=timeout_s)
    for key, val in reduced.items():
        kind, name, field_ = key.split("\t")
        dst = doc["phases"] if kind == "phase" else doc["stages"]
        entry = dst.setdefault(name, {})
        entry[field_] = int(round(val)) if field_ == "count" else val
    return doc


class _Profiler:
    def __init__(self):
        self.metrics = AppMetrics()
        self.trace_dir: Optional[str] = None
        self._tracing = False
        #: per-open-phase accumulated child seconds (exclusive-wall stack)
        self._stack: list[float] = []

    def reset(self, app_name: str = "transmogrifai_tpu",
              trace_dir: Optional[str] = None) -> AppMetrics:
        """New metrics object; with ``trace_dir``, starts one jax.profiler
        trace spanning everything until ``finalize()``. Sweep and run
        counters reset alongside so a run's counters cover exactly that
        run."""
        from transmogrifai_tpu.utils.devicewatch import reset_run
        from transmogrifai_tpu.utils.resources import resource_counters
        from transmogrifai_tpu.utils.tracing import recorder
        sweep_counters.reset()
        run_counters.reset()
        ingest_counters.reset()
        resource_counters.reset()
        recorder.reset()
        reset_run()  # the HBM timeline covers exactly this run's trace
        self.metrics = AppMetrics(app_name=app_name)
        self.trace_dir = trace_dir
        if self._tracing:  # a previous run never finalized: stop its trace
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:  # failure-ok: stale-trace stop is best-effort
                pass
            self._tracing = False
        if trace_dir is not None:
            try:
                import jax
                # lean trace: device timeline only (no host/python events,
                # no HLO protos) so post-run parsing stays cheap even for
                # multi-minute runs
                opts = None
                try:
                    opts = jax.profiler.ProfileOptions()
                    opts.host_tracer_level = 0
                    opts.python_tracer_level = 0
                    opts.enable_hlo_proto = False
                except Exception:  # failure-ok: ProfileOptions API is version-dependent
                    opts = None
                jax.profiler.start_trace(trace_dir, profiler_options=opts)
                self._tracing = True
            except Exception:  # failure-ok: tracing optional; run continues untraced
                self.trace_dir = None
        return self.metrics

    def finalize(self) -> AppMetrics:
        """Stop the run trace (if any), parse it, and attribute device time
        — to phases (coarse) AND to the innermost tracing span, so the
        stage table reports true device seconds per stage. Freezes the
        run's end timestamp and rolls the span recorder's per-stage
        aggregation into ``metrics.stages``. Idempotent; safe without a
        trace (device_s stays 0)."""
        from transmogrifai_tpu.utils.tracing import recorder
        if self._tracing:
            import jax
            try:
                jax.profiler.stop_trace()
            finally:
                self._tracing = False
            events = trace_device_events(self.trace_dir)
            self.metrics.device_events = events
            self.metrics.attribute_device_time(
                [(s, d) for s, d, _ in events])
            recorder.attribute_device_events(events)
        if self.metrics.end_time is None:
            self.metrics.end_time = time.time()
        self.metrics.stages = recorder.stage_table()
        return self.metrics

    @contextlib.contextmanager
    def phase(self, step: OpStep):
        t0 = time.time()
        _, peak_before = _device_memory()
        self._stack.append(0.0)
        try:
            yield
        finally:
            if self._tracing:
                # JAX dispatch is async: without a fence, device ops
                # enqueued near phase end can execute after the wall
                # window closes and be misattributed to the next phase.
                # Each device executes programs in enqueue order, so
                # blocking on one trivial computation PER local device
                # drains everything enqueued before it (sharded runs
                # enqueue on every mesh device, not just device 0).
                try:
                    import jax
                    jax.block_until_ready(
                        [jax.device_put(0.0, dev) + 0
                         for dev in jax.local_devices()])
                except Exception:  # failure-ok: drain fence is best-effort
                    pass
            # record on the error path too — a failed run's post-mortem
            # must still account the time spent before the failure
            t1 = time.time()
            _, peak_after = _device_memory()
            # peak_bytes_in_use is a process-lifetime high-water mark:
            # attribute it to this phase only when THIS phase raised it
            grew = peak_after if peak_after > peak_before else 0
            child_s = self._stack.pop()
            if self._stack:  # bubble own elapsed up to the enclosing phase
                self._stack[-1] += t1 - t0
            # exclusive wall: nested phases (e.g. the selector's CV inside
            # the workflow's FeatureEngineering) don't double-count
            self.metrics.record(step, (t1 - t0) - child_s, peak_hbm=grew)
            self.metrics.spans.append((step.value, t0, t1))


profiler = _Profiler()
phase = profiler.phase
