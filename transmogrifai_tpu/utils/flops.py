"""Analytic device-work (FLOP) accounting for MFU attribution.

The reference attributes executor work via Spark's task metrics
(``OpSparkListener.scala``); the TPU analog is achieved-FLOP/s against the
chip's peak. XLA's per-program ``cost_analysis`` is unavailable through the
opaque ``jax.jit`` call path without re-lowering, so each model family
records an analytic estimate of its training FLOPs at dispatch time — exact
for the dense linear algebra (matmul-dominated trainers), order-of-magnitude
for scatter/gather-bound tree histogram work (where "FLOPs" counts device
update ops and MFU is not the meaningful lens — bytes are).

Usage: ``flops.reset()`` before a run; trainers call ``flops.add(kind, n)``;
``flops.totals()`` afterward. Single-process, additive, no locking (JAX
dispatch is single-threaded per client).
"""

from __future__ import annotations

_totals: dict[str, float] = {}


def reset() -> None:
    _totals.clear()


def add(kind: str, n: float) -> None:
    _totals[kind] = _totals.get(kind, 0.0) + float(n)


def totals() -> dict[str, float]:
    return dict(_totals)


def grand_total() -> float:
    return float(sum(_totals.values()))


#: best-effort peak dense-FLOP/s by TPU device_kind substring (bf16 MXU
#: peak per chip, public spec sheets); None when unknown
_PEAKS = {
    "v5 lite": 197e12,   # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6": 918e12,        # v6e (Trillium)
}


def peak_flops_per_s() -> float | None:
    """Peak bf16 FLOP/s of device 0, or None off-TPU/unknown kind."""
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # failure-ok: device-kind probe; None means unknown
        return None
    for sub, peak in _PEAKS.items():
        if sub in kind:
            return peak
    return None
