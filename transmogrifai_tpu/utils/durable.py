"""Best-effort durable checkpoint writes — the ONE place the contract
lives.

Every checkpoint format in the framework (train manifest + layer arrays,
the selector's ``sweep.json``, the streaming ``StreamCheckpoint``) shares
the same durability rules, and they must never drift apart:

- **atomic**: payloads land via tmp-file + ``os.replace`` — a crash
  mid-write leaves the previous state intact, never a truncated file
  (:func:`atomic_json_dump`);
- **best-effort**: a write failure warns and returns ``False``; the run
  whose actual work succeeded continues un-checkpointed (degrading
  restart semantics to at-least-once), it never dies for bookkeeping
  (:func:`best_effort_checkpoint_write`);
- **injectable**: every write passes the ``checkpoint.write`` fault site,
  so the warn-and-continue path is exercisable in CI;
- **preemptable**: an injected :class:`~transmogrifai_tpu.utils.faults.
  SimulatedPreemption` propagates — a crashed process does not warn, it
  dies and resumes;
- **pressure-aware**: an observed ``ENOSPC`` (real or injected via the
  ``enospc`` fault kind) is counted in ``utils.resources.
  resource_counters`` and arms a cooldown window during which further
  best-effort writes short-circuit (counted in ``writesSkipped``)
  instead of paying a failing syscall + warning per checkpoint against
  a disk that cannot have recovered yet
  (``TRANSMOGRIFAI_ENOSPC_COOLDOWN_S``, default 30s).
"""

from __future__ import annotations

import json
import os
import warnings
from typing import Any, Callable

__all__ = ["best_effort_checkpoint_write", "atomic_json_dump",
           "ensure_checkpoint_dir"]


def ensure_checkpoint_dir(path: str, what: str) -> bool:
    """Create a checkpoint directory, best-effort: an unusable location
    (read-only mount, permissions, a file in the way) warns that ``what``
    proceeds WITHOUT checkpointing and returns False — it never fails the
    run whose actual work is healthy."""
    try:
        os.makedirs(path, exist_ok=True)
        return True
    except OSError as e:
        warnings.warn(
            f"{what}: cannot create checkpoint directory {path!r} "
            f"({type(e).__name__}: {e}); continuing WITHOUT checkpointing",
            RuntimeWarning)
        return False


def atomic_json_dump(doc: Any, path: str, **json_kw) -> None:
    """Write ``doc`` as json to ``path`` atomically (tmp + rename)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, **json_kw)
    os.replace(tmp, path)


def best_effort_checkpoint_write(write: Callable[[], None],
                                 failure_msg: str) -> bool:
    """Run ``write()`` under the shared checkpoint durability contract.
    Returns True on success; on failure warns ``failure_msg`` (with the
    cause appended) and returns False. Simulated preemption propagates.
    While the ENOSPC cooldown is armed (a recent write saw a full
    disk), the write is skipped up front and counted — the run keeps
    its at-least-once restart semantics, the full disk stops costing a
    syscall + warning per checkpoint."""
    from transmogrifai_tpu.utils.faults import (
        FaultHarnessError, fault_point,
    )
    from transmogrifai_tpu.utils.resources import (
        is_disk_full, resource_counters,
    )
    if resource_counters.enospc_backoff_active():
        resource_counters.note_write_skipped()
        return False
    try:
        fault_point("checkpoint.write")
        write()
        return True
    except FaultHarnessError:
        raise  # injected crash / misconfigured plan: surface, never swallow
    except Exception as e:  # noqa: BLE001 — warned: best-effort by contract
        if is_disk_full(e):
            resource_counters.note_enospc()  # arms the cooldown window
        warnings.warn(f"{failure_msg} ({type(e).__name__}: {e})",
                      RuntimeWarning)
        return False
