"""Contingency-matrix statistics.

Parity: reference ``utils/.../stats/OpStatistics.scala`` — chi-squared /
Cramér's V (with bias correction), mutual information, pointwise mutual
information, and association-rule confidence/support from a category x label
contingency matrix.

The contingency matrices themselves are produced on device as one
``X_onehot^T @ Y_onehot`` matmul inside the SanityChecker's fused stats
program; these helpers do the small [k, C] math on host.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["ContingencyStats", "contingency_stats", "cramers_v",
           "mutual_info", "pointwise_mutual_info"]


def _chi2(m: np.ndarray) -> float:
    n = m.sum()
    if n == 0:
        return 0.0
    row = m.sum(axis=1, keepdims=True)
    col = m.sum(axis=0, keepdims=True)
    expected = row @ col / n
    with np.errstate(divide="ignore", invalid="ignore"):
        terms = np.where(expected > 0, (m - expected) ** 2 / expected, 0.0)
    return float(terms.sum())


def _filter_empties(m: np.ndarray) -> np.ndarray:
    """Drop all-zero rows/columns (reference OpStatistics.filterEmpties)."""
    m = m[m.sum(axis=1) > 0]
    if m.size:
        m = m[:, m.sum(axis=0) > 0]
    return m


def cramers_v(m: np.ndarray) -> float:
    """Plain Cramér's V = sqrt(phi^2 / min(r-1, c-1)) on the empties-filtered
    matrix (reference OpStatistics.chiSquaredTestOnFiltered:207-209)."""
    m = _filter_empties(np.asarray(m, dtype=np.float64))
    if m.size == 0:
        return 0.0
    n = m.sum()
    r, k = m.shape
    if n == 0 or r < 2 or k < 2:
        return 0.0
    phi2 = _chi2(m) / n
    denom = min(r - 1, k - 1)
    return float(np.sqrt(phi2 / denom))


def mutual_info(m: np.ndarray) -> float:
    m = np.asarray(m, dtype=np.float64)
    n = m.sum()
    if n == 0:
        return 0.0
    p = m / n
    px = p.sum(axis=1, keepdims=True)
    py = p.sum(axis=0, keepdims=True)
    with np.errstate(divide="ignore", invalid="ignore"):
        terms = np.where(p > 0, p * np.log2(p / (px @ py)), 0.0)
    return float(terms.sum())


def pointwise_mutual_info(m: np.ndarray) -> np.ndarray:
    """PMI per cell (log2), 0 where the cell is empty."""
    m = np.asarray(m, dtype=np.float64)
    n = m.sum()
    if n == 0:
        return np.zeros_like(m)
    p = m / n
    px = p.sum(axis=1, keepdims=True)
    py = p.sum(axis=0, keepdims=True)
    with np.errstate(divide="ignore", invalid="ignore"):
        return np.where(p > 0, np.log2(p / (px @ py)), 0.0)


@dataclass(frozen=True)
class ContingencyStats:
    chi2: float
    cramers_v: float
    mutual_info: float
    pointwise_mutual_info: np.ndarray   # [categories, labels]
    #: per category: max over labels of P(label | category)
    max_rule_confidences: np.ndarray    # [categories]
    #: per category: P(category)
    supports: np.ndarray                # [categories]


def contingency_stats(m: np.ndarray) -> ContingencyStats:
    m = np.asarray(m, dtype=np.float64)
    n = max(m.sum(), 1e-12)
    row = m.sum(axis=1)
    with np.errstate(divide="ignore", invalid="ignore"):
        conf = np.where(row[:, None] > 0, m / row[:, None], 0.0)
    return ContingencyStats(
        chi2=_chi2(m),
        cramers_v=cramers_v(m),
        mutual_info=mutual_info(m),
        pointwise_mutual_info=pointwise_mutual_info(m),
        max_rule_confidences=conf.max(axis=1) if m.shape[1] else row * 0,
        supports=row / n,
    )
