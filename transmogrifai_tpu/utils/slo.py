"""SLO burn-rate engine: declarative objectives over the live metrics.

The SRE-workbook alerting layer on top of the counters the process
already keeps: an operator declares objectives —

- ``availability``: fraction of requests settling OK >= ``target``
  (errors = failed + expired settlements),
- ``latency``: fraction of requests at or under ``threshold_s`` >=
  ``target`` (measured from the monotonic cumulative latency histogram,
  so the bound snaps to a ``LATENCY_BUCKETS_S`` bucket boundary),
- ``staleness``: the continuous loop's drift-to-promotion staleness
  stays under ``bound_s`` (a freshness bound, not a ratio),

and the engine turns them into **multi-window burn rates**: the error
budget is ``1 - target``; the burn rate over a window is ``observed
error ratio / budget`` (1.0 = burning exactly the sustainable rate). An
alert fires only when BOTH its short and long windows burn above the
factor — the short window gives fast detection, the long window keeps a
single bad scrape from paging. Defaults are the SRE-workbook pair:
``fast`` = 14.4x over (5m, 1h) — budget gone in ~2 days — and ``slow`` =
6x over (30m, 6h).

Sampling: :meth:`SLOEngine.observe` snapshots the cumulative counters
and stores DELTAS; an interval in which any summed counter moved
backwards (a hot-swap dropped a lane's metrics) is recorded as zero
traffic — never as negative traffic, and never as a phantom error-only
sample — so window sums survive fleet topology changes. ``evaluate``/``status`` are
what the ``transmogrifai_slo_*`` gauges, ``/healthz`` readiness, and
``cli slo`` render; tests drive the same engine with synthetic
timelines by passing explicit ``t`` values.

Config file format (``--slo`` on ``cli serve`` / ``cli continuous``)::

    {"objectives": [
      {"name": "availability", "kind": "availability", "target": 0.999},
      {"name": "p99-latency", "kind": "latency",
       "target": 0.99, "thresholdMs": 250},
      {"name": "freshness", "kind": "staleness", "boundS": 3600}
    ]}

See docs/OBSERVABILITY.md ("SLOs and burn-rate alerts").
"""

from __future__ import annotations

import collections
import json
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

__all__ = ["BurnWindow", "SLObjective", "SLOEngine", "fold_health",
           "objectives_from_json", "load_objectives", "DEFAULT_WINDOWS"]

KINDS = ("availability", "latency", "staleness")


@dataclass(frozen=True)
class BurnWindow:
    """One multi-window burn-rate alert: fires when the burn exceeds
    ``factor`` over BOTH the short and the long window."""
    short_s: float
    long_s: float
    factor: float


#: SRE-workbook defaults: "fast" pages (budget exhausted in ~2 days at
#: this rate), "slow" tickets
DEFAULT_WINDOWS: dict = {
    "fast": BurnWindow(short_s=300.0, long_s=3600.0, factor=14.4),
    "slow": BurnWindow(short_s=1800.0, long_s=21600.0, factor=6.0),
}


@dataclass
class SLObjective:
    """One declarative objective (see module docstring for kinds)."""
    name: str
    kind: str = "availability"
    target: float = 0.999            # good-fraction target (ratio kinds)
    threshold_s: Optional[float] = None   # latency bound (kind=latency)
    bound_s: Optional[float] = None       # freshness bound (staleness)
    windows: dict = field(default_factory=lambda: dict(DEFAULT_WINDOWS))

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"SLO {self.name!r}: kind {self.kind!r} "
                             f"must be one of {KINDS}")
        if self.kind in ("availability", "latency") \
                and not 0.0 < self.target < 1.0:
            raise ValueError(f"SLO {self.name!r}: target {self.target} "
                             "must be in (0, 1)")
        if self.kind == "latency" and not self.threshold_s:
            raise ValueError(f"SLO {self.name!r}: latency objectives "
                             "need threshold_s")
        if self.kind == "staleness" and not self.bound_s:
            raise ValueError(f"SLO {self.name!r}: staleness objectives "
                             "need bound_s")
        fixed = {}
        for alert, w in self.windows.items():
            fixed[alert] = w if isinstance(w, BurnWindow) \
                else BurnWindow(*w)
        self.windows = fixed


def objectives_from_json(doc) -> list[SLObjective]:
    """Parse objectives from the config-file shape: a list of objective
    dicts, or ``{"objectives": [...]}``. Keys are camelCase in the file
    (``thresholdMs``/``thresholdS``, ``boundS``, ``windows`` mapping
    alert name to ``[shortS, longS, factor]``)."""
    if isinstance(doc, dict):
        doc = doc.get("objectives", [])
    out = []
    for i, o in enumerate(doc):
        if isinstance(o, SLObjective):
            out.append(o)
            continue
        if not isinstance(o, dict):
            raise ValueError(f"objective #{i} is not an object: {o!r}")
        threshold_s = o.get("thresholdS")
        if threshold_s is None and o.get("thresholdMs") is not None:
            threshold_s = float(o["thresholdMs"]) / 1e3
        windows = None
        if "windows" in o:
            windows = {alert: BurnWindow(float(w[0]), float(w[1]),
                                         float(w[2]))
                       for alert, w in o["windows"].items()}
        kwargs = dict(
            name=o.get("name", f"slo{i}"),
            kind=o.get("kind", "availability"),
            target=float(o.get("target", 0.999)),
            threshold_s=threshold_s,
            bound_s=(float(o["boundS"]) if o.get("boundS") is not None
                     else None))
        if windows is not None:
            kwargs["windows"] = windows
        out.append(SLObjective(**kwargs))
    return out


def load_objectives(path: str) -> list[SLObjective]:
    with open(path) as fh:
        return objectives_from_json(json.load(fh))


def fold_health(engine: Optional["SLOEngine"], doc: dict) -> None:
    """Fold an engine's alert state into an endpoint ``/healthz`` doc
    (shared by ``ScoringServer``/``FleetServer``/``ContinuousLoop``):
    attaches the ``slo`` block, and a firing fast-burn alert — the
    error budget burning at page rate — drops ``ready`` and marks the
    status ``slo_burning`` so an upstream load-balancer sheds traffic
    before anyone pages. No-op when ``engine`` is None."""
    if engine is None:
        return
    slo = engine.health()
    doc["slo"] = slo
    if slo["fastBurnFiring"]:
        doc["ready"] = False
        doc["status"] = "slo_burning"


class _Bound:
    """One objective bound to its live data source."""

    def __init__(self, obj: SLObjective, cap: int,
                 counts_fn: Optional[Callable[[], tuple]] = None,
                 value_fn: Optional[Callable[[], float]] = None):
        self.obj = obj
        self.cap = cap                # sample retention (see observe)
        self.longest_s = 3600.0       # longest window (gap rebaseline)
        self.counts_fn = counts_fn    # () -> cumulative (good, bad)
        self.value_fn = value_fn      # () -> current gauge value
        self.samples: collections.deque = collections.deque()
        self.last: Optional[tuple] = None
        self.value: float = 0.0


def _histogram_counts(hist: dict, threshold_s: float) -> tuple:
    """(good, bad) from one cumulative Prometheus-style histogram doc:
    good = requests at or under the smallest bucket bound >= threshold
    (conservative: the objective is judged at a real bucket boundary).
    A threshold ABOVE every finite bucket is judged at the largest
    finite bound — the +Inf tail is unmeasured latency and must not
    silently count as meeting the SLO (which would make the objective
    unfireable)."""
    total = int(hist.get("count", 0))
    best_bound, best_n = None, None
    largest = None
    for le, n in hist.get("buckets", {}).items():
        if le == "+Inf":
            continue
        bound = float(le)
        if largest is None or bound > largest[0]:
            largest = (bound, int(n))
        if bound >= threshold_s and (best_bound is None
                                     or bound < best_bound):
            best_bound, best_n = bound, int(n)
    if best_n is None:
        if largest is None:
            return total, 0
        return largest[1], total - largest[1]
    return best_n, total - best_n


class SLOEngine:
    """Evaluates bound objectives into multi-window burn-rate alert
    states (see module docstring)."""

    def __init__(self, max_samples: Optional[int] = None,
                 min_observe_interval_s: float = 1.0):
        """``max_samples`` (per objective) defaults to covering the
        objective's LONGEST configured window at the observe throttle
        rate — a fixed cap would silently truncate the slow alert's 6h
        long window under 1/s health probes, degenerating the smoothing
        it exists for. ~21600 samples (6h at 1/s) cost ~2 MB per
        objective. Pass an explicit cap to override (tests)."""
        self._bound: list[_Bound] = []
        self.max_samples = None if max_samples is None else int(max_samples)
        self.min_observe_interval_s = float(min_observe_interval_s)
        self._last_observe = 0.0     # monotonic throttle clock
        #: wall-clock evaluate() memo — a load balancer probing /healthz
        #: at a few Hz must not re-walk ~20k window samples per probe;
        #: invalidated by any recorded observation
        self._eval_cache: Optional[tuple] = None
        self.evaluations = 0

    # -- construction --------------------------------------------------------
    def add(self, obj: SLObjective,
            counts_fn: Optional[Callable[[], tuple]] = None,
            value_fn: Optional[Callable[[], float]] = None) -> "SLOEngine":
        if obj.kind == "staleness":
            if value_fn is None:
                raise ValueError(f"SLO {obj.name!r}: staleness needs a "
                                 "value_fn")
        elif counts_fn is None:
            raise ValueError(f"SLO {obj.name!r}: {obj.kind} needs a "
                             "counts_fn")
        longest = max((w.long_s for w in obj.windows.values()),
                      default=3600.0)
        if self.max_samples is not None:
            cap = self.max_samples
        else:
            cap = int(longest / self.min_observe_interval_s) + 16
        bound = _Bound(obj, cap, counts_fn, value_fn)
        bound.longest_s = longest
        self._bound.append(bound)
        return self

    @classmethod
    def for_serving(cls, spec, metrics_list_fn,
                    staleness_fn: Optional[Callable[[], float]] = None
                    ) -> "SLOEngine":
        """Bind objectives to live ``ServingMetrics``: ``spec`` is a
        prebuilt engine (returned as-is), a config path, or a list of
        ``SLObjective``/dicts; ``metrics_list_fn()`` returns the
        ``ServingMetrics`` to sum over (one for a ``ScoringServer``,
        every active lane's for a fleet); ``staleness_fn`` backs
        staleness objectives (the continuous loop's)."""
        if isinstance(spec, SLOEngine):
            return spec
        if isinstance(spec, str):
            objectives = load_objectives(spec)
        else:
            objectives = objectives_from_json(spec)
        engine = cls()
        for obj in objectives:
            if obj.kind == "availability":
                def counts(fn=metrics_list_fn):
                    good = bad = 0
                    for m in fn():
                        good += m.completed
                        bad += m.failed
                    return good, bad
                engine.add(obj, counts_fn=counts)
            elif obj.kind == "latency":
                def counts(fn=metrics_list_fn, thr=obj.threshold_s):
                    good = bad = 0
                    for m in fn():
                        g, b = _histogram_counts(m.latency_histogram(),
                                                 thr)
                        good += g
                        bad += b
                    return good, bad
                engine.add(obj, counts_fn=counts)
            else:
                if staleness_fn is None:
                    # a plain serving daemon has no drift/staleness
                    # source; the objective belongs to the continuous
                    # loop. Skip-with-warning keeps one objectives file
                    # shareable between `cli serve` and `cli continuous`
                    # (the documented config does exactly that) instead
                    # of killing the server at startup
                    import warnings
                    warnings.warn(
                        f"SLO {obj.name!r}: staleness objective ignored "
                        "— no staleness source here (continuous loop "
                        "only)", RuntimeWarning)
                    continue
                engine.add(obj, value_fn=staleness_fn)
        return engine

    @property
    def objectives(self) -> list[SLObjective]:
        return [b.obj for b in self._bound]

    # -- sampling ------------------------------------------------------------
    def observe(self, t: Optional[float] = None) -> bool:
        """Snapshot the cumulative sources into delta samples. Throttled
        (``min_observe_interval_s``) when ``t`` is None — scrapes and
        health probes may call at any rate; explicit ``t`` (tests,
        synthetic timelines) always records."""
        if t is None:
            now_m = time.monotonic()
            if now_m - self._last_observe < self.min_observe_interval_s:
                return False
            self._last_observe = now_m
            t = time.time()
        self._eval_cache = None      # new data: memoized state is stale
        for b in self._bound:
            if b.counts_fn is not None:
                good, bad = b.counts_fn()
                if b.last is None or (
                        b.samples
                        and t - b.samples[-1][0] > b.longest_s):
                    # first observation, or a sampling gap longer than
                    # every window: the accumulated history must NOT
                    # land as one delta stamped "now" — a long-resolved
                    # error burst would fire the burn alerts and shed a
                    # currently-healthy endpoint. Baseline and move on.
                    b.last = (good, bad)
                    b.samples.append((float(t), 0, 0))
                    continue
                if good < b.last[0] or bad < b.last[1]:
                    # ANY component moving backwards means the summed
                    # sources rebased (a hot-swap dropped a lane): the
                    # whole interval's deltas are meaningless, so record
                    # no traffic. Clamping per component instead would
                    # fabricate an error-only sample at every promotion
                    # (old lane's good counts vanish, new lane's bad
                    # counts survive) and spike the very burn windows
                    # the readiness flip reads.
                    dg = db = 0
                else:
                    dg = good - b.last[0]
                    db = bad - b.last[1]
                b.last = (good, bad)
                b.samples.append((float(t), dg, db))
                while len(b.samples) > b.cap:
                    b.samples.popleft()
            elif b.value_fn is not None:
                b.value = float(b.value_fn())
        return True

    @staticmethod
    def _window_ratio(samples, now: float, window_s: float
                      ) -> Optional[float]:
        good = bad = 0
        for ts, dg, db in reversed(samples):
            if ts <= now - window_s:
                break
            good += dg
            bad += db
        total = good + bad
        if total <= 0:
            return None     # no traffic in the window: no data
        return bad / total

    # -- evaluation ----------------------------------------------------------
    def evaluate(self, t: Optional[float] = None) -> dict:
        """Burn-rate state of every objective. Observes first (throttled
        unless ``t`` given), so a scrape is self-updating. Wall-clock
        evaluations (``t=None`` — health probes, scrapes) are memoized
        until the next recorded observation, so probe rate doesn't
        multiply the window-sum cost; explicit-``t`` timelines (tests)
        always compute."""
        self.observe(t)
        if t is None and self._eval_cache is not None:
            return self._eval_cache[1]
        now = float(t) if t is not None else time.time()
        self.evaluations += 1
        out: dict = {}
        for b in self._bound:
            obj = b.obj
            if obj.kind == "staleness":
                # b.value was refreshed by the observe() above (or is at
                # most one throttle interval old) — evaluation reads the
                # cache rather than re-calling value_fn a second time
                v = b.value
                burn = v / obj.bound_s if obj.bound_s else 0.0
                out[obj.name] = {
                    "kind": obj.kind,
                    "boundSeconds": obj.bound_s,
                    "stalenessSeconds": round(v, 3),
                    "alerts": {"bound": {
                        "burn": {"current": round(burn, 4)},
                        "firing": v > obj.bound_s}},
                    "firing": v > obj.bound_s,
                }
                continue
            budget = 1.0 - obj.target
            alerts: dict = {}
            firing_any = False
            for alert, w in obj.windows.items():
                burns: dict = {}
                over = []
                for label, win_s in (("short", w.short_s),
                                     ("long", w.long_s)):
                    ratio = self._window_ratio(b.samples, now, win_s)
                    burn = 0.0 if ratio is None else ratio / budget
                    burns[label] = round(burn, 4)
                    over.append(ratio is not None and burn > w.factor)
                firing = all(over)
                firing_any = firing_any or firing
                alerts[alert] = {"burn": burns, "factor": w.factor,
                                 "firing": firing}
            doc = {"kind": obj.kind, "target": obj.target,
                   "alerts": alerts, "firing": firing_any}
            if obj.kind == "latency":
                doc["thresholdSeconds"] = obj.threshold_s
            out[obj.name] = doc
        if t is None:
            self._eval_cache = (time.monotonic(), out)
        return out

    def status(self, t: Optional[float] = None) -> dict:
        """The one-call view ``cli slo`` and ``/healthz`` consume.
        Page severity is an alert's POSITION, not its name: the
        objective's fastest-detection alert (smallest short window —
        ``fast`` in the default pair, the sole alert of a staleness
        bound or a custom single-window set) is the one that flips
        readiness, so operator-named windows behave identically."""
        objectives = self.evaluate(t)
        firing = sorted(n for n, d in objectives.items() if d["firing"])
        fast = []
        for b in self._bound:
            d = objectives.get(b.obj.name)
            if d is None:
                continue
            alerts = d.get("alerts", {})
            live = {a for a, ad in alerts.items() if ad.get("firing")}
            if not live:
                continue
            if b.obj.kind == "staleness" or len(alerts) == 1:
                fast.append(b.obj.name)
                continue
            page = min(b.obj.windows, key=lambda a: b.obj.windows[a].short_s)
            if page in live:
                fast.append(b.obj.name)
        fast.sort()
        return {"objectives": objectives, "firing": firing,
                "fastBurnFiring": bool(fast), "fastFiring": fast}

    def health(self, t: Optional[float] = None) -> dict:
        """The compact ``/healthz`` block: which objectives fire, and
        whether any at page severity (the ``fast`` alert, or a breached
        staleness bound) — the bit that flips endpoint readiness."""
        s = self.status(t)
        return {"firing": s["firing"],
                "fastBurnFiring": s["fastBurnFiring"],
                "ok": not s["firing"]}

    def page_firing(self, t: Optional[float] = None) -> bool:
        """True while any objective burns at page severity — the ONE
        boolean consumers act on without reading the whole status doc:
        ``/healthz`` readiness flips on it and the scale-out autoscaler
        reads it as the scale-up trigger."""
        return bool(self.status(t)["fastBurnFiring"])

    # -- export --------------------------------------------------------------
    def gauge_samples(self) -> dict:
        """Label/value sample lists for the ``transmogrifai_slo_*``
        gauges (consumed by ``utils/prometheus.py``)."""
        doc = self.evaluate()
        targets, burns, firing = [], [], []
        for name, d in doc.items():
            if "target" in d:
                targets.append(({"slo": name}, d["target"]))
            for alert, a in d.get("alerts", {}).items():
                for window, burn in a.get("burn", {}).items():
                    burns.append(({"slo": name, "alert": alert,
                                   "window": window}, burn))
                firing.append(({"slo": name, "alert": alert},
                               1 if a.get("firing") else 0))
        return {"targets": targets, "burns": burns, "firing": firing}
