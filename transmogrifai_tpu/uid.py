"""Stage/feature UID registry.

Parity: reference `utils/src/main/scala/com/salesforce/op/UID.scala` —
`ClassName_000000000012`-style uids from a global counter, with reset support
for deterministic tests.
"""

from __future__ import annotations

import itertools
import re
import threading

_COUNTER = itertools.count(1)
_LOCK = threading.Lock()
_UID_RE = re.compile(r"^(.*)_(\d{12})$")


class UID:
    """Global uid factory: ``UID.of("RealVectorizer") -> "RealVectorizer_000000000001"``."""

    @staticmethod
    def of(prefix: str | type) -> str:
        if isinstance(prefix, type):
            prefix = prefix.__name__
        with _LOCK:
            count = next(_COUNTER)
        return f"{prefix}_{count:012d}"

    @staticmethod
    def reset(start: int = 1) -> None:
        """Reset the counter (tests only — mirrors reference UID.reset)."""
        global _COUNTER
        with _LOCK:
            _COUNTER = itertools.count(start)

    @staticmethod
    def from_string(uid: str) -> tuple[str, int]:
        """Parse ``Prefix_000000000012`` into (prefix, 12). Raises on bad format."""
        m = _UID_RE.match(uid)
        if not m:
            raise ValueError(f"Invalid uid format: {uid!r}")
        return m.group(1), int(m.group(2))
