"""Workflow: DAG assembly, training, scoring.

Parity: reference ``core/src/main/scala/com/salesforce/op/{OpWorkflow,
OpWorkflowCore,OpWorkflowModel}.scala`` — ``set_result_features`` back-traces
lineage; ``train()`` generates raw data through the reader, fits the leveled
DAG, and returns a ``WorkflowModel`` whose ``score()`` replays the fitted
transformer DAG (layer-fused jit programs), ``evaluate()`` runs evaluators,
``save()``/``load_model()`` round-trip the fitted pipeline, and
``score_function()`` compiles the Spark-free local scoring closure
(reference ``local/OpWorkflowModelLocal``).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Iterable, Optional, Sequence

import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.dag import Dag, DagExecutor, compute_dag
from transmogrifai_tpu.features.feature import FeatureLike
from transmogrifai_tpu.pipeline_data import PipelineData
from transmogrifai_tpu.readers.base import CustomReader, DataReader
from transmogrifai_tpu.selector.model_selector import SelectedModel
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["Workflow", "WorkflowModel", "load_model"]


class Workflow:
    def __init__(self):
        self.reader: Optional[DataReader] = None
        self.result_features: tuple[FeatureLike, ...] = ()
        self._raw_feature_filter = None
        self._workflow_cv = False
        self._model_stage_overrides: dict[str, Any] = {}
        #: fingerprint-keyed device-frame cache (round 14): repeated
        #: train() over identical host columns — and the trained model's
        #: first score over the training frame — reuse the HBM-resident
        #: columns instead of re-transferring. Shared into WorkflowModel.
        from transmogrifai_tpu.ingest_fusion import DeviceFrameCache
        self._frame_cache = DeviceFrameCache()

    def with_workflow_cv(self, enabled: bool = True) -> "Workflow":
        """Leakage-free workflow-level CV (reference ``withWorkflowCV``):
        label-dependent feature stages feeding the ModelSelector are refit
        inside each CV fold instead of once on the full training data."""
        self._workflow_cv = enabled
        return self

    # -- inputs --------------------------------------------------------------
    def set_reader(self, reader: DataReader) -> "Workflow":
        self.reader = reader
        return self

    def set_input_frame(self, frame: fr.HostFrame) -> "Workflow":
        self.reader = CustomReader(frame=frame)
        return self

    def set_input_records(self, records: Iterable[Any],
                          key_fn: Optional[Callable] = None) -> "Workflow":
        self.reader = CustomReader(records=records, key_fn=key_fn)
        return self

    def set_result_features(self, *features: FeatureLike) -> "Workflow":
        if not features:
            raise ValueError("need at least one result feature")
        self.result_features = tuple(features)
        return self

    def with_raw_feature_filter(self, rff) -> "Workflow":
        """Attach a RawFeatureFilter applied before training (blocklisting
        low-quality raw features and rewiring the DAG)."""
        self._raw_feature_filter = rff
        return self

    def with_model_stages(self, model: "WorkflowModel") -> "Workflow":
        """Resume training with already-fitted stages (reference
        ``OpWorkflow.withModelStages:468-472``): any stage in this
        workflow's DAG whose output feature matches one fitted in ``model``
        is reused as-is instead of refit."""
        for layer in model.dag:
            for t in layer:
                out = t.get_output()
                if out is not None:
                    self._model_stage_overrides[out.uid] = t
        return self

    def _substitute_fitted(self, dag: Dag,
                           extra: Optional[dict] = None) -> Dag:
        """Replace stages whose output feature is already fitted — by
        ``with_model_stages`` or (``extra``) a restored train checkpoint —
        the replay seam resumable training grafts onto. An explicit
        ``with_model_stages`` override WINS over a checkpoint restore: the
        user handed us a newer fitted stage on purpose; the on-disk copy
        may be stale."""
        overrides = self._model_stage_overrides
        if extra:
            overrides = {**extra, **overrides}
        if not overrides:
            return dag
        return [[overrides.get(s.get_output().uid, s)
                 for s in layer] for layer in dag]

    def validate(self, sample_frame: Optional[fr.HostFrame] = None) -> dict:
        """Pre-train stage validation — the TPU analog of the reference's
        ``checkSerializable`` + ``checkCtorUIDs`` (``OpWorkflow.scala:
        280-324``): where the reference verifies closures can ship to Spark
        executors, the compiled-program equivalent verifies each stage (a)
        has distinct uids and wired inputs (raised inside ``compute_dag``),
        (b) can serialize (``config``/``fitted_state`` don't raise — a saved
        model will round-trip), and (c) for device transformers, TRACES
        under abstract shapes (``jax.eval_shape`` on a sample frame): a
        stage with data-dependent Python control flow fails here with its
        uid named, instead of deep inside a fused layer compile.

        Returns {"unserializable": {uid: reason}, "untraceable":
        {uid: reason}, "layer_failures": [reason]} — a layer that cannot
        even APPLY on the sample is itself a finding (and stops deeper
        tracing). Raises only on structural problems (duplicate uids).
        Training is NOT blocked by findings — saving a model with
        unserializable stages raises at save time, as always.
        """
        from transmogrifai_tpu.stages.base import (
            DeviceTransformer, Estimator,
        )
        report: dict = {"unserializable": {}, "untraceable": {},
                        "layer_failures": []}
        dag = self._substitute_fitted(compute_dag(self.result_features))
        stages = [s for layer in dag for s in layer]
        for s in stages:
            try:
                s.config()
                if hasattr(s, "fitted_state"):
                    s.fitted_state()
            except Exception as e:  # noqa: BLE001 — report, don't raise
                report["unserializable"][s.uid] = (
                    f"{type(s).__name__}: {e}")
        if sample_frame is not None:
            import jax
            data = PipelineData.from_host(sample_frame)
            for layer in dag:
                fitted = []
                for s in layer:
                    if isinstance(s, Estimator):
                        try:
                            s = s.fit(data)
                        except Exception as e:  # noqa: BLE001 — recorded in the report
                            report["untraceable"][s.uid] = (
                                f"{type(s).__name__} fit on sample: {e}")
                            continue
                    fitted.append(s)
                for t in fitted:
                    if not isinstance(t, DeviceTransformer):
                        continue
                    try:
                        cols = [data.device_col(n)
                                for n in t.runtime_input_names()]
                        params = t.device_params()
                        jax.eval_shape(
                            lambda p, c, _t=t: _t.device_apply(p, *c),
                            params, cols)
                    except Exception as e:  # noqa: BLE001 — recorded in the report
                        report["untraceable"][t.uid] = (
                            f"{type(t).__name__}: {e}")
                try:
                    data = DagExecutor().apply_layer(data, fitted)
                except Exception as e:  # noqa: BLE001 — recorded; stops below
                    # a silently-clean report for a workflow that cannot
                    # run would be a false all-clear: record + stop (the
                    # downstream layers lack inputs now)
                    report["layer_failures"].append(
                        f"layer [{', '.join(t.uid for t in fitted)}] "
                        f"failed to apply on the sample: "
                        f"{type(e).__name__}: {e}")
                    break
        return report

    def compute_data_up_to(self, feature: FeatureLike) -> fr.HostFrame:
        """Materialize the data with all transformations applied up to (and
        including) ``feature`` (reference ``OpWorkflow.computeDataUpTo``) —
        fitting whatever estimators the path needs. Returns every feature
        generated along the way (raws + intermediates + the target)."""
        if self.reader is None:
            raise ValueError("set a reader or input frame first")
        raw = [f for f in feature.raw_features()] or [feature]
        frame = self.reader.generate_frame(raw)
        data = PipelineData.from_host(frame)
        dag = self._substitute_fitted(compute_dag([feature]))
        data, _ = DagExecutor().fit_transform(data, dag)
        return _frame_up_to(data, raw, dag)

    # -- lineage -------------------------------------------------------------
    def raw_features(self) -> list[FeatureLike]:
        seen: dict[str, FeatureLike] = {}
        for f in self.result_features:
            for r in f.raw_features():
                seen.setdefault(r.uid, r)
        return sorted(seen.values(), key=lambda f: f.name)

    # -- train ---------------------------------------------------------------
    def train(self, checkpoint_dir: Optional[str] = None) -> "WorkflowModel":
        """Fit the workflow. With ``checkpoint_dir``, training is RESUMABLE:
        each fitted DAG layer persists as it completes (``checkpoint.
        TrainCheckpoint``) and any unconfigured ModelSelector checkpoints
        its sweep into the same directory — after a crash or preemption,
        calling ``train`` again with the same directory replays completed
        layers (and completed sweep units) from disk instead of refitting.
        See docs/ROBUSTNESS.md."""
        if self.reader is None:
            raise ValueError("set a reader or input frame before train()")
        if not self.result_features:
            raise ValueError("set result features before train()")
        from transmogrifai_tpu.utils.profiling import OpStep, profiler
        from transmogrifai_tpu.utils.tracing import span
        raw = self.raw_features()
        filter_results = None
        with profiler.phase(OpStep.DATA_READING_AND_FILTERING), \
                span("workflow.ingest", reader=type(self.reader).__name__,
                     n_raw=len(raw)):
            frame = self.reader.generate_frame(raw)
            blocklist: list[str] = []
            result = self.result_features
            if self._raw_feature_filter is not None:
                frame, blocklist = self._raw_feature_filter.filter_frame(
                    frame, raw)
                filter_results = self._raw_feature_filter.results
                if blocklist:
                    result = _apply_blocklist(result, set(blocklist))
                    if not result:
                        raise ValueError(
                            "RawFeatureFilter blocked every path to the "
                            f"result features (blocklist: {blocklist})")
                    raw = [f for f in raw if f.name not in set(blocklist)]
            # ALWAYS replace workflow-applied per-key map exclusions —
            # a filterless retrain must clear a previous filtered run's
            # exclusions, not silently keep dropping healthy keys
            self._apply_map_key_blocklist(
                result, filter_results.map_key_blocklist
                if filter_results is not None else {})
        data = PipelineData.from_host(frame)
        from transmogrifai_tpu.ingest_fusion import frame_cache_enabled
        if frame_cache_enabled():
            data = self._frame_cache.adopt(frame, data)
        executor = DagExecutor()
        ckpt = None
        ckpt_overrides: dict[str, Any] = {}
        full_dag = compute_dag(result)
        if checkpoint_dir:
            from transmogrifai_tpu.checkpoint import (
                TrainCheckpoint, train_fingerprint,
            )
            from transmogrifai_tpu.selector.model_selector import (
                ModelSelector,
            )
            ckpt = TrainCheckpoint(
                checkpoint_dir,
                train_fingerprint(full_dag, frame.n_rows,
                                  [f.name for f in raw]))
            ckpt_overrides = ckpt.restore_overrides(full_dag)
            # compose with the sweep checkpoint: a mid-CV crash resumes
            # both the fitted before-DAG layers AND the partially-done
            # sweep from the same directory. Patched selectors are
            # restored after training — the directory belongs to THIS
            # train call, not to the selector (a later train() with a
            # different/no checkpoint_dir must not keep using it)
            patched_selectors = [
                s for layer in full_dag for s in layer
                if isinstance(s, ModelSelector) and s.checkpoint_dir is None]
            for s in patched_selectors:
                s.checkpoint_dir = checkpoint_dir
        else:
            patched_selectors = []
        cut = None
        if self._workflow_cv:
            from transmogrifai_tpu.dag import cut_dag
            cut = cut_dag(result)
            if cut.selector is None or not cut.during:
                cut = None  # nothing label-dependent to protect: plain fit
            elif cut.selector.get_output().uid in {
                    **self._model_stage_overrides, **ckpt_overrides}:
                # the selector itself is already fitted (with_model_stages
                # or a train checkpoint): nothing to sweep, the plain path
                # reuses it as-is
                cut = None
        try:
            if cut is not None:
                # the selector was NOT restored, so CV will actually run:
                # checkpoint-restored during-DAG stages must NOT be
                # substituted — they were fitted on the FULL training data
                # (saved after a completed sweep), and replaying them here
                # would disable the per-fold refit that keeps label
                # information out of fold validation features. They refit
                # per fold as CV requires; the checkpoint entries only
                # replay once the selector itself is restored (cut=None).
                during_uids = {s.get_output().uid
                               for layer in cut.during for s in layer}
                cv_overrides = {k: v for k, v in ckpt_overrides.items()
                                if k not in during_uids}
                cut.before = self._substitute_fitted(cut.before,
                                                     cv_overrides)
                cut.during = self._substitute_fitted(cut.during,
                                                     cv_overrides)
                cut.after = self._substitute_fitted(cut.after,
                                                    cv_overrides)
                fitted = self._fit_workflow_cv(data, cut, executor, ckpt)
            else:
                dag = self._substitute_fitted(full_dag, ckpt_overrides)
                with profiler.phase(OpStep.FEATURE_ENGINEERING):
                    _, fitted = self._fit_layers(executor, data, dag, ckpt)
        finally:
            for s in patched_selectors:
                s.checkpoint_dir = None
        model = WorkflowModel(
            result_features=result,
            raw_features=raw, dag=fitted, executor=executor,
            blocklisted=blocklist,
            label_distribution=_label_distribution(frame, raw),
            raw_filter_results=filter_results)
        # the model scores through the same device-frame cache: a
        # train-then-score session over the training frame (holdout
        # evaluation, insights) never re-uploads identical host columns
        model._frame_cache = self._frame_cache
        return model

    @staticmethod
    def _apply_map_key_blocklist(result, map_key_blocklist: dict) -> None:
        """Reference ``OpWorkflow.scala:118-167`` setBlocklist per-key map
        exclusions: rewire every map vectorizer consuming a flagged map
        feature so the excluded keys never expand into columns.

        Workflow-applied exclusions are REPLACED per train(), never
        accumulated: they live in the stage's separate
        ``wf_block_keys_by_feature`` dict (consulted alongside the
        user-owned ``block_keys_by_feature``, which is never touched), so
        keys that are healthy again on refreshed data come back while user
        config — including edits between trains — is always preserved."""
        from transmogrifai_tpu.ops.vectorizers.maps import _MapVectorizerBase
        stages = {s for f in result for s in f.parent_stages()}
        for stage in stages:
            if not isinstance(stage, _MapVectorizerBase):
                continue
            stage.wf_block_keys_by_feature = {
                name: tuple(sorted(map_key_blocklist[name]))
                for name in stage.input_names
                if map_key_blocklist.get(name)}

    @staticmethod
    def _fit_layers(executor: DagExecutor, data: PipelineData, dag: Dag,
                    ckpt=None, layer_offset: int = 0
                    ) -> tuple[PipelineData, Dag]:
        """Layer-at-a-time ``fit_transform`` with resume accounting and
        per-layer checkpointing. A layer whose estimators were all replaced
        by checkpoint-restored models counts as resumed (replayed, not
        refit); every other completed layer is fitted and — when a
        checkpoint is active — persisted before the next layer starts, so
        a crash loses at most the in-flight layer. ``fault_point
        ("train.layer")`` fires at each layer start: the deterministic
        preemption site the chaos suite kills training at.

        Note on FE fusion (round 14): this loop deliberately feeds
        ``fit_transform`` ONE layer at a time — the per-layer fault-point
        and checkpoint granularity is the chaos/resume contract — so
        cross-layer fusion here is bounded to within a layer. The
        multi-layer fused programs fire where whole fitted DAGs replay:
        ``executor.transform`` (scoring, CV validation transforms) and
        the selector's per-fold during-DAG ``fit_transform`` over the
        full multi-layer cut (``fit_with_dag``)."""
        from transmogrifai_tpu.stages.base import Estimator
        from transmogrifai_tpu.utils.faults import fault_point
        from transmogrifai_tpu.utils.profiling import run_counters
        fitted_dag: Dag = []
        for li, layer in enumerate(dag):
            fault_point("train.layer")
            resumed = (not any(isinstance(s, Estimator) for s in layer)
                       and any(getattr(s, "_from_checkpoint", False)
                               for s in layer))
            data, fl = executor.fit_transform(data, [layer])
            fitted_dag.extend(fl)
            if resumed:
                run_counters.layers_resumed += 1
            else:
                run_counters.layers_fitted += 1
                if ckpt is not None:
                    ckpt.save_layer(layer_offset + li, fl[0])
        return data, fitted_dag

    def _fit_workflow_cv(self, data: PipelineData, cut, executor,
                         ckpt=None) -> Dag:
        """Reference ``OpWorkflow.scala:408-449``: fit the pre-CV DAG once,
        run the selector with the in-CV (label-dependent) DAG refit per
        fold, then fit whatever remains downstream. With ``ckpt``, the
        before-DAG layers checkpoint as they complete (the selector's own
        sweep checkpoints through ``sweep.json``), and the full-data-refit
        during layers + selector + tail checkpoint after selection."""
        from transmogrifai_tpu.utils.profiling import OpStep, profiler
        with profiler.phase(OpStep.FEATURE_ENGINEERING):
            data, fitted_before = self._fit_layers(
                executor, data, cut.before, ckpt)
        with profiler.phase(OpStep.CROSS_VALIDATION):
            selected, fitted_during, data = cut.selector.fit_with_dag(
                data, cut.during, executor)
        n_before = len(cut.before)
        if ckpt is not None:
            for i, layer in enumerate(fitted_during):
                ckpt.save_layer(n_before + i, layer)
        with profiler.phase(OpStep.FEATURE_ENGINEERING):
            _, fitted_tail = self._fit_layers(
                executor, data, [[selected]] + cut.after, ckpt,
                layer_offset=n_before + len(fitted_during))
        return fitted_before + fitted_during + fitted_tail


class WorkflowModel:
    def __init__(self, result_features: Sequence[FeatureLike],
                 raw_features: Sequence[FeatureLike], dag: Dag,
                 executor: Optional[DagExecutor] = None,
                 blocklisted: Sequence[str] = (),
                 label_distribution: Optional[dict] = None,
                 raw_filter_results=None):
        self.result_features = tuple(result_features)
        self.raw_features = list(raw_features)
        self.dag = dag
        self.executor = executor or DagExecutor()
        self.blocklisted = list(blocklisted)
        #: bounded-bin label histogram captured at train time (ModelInsights)
        self.label_distribution = label_distribution
        #: RawFeatureFilterResults (or None) — exclusion reasons incl.
        #: per-key map blocklists, surfaced in summary/ModelInsights
        self.raw_filter_results = raw_filter_results
        #: device-frame cache shared from the training Workflow (or a
        #: fresh one for loaded models): identical host frames skip the
        #: host->device re-transfer at scoring time
        from transmogrifai_tpu.ingest_fusion import DeviceFrameCache
        self._frame_cache = DeviceFrameCache()

    # -- scoring -------------------------------------------------------------
    def _ingest_frame(self, reader_or_frame) -> fr.HostFrame:
        """HOST half of ingest: raw-feature frame generation only (no jax
        work) — safe to run on the streaming prefetch thread while the
        device executes the previous batch's FE program."""
        if isinstance(reader_or_frame, fr.HostFrame):
            reader: DataReader = CustomReader(frame=reader_or_frame)
        else:
            reader = reader_or_frame
        available = reader.available_columns()
        raw = list(self.raw_features)
        if available is not None:
            # The name-presence guard applies to features read by COLUMN
            # NAME. A predictor with a custom extract_fn computes its value
            # from the whole record, so its name is not a source column by
            # design (reference FeatureGeneratorStage) — exempt, UNLESS the
            # data is a bare frame (columns are all there is to extract
            # from). Responses stay name-ruled by default: they are
            # optional at scoring time and an extractor run against
            # label-less records would crash scoring that should work —
            # EXCEPT an extractor-backed response the caller explicitly
            # requested as a result feature (reference aggregate readers
            # compute response windows at score time on request,
            # JoinsAndAggregates.scala), which must run to be returned.
            frame_backed = isinstance(reader, CustomReader) \
                and reader.frame is not None
            requested = {f.name for f in self.result_features}

            def column_read(f) -> bool:
                if frame_backed:
                    return True
                if getattr(f.origin_stage, "extract_fn", None) is None:
                    return True
                return f.is_response and f.name not in requested

            missing_required = sorted(
                f.name for f in raw
                if not f.is_response and column_read(f)
                and f.name not in available)
            if missing_required:
                raise KeyError(
                    f"Scoring data lacks predictor columns {missing_required}")
            raw = [f for f in raw
                   if not column_read(f) or f.name in available]
        return reader.generate_frame(raw)

    def _ingest(self, reader_or_frame) -> PipelineData:
        return self._wrap_frame(self._ingest_frame(reader_or_frame))

    def _wrap_frame(self, frame: fr.HostFrame) -> PipelineData:
        """DEVICE half of ingest: wrap a generated host frame, consulting
        the device-frame cache so identical host columns reuse their
        resident device arrays. Scoring consults via the O(columns)
        identity memo only (``register=False``): the train-then-score
        flow hits (the training frame's column objects are registered at
        ``train()``), while a stream of distinct micro-batches never pays
        the O(rows) content hash."""
        from transmogrifai_tpu.ingest_fusion import frame_cache_enabled
        data = PipelineData.from_host(frame)
        if frame_cache_enabled():
            data = self._frame_cache.adopt(frame, data, register=False)
        return data

    def transform(self, reader_or_frame) -> PipelineData:
        from transmogrifai_tpu.utils.tracing import span
        with span("workflow.ingest",
                  reader=type(reader_or_frame).__name__):
            data = self._ingest(reader_or_frame)
        with span("workflow.transform", n_layers=len(self.dag)):
            return self.executor.transform(data, self.dag)

    def score(self, reader_or_frame, keep_raw_features: bool = False,
              keep_intermediate_features: bool = False) -> fr.HostFrame:
        """Run the fitted DAG; returns a host frame of result features
        (+ key), optionally with raw/intermediate columns."""
        data = self.transform(reader_or_frame)
        return self._score_frame(data, keep_raw_features,
                                 keep_intermediate_features)

    def _score_frame(self, data, keep_raw_features: bool = False,
                     keep_intermediate_features: bool = False) -> fr.HostFrame:
        names = [f.name for f in self.result_features]
        if keep_raw_features:
            names = [f.name for f in self.raw_features
                     if data.has(f.name)] + names
        if keep_intermediate_features:
            names = [n for n in list(data.host.names()) + list(data.device)
                     if n not in names] + names
        cols = {n: data.host_col(n) for n in dict.fromkeys(names)}
        return fr.HostFrame(cols, data.host.key)

    def evaluate(self, reader_or_frame, evaluator,
                 label: Optional[FeatureLike] = None,
                 prediction: Optional[FeatureLike] = None):
        data = self.transform(reader_or_frame)
        return self._evaluate_data(data, evaluator, label, prediction)

    def _evaluate_data(self, data, evaluator,
                       label: Optional[FeatureLike] = None,
                       prediction: Optional[FeatureLike] = None):
        pred_f = prediction or self._prediction_feature()
        label_f = label or self._label_feature(pred_f)
        return evaluator.evaluate(data, label_f.name, pred_f.name)

    def score_and_evaluate(self, reader_or_frame, evaluator, **kw):
        data = self.transform(reader_or_frame)
        return (self._score_frame(data, **kw),
                self._evaluate_data(data, evaluator))

    def _prediction_feature(self) -> FeatureLike:
        preds = [f for f in self.result_features
                 if issubclass(f.ftype, ft.Prediction)]
        if not preds:
            raise ValueError("No Prediction-typed result feature")
        return preds[0]

    def _label_feature(self, pred_f: FeatureLike) -> FeatureLike:
        for p in pred_f.origin_stage.input_features:
            if p.is_response:
                return p
        resp = [f for f in self.raw_features if f.is_response]
        if resp:
            return resp[0]
        raise ValueError("No response feature found for evaluation")

    # -- introspection -------------------------------------------------------
    def stages(self) -> list:
        return [t for layer in self.dag for t in layer]

    def selector_summary(self):
        for t in self.stages():
            if isinstance(t, SelectedModel) and t.summary is not None:
                return t.summary
        return None

    def summary_json(self) -> dict:
        from transmogrifai_tpu.utils.version import VersionInfo
        s = self.selector_summary()
        out = {
            "versionInfo": VersionInfo.to_json(),
            "resultFeatures": [f.name for f in self.result_features],
            "rawFeatures": [f.name for f in self.raw_features],
            "blocklistedFeatures": self.blocklisted,
            "stages": [{"uid": t.uid, "operation": t.operation_name}
                       for t in self.stages()],
        }
        if s is not None:
            out["selectedModel"] = s.to_json()
        if self.raw_filter_results is not None:
            out["rawFeatureFilterResults"] = self.raw_filter_results.to_json()
        return out

    def summary_pretty(self) -> str:
        s = self.selector_summary()
        lines = [f"Fitted workflow with {len(self.stages())} stages"]
        if s:
            lines.append(f"Selected model: {s.best_model_name} "
                         f"({s.validation_metric}={_best_metric(s):.4f} "
                         f"over {s.validation_type})")
            for name, m in (s.holdout_evaluation or {}).items():
                lines.append(f"Holdout [{name}]: " + ", ".join(
                    f"{k}={v:.4f}" for k, v in m.items()
                    if isinstance(v, (int, float))))
        return "\n".join(lines)

    def summary(self) -> str:
        return json.dumps(self.summary_json(), indent=2, default=str)

    def model_insights(self, prediction: Optional[FeatureLike] = None):
        """Merged explainability report (reference modelInsights(feature))."""
        from transmogrifai_tpu.insights.model_insights import ModelInsights
        return ModelInsights.from_workflow(self, prediction)

    def record_insights(self, reader_or_frame, top_k: int = 20):
        """Per-row LOCO insights for the scored data (reference
        RecordInsightsLOCO applied to the model's feature vector)."""
        from transmogrifai_tpu.insights.loco import RecordInsightsLOCO
        pred_f = self._prediction_feature()
        sel = pred_f.origin_stage
        feat_name = None
        for t in self.stages():
            if t.get_output() == pred_f:
                feat_name = t.runtime_input_names()[-1]
                model = t
        data = self.transform(reader_or_frame)
        loco = RecordInsightsLOCO(model=model, top_k=top_k)
        col = data.host_col(feat_name)
        return loco.host_apply(col).values

    def compute_data_up_to(self, feature: FeatureLike,
                           reader_or_frame) -> fr.HostFrame:
        """Materialize data through the FITTED stages up to ``feature``
        (reference ``OpWorkflowModel.computeDataUpTo``). Returns every
        feature generated along the way (raws + intermediates + target)."""
        data = self._ingest(reader_or_frame)
        # fitted models carry their own uids; ancestry matches on the
        # output feature nodes, which fit() shares with the estimators
        needed_outputs = {s.get_output().uid
                          for s in feature.parent_stages()} | {feature.uid}
        dag = [[t for t in layer if t.get_output().uid in needed_outputs]
               for layer in self.dag]
        dag = [l for l in dag if l]
        if not feature.is_raw and not any(
                t.get_output().uid == feature.uid
                for layer in dag for t in layer):
            raise KeyError(
                f"Feature {feature.name!r} is not produced by this fitted "
                "model's DAG")
        data = self.executor.transform(data, dag)
        return _frame_up_to(data, feature.raw_features(), dag)

    def score_stream(self, streaming_reader, write_batch=None):
        """Micro-batch continuous scoring (reference StreamingScore): yields
        one scored HostFrame per batch from the streaming reader."""
        from transmogrifai_tpu.readers.streaming import stream_score
        return stream_score(self, streaming_reader, write_batch)

    # -- persistence ---------------------------------------------------------
    def save(self, path: str, overwrite: bool = True) -> None:
        from transmogrifai_tpu.serialization import save_model
        save_model(self, path, overwrite=overwrite)

    # -- local serving -------------------------------------------------------
    def score_function(self, strict: bool = False):
        from transmogrifai_tpu.local.scoring import make_score_function
        return make_score_function(self, strict=strict)

    def serving_server(self, **kw):
        """Online micro-batched scoring server over the compiled DAG
        (``serving/``): ``submit(row) -> Future``, backpressure, graceful
        degradation to the row path. See ``docs/SERVING.md``."""
        from transmogrifai_tpu.serving import ScoringServer
        return ScoringServer(self, **kw)


def _frame_up_to(data, raw_features, dag) -> fr.HostFrame:
    """Raws + every stage output materialized by ``dag``, as a HostFrame."""
    names = [f.name for f in raw_features] + \
        [s.get_output().name for layer in dag for s in layer]
    cols = {n: data.host_col(n) for n in dict.fromkeys(names)
            if data.has(n)}
    return fr.HostFrame(cols, data.host.key)


def _label_distribution(frame: fr.HostFrame, raw_features) -> Optional[dict]:
    """Bounded-memory label histogram (reference: StreamingHistogram fed by
    the regression label; here for any numeric response)."""
    from transmogrifai_tpu.utils.streaming_histogram import StreamingHistogram

    for f in raw_features:
        if not f.is_response or f.name not in frame.columns:
            continue
        col = frame.columns[f.name]
        try:
            vals = np.asarray(col.values, np.float64)
        except (TypeError, ValueError):
            return None
        mask = getattr(col, "mask", None)
        if mask is not None:
            vals = vals[np.asarray(mask, bool)]
        h = StreamingHistogram(max_bins=100).update_all(vals)
        d = h.to_json()
        d["name"] = f.name
        d["count"] = int(np.isfinite(vals).sum())
        if d["count"]:
            d["mean"] = float(np.nanmean(vals))
            d["min"] = float(np.nanmin(vals))
            d["max"] = float(np.nanmax(vals))
        return d
    return None


def _apply_blocklist(result_features: Sequence[FeatureLike],
                     blocked: set[str]) -> tuple[FeatureLike, ...]:
    """Rewire the DAG dropping blocklisted raw features (reference
    ``OpWorkflow.setBlocklist:118-167`` semantics): variadic stages lose the
    blocked inputs; fixed-arity stages with a blocked input become blocked
    themselves and the block propagates to their consumers. Mutates stage
    wiring in place (the pre-training graph is the only owner)."""
    blocked_uids: set[str] = set()

    def is_blocked(f: FeatureLike) -> bool:
        return (f.is_raw and f.name in blocked) or f.uid in blocked_uids

    for layer in compute_dag(result_features):
        for stage in layer:
            new_in = tuple(p for p in stage.input_features if not is_blocked(p))
            if len(new_in) == len(stage.input_features):
                continue
            min_arity = len(stage.in_types) if not stage.variadic \
                else len(stage.in_types)  # variadic: fixed prefix + >=1
            ok = (stage.variadic and len(new_in) >= min_arity) or \
                 (not stage.variadic and len(new_in) == len(stage.in_types))
            if ok:
                stage._inputs = new_in
                out = stage._output
                if out is not None:
                    out._parents = new_in
            else:
                blocked_uids.add(stage.get_output().uid)
    return tuple(f for f in result_features if not is_blocked(f))


def _best_metric(s) -> float:
    for r in s.validation_results:
        if r.model_name == s.best_model_name:
            return float(r.metric_values.get(s.validation_metric, float("nan")))
    return float("nan")


def load_model(path: str) -> WorkflowModel:
    from transmogrifai_tpu.serialization import load_model as _load
    return _load(path)


# attach for API parity: Workflow.load_model(path)
Workflow.load_model = staticmethod(load_model)
