"""Compiled online batch scorer over a fitted workflow's device DAG.

The offline path (``WorkflowModel.score``) jit-compiles one fused program
per DAG layer keyed by input shapes — fine when a job scores one big frame,
fatal for online serving where every request batch has a different row
count and a text column's batch-local dictionary (``dict_encode``) changes
the jit cache key on every distinct batch. ``CompiledScorer`` makes the
compiled path servable:

- **padding buckets**: batches pad (by replicating the last row) up to the
  next power-of-two bucket ``<= max_batch``, so the whole serving lifetime
  touches at most ``log2(max_batch / min_bucket) + 1`` shapes per layer —
  a bounded compile cache by construction. ``warmup()`` pre-dispatches
  every bucket so steady-state traffic never compiles (asserted via the
  scorer's per-instance ``utils.profiling.ServingCounters``).
- **frozen text vocab**: text-ish columns consumed by device stages encode
  against a per-column vocabulary frozen at scorer construction (seeded
  from the fitted stages' category sets, e.g. ``OneHotModel.categories``,
  plus an unknown sentinel). Unseen strings map to the sentinel, which no
  fitted category table contains, so they land in the OTHER/unseen slot —
  exactly the row path's semantics for an unseen value — while the jit
  cache key (vocab is static aux data) stays constant.
- **donated input buffers**: on accelerator backends, per-batch input
  uploads whose last consumer is a layer are donated to that layer's
  program (``dag.fuse_layer_program(donate=True)``), so a request batch
  holds ~1x its memory on device instead of accumulating dead columns.

Row parity: ``score_batch(rows)`` == ``make_score_function(model)(row)``
per row (up to f32 device math), asserted in tests/test_serving.py.
"""

from __future__ import annotations

import threading
from typing import Any, Optional, Sequence

import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.dag import fuse_layer_program
from transmogrifai_tpu.pipeline_data import PipelineData
from transmogrifai_tpu.serving import wireformat as wf
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.utils.precision import (
    PRECISION_BYTE_FACTOR, normalize_precision, params_nbytes,
)
from transmogrifai_tpu.utils.profiling import ServingCounters

__all__ = ["CompiledScorer", "UNKNOWN_TOKEN", "rung_of_layer_key"]


def rung_of_layer_key(lk) -> str:
    """The precision rung a (private or shared) program-layer key belongs
    to. Key shapes: ``li`` (int, f32 scoring) | ``(precision, li)``
    (variant scoring) | ``("explain", li, chunk)`` (f32 explain) |
    ``("explain", li, chunk, precision)`` (variant explain)."""
    if not isinstance(lk, tuple):
        return "f32"
    if lk[:1] == ("explain",):
        return lk[3] if len(lk) > 3 else "f32"
    return lk[0]

#: sentinel appended to every frozen serving vocab; never a fitted category,
#: so downstream static tables route it to their OTHER/unseen slot
UNKNOWN_TOKEN = "⟨serving-unknown⟩"


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _prediction_rows(col: fr.PredictionColumn, n: int) -> list[dict]:
    """Bulk ``PredictionColumn -> [{prediction, rawPrediction_i,
    probability_i}]`` matching ``ft.Prediction.make(...).value`` exactly."""
    def as_2d(a):
        a = np.asarray(a, np.float64)  # precision-ok: post-program JSON boxing
        return a.reshape(a.shape[0], -1)[:n]

    pred = np.asarray(col.prediction, np.float64)[:n].tolist()  # precision-ok: post-program JSON boxing
    raw = as_2d(col.raw_prediction)
    prob = as_2d(col.probability)
    raw_keys = [f"{ft.Prediction.RawPredictionName}_{i}"
                for i in range(raw.shape[1])]
    prob_keys = [f"{ft.Prediction.ProbabilityName}_{i}"
                 for i in range(prob.shape[1])]
    raw_l, prob_l = raw.tolist(), prob.tolist()
    out = []
    for i in range(n):
        d = {ft.Prediction.PredictionName: pred[i]}
        d.update(zip(raw_keys, raw_l[i]))
        d.update(zip(prob_keys, prob_l[i]))
        out.append(d)
    return out


class CompiledScorer:
    """Jitted columnar batch scorer for a fitted ``WorkflowModel``.

    ``score_batch(rows) -> list[dict]`` where rows/results use the local
    row-path contract ({raw feature name: python value} in, {result feature
    name: python value} out). Thread-safe for one concurrent dispatcher
    (the micro-batcher's worker); construction is cheap, compiles happen
    lazily per bucket (or eagerly via ``warmup``).
    """

    def __init__(self, model, max_batch: int = 256, min_bucket: int = 8,
                 donate: Optional[bool] = None,
                 counters: Optional[ServingCounters] = None,
                 program_cache=None, fingerprint: Optional[str] = None,
                 precision: str = "f32"):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.model = model
        #: ACTIVE precision-ladder rung — the rung steady-state dispatches
        #: run at. The default f32 rung is byte-identical to the
        #: pre-ladder scorer (keys, programs, params untouched). The
        #: server owns rung transitions (gated promotion / pressure
        #: demotion) via ``set_precision`` on its dispatcher thread.
        self.precision = normalize_precision(precision)
        #: per-scorer compile/dispatch attribution: THIS scorer's snapshot
        #: must not include other servers' compiles
        self.counters = counters if counters is not None else \
            ServingCounters()
        #: shared cross-model cache seam (serving/fleet.ProgramCache): when
        #: set, fused layer programs are held per (model fingerprint,
        #: layer, padding bucket) in the SHARED LRU instead of this
        #: scorer's private dict — two scorers over byte-identical fitted
        #: models (same checkpoint dir) share compiled entries, while the
        #: fingerprint keeps schema-identical-but-differently-fitted
        #: models from ever colliding. Insertions/evictions are attributed
        #: to this scorer's ``counters`` by the cache.
        self.program_cache = program_cache
        if program_cache is not None and fingerprint is None:
            from transmogrifai_tpu.checkpoint import model_fingerprint
            fingerprint = model_fingerprint(model=model)
        self.fingerprint = fingerprint
        self.max_batch = int(max_batch)
        min_bucket = max(1, min(int(min_bucket), self.max_batch))
        self.buckets: list[int] = []
        b = _next_pow2(min_bucket)
        while b < self.max_batch:
            self.buckets.append(b)
            b <<= 1
        self.buckets.append(self.max_batch)
        if donate is None:
            import jax
            donate = jax.default_backend() != "cpu"
        self.donate = bool(donate)

        self._result = [(f.name, f.ftype) for f in model.result_features]
        #: per layer: (host transformers, device transformers)
        self._layers = [
            ([t for t in layer if not t.is_device],
             [t for t in layer if t.is_device])
            for layer in model.dag]
        # Raw columns the fitted DAG actually reads at transform time
        # (prediction models exclude their label input from
        # runtime_input_names, so label-less requests serve fine). A
        # response raw that IS consumed (e.g. a label indexer feeding the
        # prediction's lineage) builds as its nearest nullable base type:
        # requests legitimately omit the label, and RealNN would reject the
        # resulting Nones.
        runtime_needed = {n for layer in model.dag for t in layer
                          for n in t.runtime_input_names()}
        runtime_needed.update(n for n, _ in self._result)
        self._raw = []
        for f in model.raw_features:
            if f.name not in runtime_needed:
                continue
            ftype = f.ftype
            if f.is_response:
                ftype = ft.nullable_base(ftype)
            self._raw.append((f.name, ftype))
        #: private fused programs: layer index ``li`` for the f32 rung
        #: (pre-ladder key, unchanged), ``(precision, li)`` for variants
        self._programs: dict[Any, Any] = {}
        #: memoized per-(stage uid, rung) quantized/specialized params —
        #: quantization is host-side work that must not run per dispatch
        self._qparams: dict[tuple, Any] = {}
        #: warmup-only program cost analysis (utils/devicewatch.py):
        #: lowering re-traces on host, so it runs once per (layer,
        #: bucket) during warmup and NEVER on the steady-state path
        self._analyze_cold = False
        self._analyzed: set = set()
        self._vocabs: dict[str, tuple[tuple[str, ...], dict]] = {}
        self._vocab_lock = threading.Lock()
        self._seed_vocabs()
        self._free_plan = self._plan_last_uses()

    # -- static plans --------------------------------------------------------
    def _seed_vocabs(self) -> None:
        """Freeze a serving vocabulary for every text column a device stage
        consumes, from the fitted category sets of its consumers. Columns
        with no introspectable categories freeze on first sight instead
        (``_encode_text``)."""
        cats_by_col: dict[str, set] = {}
        for _, dev_ts in self._layers:
            for t in dev_ts:
                cats = getattr(t, "categories", None)
                if not cats:
                    continue
                names = t.runtime_input_names()
                if len(cats) != len(names):
                    continue
                for name, cs in zip(names, cats):
                    cats_by_col.setdefault(name, set()).update(
                        str(c) for c in cs)
        for name, cs in cats_by_col.items():
            self._freeze_vocab(name, sorted(cs))

    def _freeze_vocab(self, name: str, values: Sequence[str]) -> None:
        vocab = tuple(values) + (UNKNOWN_TOKEN,)
        self._vocabs[name] = (vocab, {v: i for i, v in enumerate(vocab)})

    def _plan_last_uses(self) -> list[list[str]]:
        """Per layer: input column names whose LAST consumer is that layer's
        device program and which no later layer, host pull, or result
        extraction rereads — the donation/free set."""
        keep_after: list[set] = []
        needed = {name for name, _ in self._result}
        for host_ts, dev_ts in reversed(self._layers):
            keep_after.insert(0, set(needed))
            for t in host_ts + dev_ts:
                needed.update(t.runtime_input_names())
        plan: list[list[str]] = []
        for (host_ts, dev_ts), keep in zip(self._layers, keep_after):
            ins = {n for t in dev_ts for n in t.runtime_input_names()}
            plan.append(sorted(ins - keep))
        return plan

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_batch

    def shed_largest_bucket(self) -> Optional[int]:
        """Degradation-ladder rung (utils/resources.py): drop the largest
        padding bucket so every future batch pads (and splits) to smaller
        shapes — the serving analog of the sweep's lane-chunk halving.
        Called by the server's OOM handler on the dispatcher thread (the
        only mutator of ``buckets``/``max_batch``). Shared-cache entries
        for the shed bucket are evicted so their accounted HBM is
        actually released; the private-dict jit caches keep their (now
        never-dispatched) traces — an accounting estimate, like every
        HBM guard here. Returns the shed bucket, or None when only one
        bucket remains (the floor: below it the row path serves)."""
        if len(self.buckets) <= 1:
            return None
        shed = self.buckets.pop()
        self.max_batch = self.buckets[-1]
        if self.program_cache is not None:
            self.program_cache.evict_bucket(self.fingerprint, shed)
        return shed

    def set_precision(self, precision: str) -> str:
        """Switch the active ladder rung. Programs/params for the new rung
        build lazily on the next dispatch (or eagerly if ``warmup`` warmed
        the rung); the old rung's programs stay cached so a fallback to
        f32 after a rejected promotion re-dispatches without a compile.
        Returns the previous rung. Caller is the server's dispatcher
        thread (the only mutator, like ``buckets``)."""
        prev, self.precision = self.precision, normalize_precision(precision)
        return prev

    def evict_precision(self, precision: str) -> int:
        """Drop every compiled entry of one rung (all buckets, scoring
        AND explain) so its accounted HBM actually releases — the
        demotion rung's analog of ``shed_largest_bucket``'s eviction.
        Returns the number of entries evicted (shared cache) or private
        programs dropped."""
        precision = normalize_precision(precision)
        if self.program_cache is not None:
            return self.program_cache.evict_matching(
                lambda k: isinstance(k, tuple) and len(k) == 3
                and k[0] == self.fingerprint
                and rung_of_layer_key(k[1]) == precision)
        stale = [k for k in self._programs
                 if rung_of_layer_key(k) == precision]
        for k in stale:
            self._programs.pop(k, None)
        return len(stale)

    def _params_for(self, dev_ts, precision: str) -> dict:
        """Per-stage params pytree for a rung. The f32 master rung calls
        ``device_params()`` fresh per dispatch, exactly like the
        pre-ladder path. Non-f32 rungs memoize the (possibly quantized)
        tree per (stage uid, rung): ``quantize_device_params`` does
        host-side weight quantization that must not rerun per batch."""
        if precision == "f32":
            return {t.uid: t.device_params() for t in dev_ts}
        params = {}
        for t in dev_ts:
            key = (t.uid, precision)
            p = self._qparams.get(key)
            if p is None and key not in self._qparams:
                p = t.quantize_device_params(precision)
                if p is None:
                    p = t.device_params()
                self._qparams[key] = p
            params[t.uid] = p
        return params

    # -- encoding ------------------------------------------------------------
    def _encode_text(self, name: str, col: fr.HostColumn) -> fr.CodesColumn:
        import jax.numpy as jnp
        entry = self._vocabs.get(name)
        if entry is None:
            with self._vocab_lock:
                entry = self._vocabs.get(name)
                if entry is None:
                    # no fitted categories to seed from: freeze on the first
                    # batch's values — later unseen values map to the
                    # sentinel (OTHER semantics), the cache key stays fixed
                    seen = sorted({str(v) for v in col.values
                                   if v is not None})
                    self._freeze_vocab(name, seen)
                    entry = self._vocabs[name]
        vocab, index = entry
        unk = len(vocab) - 1
        codes = np.fromiter(
            (-1 if v is None else index.get(v, unk) for v in col.values),
            dtype=np.int32, count=len(col.values))
        return fr.CodesColumn(jnp.asarray(codes), vocab)

    def _device_input(self, data: PipelineData, name: str):
        if name in data.device:
            return data.device[name]
        if name in data.host and data.host[name].kind in fr.TEXT_KINDS:
            col = self._encode_text(name, data.host[name])
            data.device[name] = col
            return col
        return data.device_col(name)

    # -- scoring -------------------------------------------------------------
    def warmup(self, row: dict, buckets: Optional[Sequence[int]] = None,
               precisions: Optional[Sequence[str]] = None) -> list[int]:
        """Dispatch one replicated batch per padding bucket (per ladder
        rung in ``precisions``, default the active rung only) so every
        fused layer program is compiled before traffic arrives. Returns
        the buckets warmed. Compiles triggered here attribute to the
        ``serving.bucket_<n>`` site of the devicewatch compile telemetry
        (non-f32 rungs suffix the rung name), and each (layer, bucket,
        rung) program gets a one-time cost analysis (FLOPs / bytes / HLO
        size) — warmup is the cold seam, so the steady-state dispatch
        path pays nothing for either. Warming every rung a server may
        promote/demote to is what makes rung transitions compile-free:
        0 post-warmup compiles per (bucket, precision)."""
        from transmogrifai_tpu.utils.devicewatch import compile_telemetry
        warmed = []
        self._analyze_cold = True
        try:
            for p in (precisions if precisions is not None
                      else (self.precision,)):
                p = normalize_precision(p)
                site_suffix = "" if p == "f32" else f"_{p}"
                for b in (buckets if buckets is not None else self.buckets):
                    with compile_telemetry.building(
                            f"serving.bucket_{b}{site_suffix}"):
                        self.score_batch([dict(row)] * int(b), precision=p)
                    if int(b) not in warmed:
                        warmed.append(int(b))
        finally:
            self._analyze_cold = False
        return warmed

    def score_batch(self, rows: Sequence[dict],
                    precision: Optional[str] = None) -> list[dict]:
        rows = list(rows)
        if not rows:
            return []
        if len(rows) > self.max_batch:
            out: list[dict] = []
            for i in range(0, len(rows), self.max_batch):
                out.extend(self.score_batch(rows[i:i + self.max_batch],
                                            precision=precision))
            return out
        n = len(rows)
        bucket = self.bucket_for(n)
        # pad by replicating the last row: all transforms are row-local at
        # scoring time, so padded slots compute real (discarded) values and
        # can never poison statistics (there are none in a fitted DAG)
        padded = rows + [rows[-1]] * (bucket - n)
        cols = {name: fr.HostColumn.from_values(
                    ftype, [r.get(name) for r in padded])
                for name, ftype in self._raw}
        data = self._transform_counted(
            PipelineData(fr.HostFrame(cols)), bucket, precision)
        return self._extract_rows(data, n)

    def _transform_counted(self, data: PipelineData, bucket: int,
                           precision: Optional[str] = None) -> PipelineData:
        """``_transform`` plus per-dispatch compile accounting — shared
        by the row entry (``score_batch``) and the columnar entry
        (``score_columns``). ``precision=None`` dispatches at the active
        rung; the server's promotion gate passes an explicit rung to
        shadow-score a candidate without touching the live one."""
        precision = self.precision if precision is None \
            else normalize_precision(precision)
        if self.program_cache is not None:
            # shared-cache mode: one program per (fingerprint, layer,
            # bucket) key, so an insertion IS a compile (the entry's one
            # shape traces on first dispatch) — the cache attributes
            # insertions/evictions to this scorer's counters directly
            data = self._transform(data, bucket, precision)
            self.counters.count(bucket, dispatches=1)
            return data
        # compile accounting via this scorer's OWN fused-program
        # jit-cache growth: exact and per-scorer (a process-global
        # compile listener would cross-attribute concurrent servers)
        before = self._program_cache_entries()
        data = self._transform(data, bucket, precision)
        grew = self._program_cache_entries() - before
        self.counters.count(bucket, dispatches=1, compiles=grew)
        if grew:
            # cold path only: steady-state traffic never gets here —
            # a compile event under load is the flight-recorder
            # symptom of a bucket/cache misconfiguration
            from transmogrifai_tpu.utils.events import events
            events.emit("serving.compile", bucket=bucket,
                        programs=grew, precision=precision,
                        fingerprint=self.fingerprint)
        return data

    # -- columnar (wire-frame) scoring ---------------------------------------
    def host_columns_from_wire(self, frame: "wf.WireFrame"
                               ) -> tuple[dict, int]:
        """Decoded request frame -> ``{name: HostColumn}`` for every raw
        feature the DAG reads, bypassing the per-row dict walk AND the
        per-cell ``ftype._validate`` calls — typed wire buffers land in
        the column representations ``HostColumn.from_values`` would have
        built (SNIPPETS[3]'s pre-partitioned-operand rule at the
        socket). Fixed-width columns stay zero-copy views over the frame
        buffer until padding. Returns ``(cols, n_rows)``.

        A missing required column raises ``KeyError`` (HTTP 400, like a
        strict-admission miss on the row path); a wire dtype the feature
        kind can't accept raises ``WireFormatError``; an empty value in
        a non-nullable column raises ``FeatureTypeValueError`` exactly
        like the row path."""
        n = frame.n_rows
        cols: dict[str, fr.HostColumn] = {}
        for name, ftype in self._raw:
            col = frame.columns.get(name)
            if col is None:
                raise KeyError(
                    f"request frame missing raw feature {name!r}")
            cols[name] = self._host_col_from_wire(name, ftype, col, n)
        return cols, n

    @staticmethod
    def _host_col_from_wire(name: str, ftype, col: "wf.WireColumn",
                            n: int) -> fr.HostColumn:
        kind = ftype.device_kind
        if col.dtype == wf.JSONCOL:
            # escape hatch for any kind: python values through the
            # validating builder (maps, lists, prediction, ...)
            return fr.HostColumn.from_values(ftype, col.values)
        if kind in fr.NUMERIC_KINDS:
            if col.dtype not in (wf.F64, wf.F32, wf.I64, wf.I32,
                                 wf.BOOL):
                raise wf.WireFormatError(
                    f"column {name!r}: dtype {col.dtype} is not "
                    f"numeric (feature kind {kind!r})")
            vals = np.asarray(col.values)
            if vals.ndim != 1:
                raise wf.WireFormatError(
                    f"column {name!r}: width {vals.shape[1]} invalid "
                    f"for a scalar {kind!r} feature")
            mask = np.ones(n, dtype=bool) if col.mask is None \
                else np.asarray(col.mask, dtype=bool)
            if not ftype.is_nullable and not mask.all():
                raise ft.FeatureTypeValueError(
                    f"{ftype.__name__} column contains empty values")
            if not mask.all():
                # missing slots hold 0, matching _build_numeric — fill
                # with the column's OWN dtype so a binary F32/I32 frame
                # never pays a silent f64 upcast (2x host memory) here;
                # the device path casts once, straight to f32
                vals = np.where(mask, vals, vals.dtype.type(0))
            return fr.HostColumn(ftype, vals, mask)
        if kind in fr.TEXT_KINDS:
            if col.dtype != wf.TEXT:
                raise wf.WireFormatError(
                    f"column {name!r}: dtype {col.dtype} is not TEXT "
                    f"(feature kind {kind!r})")
            vals = np.empty(n, dtype=object)
            for i, v in enumerate(col.values):
                vals[i] = v
            return fr.HostColumn(ftype, vals, None)
        if kind == "geolocation":
            if col.dtype not in (wf.F64, wf.F32) \
                    or np.ndim(col.values) != 2 \
                    or col.values.shape[1] != 3:
                raise wf.WireFormatError(
                    f"column {name!r}: geolocation rides as F64 "
                    "width=3 (lat, lon, accuracy)")
            # dtype-preserving: an F32 geolocation block stays f32 on the
            # host (no silent 2x copy); F64 wire data keeps f64
            vals = np.asarray(col.values)
            mask = np.ones(n, dtype=bool) if col.mask is None \
                else np.asarray(col.mask, dtype=bool)
            if not mask.all():
                vals = np.where(mask[:, None], vals, vals.dtype.type(0))
            return fr.HostColumn(ftype, vals, mask)
        if kind == "vector":
            if col.dtype not in (wf.F32, wf.F64) \
                    or np.ndim(col.values) != 2:
                raise wf.WireFormatError(
                    f"column {name!r}: feature vectors ride as F32 "
                    "width=d")
            return fr.HostColumn(
                ftype, np.asarray(col.values, dtype=np.float32), None)
        raise wf.WireFormatError(
            f"column {name!r}: feature kind {kind!r} requires a JSON "
            "wire column")

    @staticmethod
    def _pad_cols(cols: dict, n: int, bucket: int) -> dict:
        """Pad every column to ``bucket`` rows by replicating the last
        row — the array-level analog of ``score_batch``'s row padding
        (transforms are row-local at scoring time, so padded slots
        compute real, discarded values)."""
        if bucket == n:
            return cols
        pad = bucket - n
        out = {}
        for name, col in cols.items():
            vals = np.concatenate(
                [col.values, np.repeat(col.values[-1:], pad, axis=0)])
            mask = None if col.mask is None else np.concatenate(
                [col.mask, np.repeat(col.mask[-1:], pad)])
            out[name] = fr.HostColumn(col.ftype, vals, mask, col.meta)
        return out

    def score_columns(self, cols: dict, n: int,
                      precision: Optional[str] = None) -> dict:
        """Columnar scoring entry: ``{name: HostColumn}`` (every raw
        feature the DAG reads, ``n`` rows each) -> ``{result name:
        ndarray | list}`` with prediction results flattened to dotted
        f64 columns (``{name}.prediction``, ``{name}.rawPrediction_i``,
        ``{name}.probability_i``) — the shape ``wireformat.
        reply_columns`` ships. No row dicts are built in either
        direction; parity with ``score_batch`` is exact (same programs,
        same padding)."""
        if n == 0:
            return {}
        if n > self.max_batch:
            merged: dict = {}
            for i in range(0, n, self.max_batch):
                j = min(i + self.max_batch, n)
                part = self.score_columns(
                    {name: c.take(np.arange(i, j))
                     for name, c in cols.items()}, j - i,
                    precision=precision)
                for k, v in part.items():
                    if k in merged:
                        merged[k] = np.concatenate([merged[k], v]) \
                            if isinstance(v, np.ndarray) \
                            else merged[k] + v
                    else:
                        merged[k] = v
            return merged
        bucket = self.bucket_for(n)
        data = self._transform_counted(
            PipelineData(fr.HostFrame(self._pad_cols(cols, n, bucket))),
            bucket, precision)
        return self._extract_columns(data, n)

    def _extract_columns(self, data: PipelineData, n: int) -> dict:
        """Result columns in columnar form — the framed-reply analog of
        ``_extract_rows`` (one array per column, zero per-cell boxing
        for device results)."""
        out: dict = {}
        for name, ftype in self._result:
            dev = data.device.get(name)
            if isinstance(dev, fr.PredictionColumn):
                out[f"{name}.{ft.Prediction.PredictionName}"] = \
                    np.asarray(dev.prediction, np.float64)[:n]  # precision-ok: post-program reply columns
                for label, block in (
                        (ft.Prediction.RawPredictionName,
                         dev.raw_prediction),
                        (ft.Prediction.ProbabilityName,
                         dev.probability)):
                    arr = np.asarray(block, np.float64)  # precision-ok: post-program reply columns
                    arr = arr.reshape(arr.shape[0], -1)[:n]
                    for i in range(arr.shape[1]):
                        out[f"{name}.{label}_{i}"] = \
                            np.ascontiguousarray(arr[:, i])
            elif isinstance(dev, fr.VectorColumn):
                out[name] = np.asarray(dev.values, np.float64)[:n]  # precision-ok: post-program reply columns
            else:
                col = data.host_col(name)
                vectorish = issubclass(ftype, ft.OPVector)
                vals = [col.python_value(i) for i in range(n)]
                if vectorish:
                    vals = [None if v is None else list(map(float, v))
                            for v in vals]
                out[name] = vals
        return out

    def _program_cache_entries(self) -> int:
        total = 0
        for prog in self._programs.values():
            try:
                total += prog._cache_size()
            except Exception:  # jit internals moved: compiles stay 0 (failure-ok)
                pass
        return total

    def _program_for(self, li: int, dev_ts, bucket: int,
                     precision: str = "f32"):
        """The fused program for layer ``li`` at ``bucket`` — from the
        shared cross-model cache when one is attached (per-bucket program
        instances so the LRU can evict at (model, bucket) granularity),
        else this scorer's private per-layer dict (whose jit cache holds
        every bucket's trace, bounded by construction).

        The precision rung tags the key: f32 keeps the pre-ladder keys
        byte-identical (``li`` private / ``(fp, li, bucket)`` shared);
        non-f32 rungs fold the rung into the LAYER component —
        ``(precision, li)`` private, ``(fp, (precision, li), bucket)``
        shared — so every existing eviction predicate (``len(k) == 3``,
        ``k[0] == fp``, ``k[2] == bucket``) covers variant entries with
        no change."""
        lk = li if precision == "f32" else (precision, li)
        if self.program_cache is None:
            program = self._programs.get(lk)
            if program is None:
                program = fuse_layer_program(dev_ts, donate=self.donate,
                                             precision=precision)
                self._programs[lk] = program
            return program
        return self.program_cache.get(
            (self.fingerprint, lk, bucket),
            lambda: fuse_layer_program(dev_ts, donate=self.donate,
                                       precision=precision),
            # thunk: the param-pytree walk only runs on a miss, not on
            # every steady-state dispatch
            bytes_est=lambda: self.layer_entry_bytes(li, bucket, precision),
            counters=self.counters, bucket=bucket)

    def layer_entry_bytes(self, li: int, bucket: int,
                          precision: str = "f32") -> int:
        """Coarse HBM estimate for one compiled (layer, bucket) entry:
        the padded per-batch IO buffers (inputs + outputs x bucket rows x
        8B) plus the layer's fitted parameters AMORTIZED over this
        scorer's bucket count — params are per-call operands shared by
        every bucket's program, so charging them fully per entry would
        overstate a fully-resident model by the bucket count and drive
        the shared cache's LRU into needless evict/recompile churn. The
        serving generalization of the sweep's ``tree_stack_bytes``
        guard; an ESTIMATE by design (vector widths are unknown before
        trace) — a working-set bound, not an allocator.

        Non-f32 rungs scale by the rung's byte factor (bf16 halves the
        in-program IO/activation footprint, int8 quarters the weight
        payload) — the accounting that turns precision demotion into
        real resident-model headroom at a fixed cache budget."""
        host_ts, dev_ts = self._layers[li]
        n_io = len({n for t in dev_ts for n in t.runtime_input_names()}) \
            + len(dev_ts)
        param_bytes = 0
        for t in dev_ts:
            param_bytes += params_nbytes(t.device_params())
        raw = n_io * int(bucket) * 8 \
            + param_bytes // max(len(self.buckets), 1)
        factor = PRECISION_BYTE_FACTOR.get(precision, 1.0)
        return max(1, int(raw * factor))

    def _transform(self, data: PipelineData, bucket: int,
                   precision: str = "f32") -> PipelineData:
        for li, (host_ts, dev_ts) in enumerate(self._layers):
            if host_ts:
                data = data.with_host_cols(
                    {t.get_output().name: t.output_column(data)
                     for t in host_ts})
            if not dev_ts:
                continue
            program = self._program_for(li, dev_ts, bucket, precision)
            params = self._params_for(dev_ts, precision)
            in_cols = {n: self._device_input(data, n)
                       for t in dev_ts for n in t.runtime_input_names()}
            spent = set(self._free_plan[li]) if self.donate else set()
            donate_cols = {n: c for n, c in in_cols.items() if n in spent}
            keep_cols = {n: c for n, c in in_cols.items() if n not in spent}
            if self._analyze_cold \
                    and (li, bucket, precision) not in self._analyzed:
                # warmup-only: lower (host retrace, no backend compile)
                # and record FLOPs/bytes/HLO size BEFORE the dispatch —
                # after it, donated buffers are dead
                self._analyzed.add((li, bucket, precision))
                from transmogrifai_tpu.utils.devicewatch import (
                    analyze_program, compile_telemetry,
                )
                suffix = "" if precision == "f32" else f".{precision}"
                compile_telemetry.record_program_cost(
                    f"serving.layer{li}.bucket{bucket}{suffix}",
                    analyze_program(program, params, donate_cols,
                                    keep_cols))
            outs = program(params, donate_cols, keep_cols)
            # donated buffers are dead: drop the references so nothing can
            # reread them (and the host copy frees with the batch)
            for name in self._free_plan[li]:
                data.device.pop(name, None)
            data = data.with_device_cols(outs)
            for t in dev_ts:  # fitted vector metadata, outside the trace
                m = getattr(outs.get(t.get_output().name), "metadata", None)
                if m is not None:
                    t.out_meta = m
        return data

    def _extract_rows(self, data: PipelineData, n: int) -> list[dict]:
        """Result columns -> per-row python values, matching the row
        closure's output contract. Device prediction/vector columns
        extract in bulk (one ``tolist`` per column, not one numpy boxing
        per cell) — result extraction is the batched path's second-largest
        host cost after column build."""
        per_col: list[list] = []
        names = []
        for name, ftype in self._result:
            names.append(name)
            dev = data.device.get(name)
            if isinstance(dev, fr.PredictionColumn):
                per_col.append(_prediction_rows(dev, n))
            elif isinstance(dev, fr.VectorColumn):
                per_col.append(
                    np.asarray(dev.values, np.float64)[:n].tolist())  # precision-ok: post-program JSON boxing
            else:
                col = data.host_col(name)
                vectorish = issubclass(ftype, ft.OPVector)
                vals = [col.python_value(i) for i in range(n)]
                if vectorish:
                    vals = [None if v is None else list(map(float, v))
                            for v in vals]
                per_col.append(vals)
        return [dict(zip(names, cells)) for cells in zip(*per_col)]
