"""Binary columnar scoring wire format (``application/x-tmog-frame``).

The JSON scoring path pays three taxes per request: JSON parse, a
per-row dict walk into typed columns (``HostColumn.from_values`` calls
``ftype._validate`` per CELL), and JSON serialize on the way out. At
91.2k rps engine speed those taxes ARE the serving cost. This module
defines a length-prefixed binary frame that ships a request **batch**
as typed column buffers laid out the way the padding-bucket scorer
wants them — decode is ``np.frombuffer`` over memoryview slices
(zero-copy for every fixed-width column), and ``CompiledScorer.
score_columns`` consumes the arrays without ever materializing rows.

Frame layout (all integers little-endian)::

    offset  size  field
    0       u32   frame_len: bytes that FOLLOW this field
    4       4s    magic  b"TMOG"
    8       u8    version (= 1)
    9       u8    kind: 1=request  2=reply  3=error
    10      u16   model_id_len (bytes)
    12      u32   n_rows
    16      u16   n_cols
    18      u16   meta_len (bytes)
    20      ...   model_id, utf-8  (fixed offset: routers peek it
                  without parsing anything else — see peek_model_id)
    .       ...   meta, utf-8 JSON object ({} when meta_len=0); on
                  requests e.g. {"explain": 3}, on replies
                  {"traceId": ..., "lineage": {...}}
    .       ...   column table, n_cols entries:
                    u16 name_len | name utf-8 | u8 dtype | u8 flags
                    | u32 width | u32 data_len
    .       ...   column buffers, 8-byte aligned (from frame start),
                  in table order; per column:
                    [null bitmap, ceil(n_rows/8) bytes, LSB-first,
                     bit=1 means present]        (iff flags bit0)
                    [u32 offsets[n_rows+1]]      (iff TEXT/JSON)
                    [data, data_len bytes]

dtypes: 1=F64 2=F32 3=I64 4=I32 5=BOOL(u8) 6=TEXT(utf-8) 7=JSON.
``width`` is the per-row element count for fixed-width columns (1 for
scalars, 3 for geolocation, d for feature vectors); 0 for TEXT/JSON.
``data_len`` is the data buffer's byte length (for TEXT/JSON it equals
``offsets[n_rows]``), so a decoder can bounds-check every buffer
before touching it. Malformed frames raise :class:`WireFormatError`
(a ``ValueError``, so the HTTP layer's 400 mapping applies unchanged).

Deliberately jax-free (stdlib + numpy): the scale-out router imports
``peek_model_id`` to route opaque frames, and clients encode requests
with no framework on the box.
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import numpy as np

__all__ = [
    "CONTENT_TYPE_FRAME", "MAGIC", "VERSION", "MODEL_ID_OFFSET",
    "KIND_REQUEST", "KIND_REPLY", "KIND_ERROR",
    "F64", "F32", "I64", "I32", "BOOL", "TEXT", "JSONCOL",
    "WireFormatError", "WireColumn", "WireFrame",
    "encode_frame", "decode_frame", "peek_model_id", "peek_meta",
    "peek_request_id",
    "encode_rows", "rows_to_columns", "reply_columns",
    "rows_to_reply_columns", "reply_to_rows", "frame_to_rows",
]

#: the negotiated content type for framed requests AND replies
CONTENT_TYPE_FRAME = "application/x-tmog-frame"

MAGIC = b"TMOG"
VERSION = 1
#: byte offset of the model id within a frame — fixed by construction
#: so a router peeks the routing key without decoding columns
MODEL_ID_OFFSET = 20

KIND_REQUEST = 1
KIND_REPLY = 2
KIND_ERROR = 3

# dtype codes
F64, F32, I64, I32, BOOL, TEXT, JSONCOL = 1, 2, 3, 4, 5, 6, 7

_NP_DTYPE = {F64: np.dtype("<f8"), F32: np.dtype("<f4"),
             I64: np.dtype("<i8"), I32: np.dtype("<i4"),
             BOOL: np.dtype("u1")}

_FLAG_BITMAP = 0x01

_HEADER = struct.Struct("<4sBBHIHH")           # after the length prefix
_COL_FIXED = struct.Struct("<BBII")            # dtype, flags, width, data_len

#: hard ceiling a decoder enforces before allocating anything
MAX_FRAME_BYTES = 64 << 20


class WireFormatError(ValueError):
    """Malformed/corrupt/truncated frame — the client's fault (HTTP
    400), never a server crash."""


@dataclass
class WireColumn:
    """One decoded (or to-be-encoded) column.

    ``values``: numpy array for fixed-width dtypes ((n,) or (n, width)),
    list of ``str | None`` for TEXT, list of python values for JSON.
    ``mask``: bool[n] (True = present) or None (= all present).
    """

    name: str
    dtype: int
    values: Any
    mask: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return len(self.values)


@dataclass
class WireFrame:
    kind: int
    model_id: str
    n_rows: int
    meta: dict = field(default_factory=dict)
    columns: dict = field(default_factory=dict)   # name -> WireColumn


def _pad8(n: int) -> int:
    return (-n) % 8


def _pack_bitmap(mask: np.ndarray) -> bytes:
    return np.packbits(np.asarray(mask, dtype=bool),
                       bitorder="little").tobytes()


def _unpack_bitmap(buf: memoryview, n: int) -> np.ndarray:
    bits = np.unpackbits(np.frombuffer(buf, dtype=np.uint8),
                         bitorder="little")
    return bits[:n].astype(bool)


# -- encode -------------------------------------------------------------------

def _column_buffers(col: WireColumn, n_rows: int) -> tuple:
    """-> (dtype, flags, width, data_len, [buffer bytes...])."""
    bufs: list[bytes] = []
    flags = 0
    if col.mask is not None:
        mask = np.asarray(col.mask, dtype=bool)
        if mask.shape != (n_rows,):
            raise WireFormatError(
                f"column {col.name!r}: mask shape {mask.shape} != "
                f"({n_rows},)")
        flags |= _FLAG_BITMAP
        bufs.append(_pack_bitmap(mask))
    if col.dtype in _NP_DTYPE:
        arr = np.ascontiguousarray(col.values, dtype=_NP_DTYPE[col.dtype])
        if arr.ndim == 1:
            width = 1
        elif arr.ndim == 2:
            width = int(arr.shape[1])
        else:
            raise WireFormatError(
                f"column {col.name!r}: ndim {arr.ndim} unsupported")
        if arr.shape[0] != n_rows:
            raise WireFormatError(
                f"column {col.name!r}: {arr.shape[0]} rows != {n_rows}")
        data = arr.tobytes()
        bufs.append(data)
        return col.dtype, flags, width, len(data), bufs
    if col.dtype in (TEXT, JSONCOL):
        if len(col.values) != n_rows:
            raise WireFormatError(
                f"column {col.name!r}: {len(col.values)} rows != {n_rows}")
        parts: list[bytes] = []
        offsets = np.zeros(n_rows + 1, dtype=np.uint32)
        at = 0
        present = np.ones(n_rows, dtype=bool)
        for i, v in enumerate(col.values):
            if v is None:
                present[i] = False
                b = b""
            elif col.dtype == TEXT:
                b = str(v).encode("utf-8")
            else:
                b = json.dumps(v, default=str).encode("utf-8")
            parts.append(b)
            at += len(b)
            offsets[i + 1] = at
        if col.mask is None and not present.all():
            # nulls are carried by the bitmap, not by empty strings
            flags |= _FLAG_BITMAP
            bufs.append(_pack_bitmap(present))
        bufs.append(offsets.tobytes())
        blob = b"".join(parts)
        bufs.append(blob)
        return col.dtype, flags, 0, len(blob), bufs
    raise WireFormatError(f"column {col.name!r}: unknown dtype "
                          f"{col.dtype}")


def encode_frame(model_id: str, columns: Sequence[WireColumn],
                 n_rows: int, kind: int = KIND_REQUEST,
                 meta: Optional[dict] = None) -> bytes:
    """Serialize one frame. ``columns`` order is preserved on the wire
    (and thus in ``decode_frame``'s dict). Accepts a sequence of
    columns or a name->column dict (a decoded frame's ``columns``)."""
    if isinstance(columns, dict):
        columns = list(columns.values())
    mid = (model_id or "").encode("utf-8")
    meta_b = json.dumps(meta, default=str).encode("utf-8") if meta else b""
    if len(mid) > 0xFFFF:
        raise WireFormatError("model id too long")
    if len(meta_b) > 0xFFFF:
        raise WireFormatError("frame meta too large")
    table = bytearray()
    col_bufs: list[list[bytes]] = []
    for col in columns:
        dtype, flags, width, data_len, bufs = _column_buffers(col, n_rows)
        name_b = col.name.encode("utf-8")
        if len(name_b) > 0xFFFF:
            raise WireFormatError(f"column name too long: {col.name!r}")
        table += struct.pack("<H", len(name_b)) + name_b
        table += _COL_FIXED.pack(dtype, flags, width, data_len)
        col_bufs.append(bufs)
    head = _HEADER.pack(MAGIC, VERSION, kind, len(mid), int(n_rows),
                        len(columns), len(meta_b))
    body = bytearray()
    body += head + mid + meta_b + table
    # buffers region: every buffer 8-byte aligned from frame start
    # (frame start = the u32 length prefix, so offsets below are +4)
    for bufs in col_bufs:
        for b in bufs:
            body += b"\0" * _pad8(4 + len(body))
            body += b
    return struct.pack("<I", len(body)) + bytes(body)


# -- decode -------------------------------------------------------------------

def _need(buf, at: int, n: int, what: str) -> None:
    if at + n > len(buf):
        raise WireFormatError(
            f"truncated frame: {what} needs bytes [{at}:{at + n}) of "
            f"{len(buf)}")


def peek_model_id(buf: bytes) -> str:
    """The routing key, read from the fixed-offset header ONLY — a
    router forwards the frame as opaque bytes without decoding any
    column. Validates just magic/version/lengths."""
    _need(buf, 0, MODEL_ID_OFFSET, "header")
    (magic, version, kind, mid_len, n_rows, n_cols,
     meta_len) = _HEADER.unpack_from(buf, 4)
    if magic != MAGIC:
        raise WireFormatError(f"bad magic {magic!r}")
    if version != VERSION:
        raise WireFormatError(f"unsupported frame version {version}")
    _need(buf, MODEL_ID_OFFSET, mid_len, "model id")
    try:
        return bytes(buf[MODEL_ID_OFFSET:MODEL_ID_OFFSET
                         + mid_len]).decode("utf-8")
    except UnicodeDecodeError as e:
        raise WireFormatError(f"model id not utf-8: {e}") from None


def peek_meta(buf: bytes) -> dict:
    """The frame's meta dict, read WITHOUT touching any column — the
    replica's idempotency hook (``meta["request_id"]``) and the
    router's, when a client stamped the key in-band instead of in the
    ``X-Request-Id`` header. Validates magic/version/lengths only as
    far as the meta blob reaches."""
    _need(buf, 0, MODEL_ID_OFFSET, "header")
    (magic, version, kind, mid_len, n_rows, n_cols,
     meta_len) = _HEADER.unpack_from(buf, 4)
    if magic != MAGIC:
        raise WireFormatError(f"bad magic {magic!r}")
    if version != VERSION:
        raise WireFormatError(f"unsupported frame version {version}")
    if not meta_len:
        return {}
    at = MODEL_ID_OFFSET + mid_len
    _need(buf, at, meta_len, "meta")
    try:
        meta = json.loads(bytes(buf[at:at + meta_len]))
    except ValueError as e:
        raise WireFormatError(f"frame meta not JSON: {e}") from None
    if not isinstance(meta, dict):
        raise WireFormatError("frame meta must be a JSON object")
    return meta


def peek_request_id(buf: bytes):
    """``meta["request_id"]`` if the frame carries one (str, bounded),
    else None. Never raises: a frame too mangled to peek returns None
    and fails loudly later in :func:`decode_frame`."""
    try:
        rid = peek_meta(buf).get("request_id")
    except Exception:  # noqa: BLE001 — peek is best-effort
        return None
    if isinstance(rid, str) and 0 < len(rid) <= 128:
        return rid
    return None


def decode_frame(buf: bytes) -> WireFrame:
    """Parse + validate one frame (the payload INCLUDING the u32 length
    prefix). Fixed-width columns are zero-copy views over ``buf``."""
    buf = memoryview(buf) if not isinstance(buf, memoryview) \
        else buf
    if len(buf) > MAX_FRAME_BYTES:
        raise WireFormatError(
            f"frame of {len(buf)} bytes exceeds the {MAX_FRAME_BYTES}-"
            "byte bound")
    _need(buf, 0, 4, "length prefix")
    (frame_len,) = struct.unpack_from("<I", buf, 0)
    if frame_len != len(buf) - 4:
        raise WireFormatError(
            f"frame length {frame_len} != payload {len(buf) - 4}")
    _need(buf, 4, _HEADER.size, "header")
    (magic, version, kind, mid_len, n_rows, n_cols,
     meta_len) = _HEADER.unpack_from(buf, 4)
    if magic != MAGIC:
        raise WireFormatError(f"bad magic {magic!r}")
    if version != VERSION:
        raise WireFormatError(f"unsupported frame version {version}")
    if kind not in (KIND_REQUEST, KIND_REPLY, KIND_ERROR):
        raise WireFormatError(f"unknown frame kind {kind}")
    at = MODEL_ID_OFFSET
    _need(buf, at, mid_len, "model id")
    try:
        model_id = bytes(buf[at:at + mid_len]).decode("utf-8")
    except UnicodeDecodeError as e:
        raise WireFormatError(f"model id not utf-8: {e}") from None
    at += mid_len
    _need(buf, at, meta_len, "meta")
    meta: dict = {}
    if meta_len:
        try:
            meta = json.loads(bytes(buf[at:at + meta_len]))
        except ValueError as e:
            raise WireFormatError(f"frame meta not JSON: {e}") from None
        if not isinstance(meta, dict):
            raise WireFormatError("frame meta must be a JSON object")
    at += meta_len
    # column table
    cols_spec = []
    for _ in range(n_cols):
        _need(buf, at, 2, "column name length")
        (name_len,) = struct.unpack_from("<H", buf, at)
        at += 2
        _need(buf, at, name_len, "column name")
        try:
            name = bytes(buf[at:at + name_len]).decode("utf-8")
        except UnicodeDecodeError as e:
            raise WireFormatError(
                f"column name not utf-8: {e}") from None
        at += name_len
        _need(buf, at, _COL_FIXED.size, "column descriptor")
        dtype, flags, width, data_len = _COL_FIXED.unpack_from(buf, at)
        at += _COL_FIXED.size
        cols_spec.append((name, dtype, flags, width, data_len))
    # buffers region
    columns: dict[str, WireColumn] = {}
    for name, dtype, flags, width, data_len in cols_spec:
        mask = None
        if flags & _FLAG_BITMAP:
            at += _pad8(at)
            nbytes = (n_rows + 7) // 8
            _need(buf, at, nbytes, f"null bitmap of {name!r}")
            mask = _unpack_bitmap(buf[at:at + nbytes], n_rows)
            at += nbytes
        if dtype in _NP_DTYPE:
            npdt = _NP_DTYPE[dtype]
            if width < 1:
                raise WireFormatError(
                    f"column {name!r}: width {width} invalid for "
                    f"dtype {dtype}")
            want = n_rows * width * npdt.itemsize
            if data_len != want:
                raise WireFormatError(
                    f"column {name!r}: data_len {data_len} != "
                    f"{n_rows} rows x {width} x {npdt.itemsize}B")
            at += _pad8(at)
            _need(buf, at, data_len, f"data of {name!r}")
            arr = np.frombuffer(buf[at:at + data_len], dtype=npdt)
            if width > 1:
                arr = arr.reshape(n_rows, width)
            at += data_len
            columns[name] = WireColumn(name, dtype, arr, mask)
        elif dtype in (TEXT, JSONCOL):
            at += _pad8(at)
            off_bytes = 4 * (n_rows + 1)
            _need(buf, at, off_bytes, f"offsets of {name!r}")
            offsets = np.frombuffer(buf[at:at + off_bytes],
                                    dtype=np.uint32)
            at += off_bytes
            at += _pad8(at)
            _need(buf, at, data_len, f"text blob of {name!r}")
            if n_rows and (int(offsets[-1]) != data_len
                           or np.any(np.diff(offsets.astype(np.int64))
                                     < 0)):
                raise WireFormatError(
                    f"column {name!r}: corrupt offsets")
            blob = bytes(buf[at:at + data_len])
            at += data_len
            vals: list = []
            try:
                for i in range(n_rows):
                    if mask is not None and not mask[i]:
                        vals.append(None)
                        continue
                    piece = blob[offsets[i]:offsets[i + 1]]
                    if dtype == TEXT:
                        vals.append(piece.decode("utf-8"))
                    else:
                        vals.append(json.loads(piece) if piece
                                    else None)
            except (UnicodeDecodeError, ValueError) as e:
                raise WireFormatError(
                    f"column {name!r}: bad cell payload: {e}") from None
            columns[name] = WireColumn(name, dtype, vals, mask)
        else:
            raise WireFormatError(
                f"column {name!r}: unknown dtype {dtype}")
    return WireFrame(kind=kind, model_id=model_id, n_rows=int(n_rows),
                     meta=meta, columns=columns)


# -- client-side conveniences -------------------------------------------------

def rows_to_columns(rows: Sequence[dict],
                    schema: Optional[dict] = None) -> list[WireColumn]:
    """Infer wire columns from request rows (the client's encode
    helper; the hot path on a real client keeps columns natively and
    never builds rows at all). Inference: all-numeric -> F64 (+bitmap
    when any None), bool -> BOOL, str -> TEXT, anything else -> JSON.
    ``schema`` ({name: dtype or (dtype, width)}) overrides inference
    where it matters (e.g. geolocation lists as F64 width=3)."""
    names: list[str] = []
    seen = set()
    for r in rows:
        for k in r:
            if k not in seen:
                seen.add(k)
                names.append(k)
    out = []
    n = len(rows)
    for name in names:
        vals = [r.get(name) for r in rows]
        spec = (schema or {}).get(name)
        if spec is not None:
            dtype = spec[0] if isinstance(spec, tuple) else spec
            if dtype in _NP_DTYPE:
                width = spec[1] if isinstance(spec, tuple) else 1
                mask = np.array([v is not None for v in vals], bool)
                fill = 0 if width == 1 else [0.0] * width
                dense = [fill if v is None else v for v in vals]
                arr = np.asarray(dense, dtype=_NP_DTYPE[dtype])
                out.append(WireColumn(
                    name, dtype, arr,
                    None if mask.all() else mask))
            else:
                out.append(WireColumn(name, dtype, vals))
            continue
        non_null = [v for v in vals if v is not None]
        if non_null and all(isinstance(v, bool) for v in non_null):
            mask = np.array([v is not None for v in vals], bool)
            arr = np.array([bool(v) for v in vals], dtype=np.uint8)
            out.append(WireColumn(name, BOOL, arr,
                                  None if mask.all() else mask))
        elif non_null and all(
                isinstance(v, (int, float)) and not isinstance(v, bool)
                for v in non_null):
            mask = np.array([v is not None for v in vals], bool)
            arr = np.array([0.0 if v is None else float(v)
                            for v in vals], dtype=np.float64)
            out.append(WireColumn(name, F64, arr,
                                  None if mask.all() else mask))
        elif non_null and all(isinstance(v, str) for v in non_null):
            out.append(WireColumn(name, TEXT, vals))
        else:
            out.append(WireColumn(name, JSONCOL, vals))
    return out


def encode_rows(model_id: str, rows: Sequence[dict],
                schema: Optional[dict] = None,
                meta: Optional[dict] = None) -> bytes:
    """Client one-liner: rows -> request frame bytes."""
    return encode_frame(model_id, rows_to_columns(rows, schema),
                        len(rows), kind=KIND_REQUEST, meta=meta)


def reply_columns(result_cols: dict, n_rows: int) -> list[WireColumn]:
    """Server-side: ``CompiledScorer.score_columns`` output (name ->
    ndarray | list) to typed reply columns. f64/f32/int arrays ride as
    their native dtype; python-value lists ride as JSON."""
    out = []
    for name, vals in result_cols.items():
        if isinstance(vals, np.ndarray) and vals.dtype.kind in "fiu":
            code = {np.dtype("f8"): F64, np.dtype("f4"): F32,
                    np.dtype("i8"): I64,
                    np.dtype("i4"): I32}.get(vals.dtype, None)
            if code is None:
                vals = np.asarray(vals, np.float64)
                code = F64
            out.append(WireColumn(name, code, vals))
        else:
            out.append(WireColumn(name, JSONCOL, list(vals)))
    return out


def rows_to_reply_columns(rows: Sequence[Any]) -> list[WireColumn]:
    """Row-path fallback encoder: score documents (or per-row
    exceptions) -> JSON reply columns, plus an ``error`` column naming
    any row whose scoring failed (its other cells are null). The frame
    reply must settle every row — zero-drop semantics do not change
    with the encoding."""
    names: list[str] = []
    seen = set()
    any_err = False
    for r in rows:
        if isinstance(r, BaseException):
            any_err = True
            continue
        for k in r:
            if k not in seen:
                seen.add(k)
                names.append(k)
    cols = [WireColumn(name,
                       JSONCOL,
                       [None if isinstance(r, BaseException)
                        else r.get(name) for r in rows])
            for name in names]
    if any_err:
        cols.append(WireColumn(
            "error", JSONCOL,
            [f"{type(r).__name__}: {str(r)[:300]}"
             if isinstance(r, BaseException) else None for r in rows]))
    return cols


def frame_to_rows(frame: WireFrame) -> list[dict]:
    """Request frame -> plain request rows (python values, None for
    masked-out cells) — the seam for paths that genuinely need rows
    (the explain lane, the degraded-mode row fallback)."""
    rows: list[dict] = [{} for _ in range(frame.n_rows)]
    for name, col in frame.columns.items():
        mask = col.mask
        if isinstance(col.values, np.ndarray):
            vals = col.values.tolist()
        else:
            vals = col.values
        for i in range(frame.n_rows):
            v = None if (mask is not None and not mask[i]) else vals[i]
            if col.dtype == BOOL and v is not None:
                v = bool(v)
            rows[i][name] = v
    return rows


def reply_to_rows(frame: WireFrame) -> list[dict]:
    """Client-side: reply frame -> score documents. Dotted column
    names (``pred.prediction``) fold back into one nested dict per
    row, matching the JSON reply shape exactly."""
    n = frame.n_rows
    rows: list[dict] = [{} for _ in range(n)]
    for name, col in frame.columns.items():
        if isinstance(col.values, np.ndarray):
            vals = col.values.tolist()
        else:
            vals = col.values
        top, dot, sub = name.partition(".")
        for i in range(n):
            v = vals[i]
            if col.mask is not None and not col.mask[i]:
                v = None
            if dot:
                rows[i].setdefault(top, {})[sub] = v
            else:
                rows[i][name] = v
    return rows
