"""The online scoring service: admission -> micro-batch -> compiled DAG,
with graceful degradation to the engine-free row path.

Request lifecycle:

1. **admission** (caller thread): with ``strict`` (default) the row is
   validated against the model's required raw-feature keys
   (``local.scoring.check_row``) — malformed requests are rejected at the
   door with a ``KeyError`` naming the missing keys, never queued. A full
   queue rejects with ``BackpressureError`` (+ retry-after hint).
2. **dispatch** (batcher worker): the coalesced batch goes to the compiled
   bucket-padded scorer. Transient device errors retry via
   ``utils.retry.with_device_retry``; if the compiled path still fails —
   transient or not — the batch is re-scored through the
   ``local/scoring.py`` row closure, so an ACCEPTED request never pays for
   a device fault with an error, let alone a drop.
3. **degraded mode**: after a compiled-path failure the server stays on the
   row path (correct but slow) and re-probes the compiled path with a live
   batch every ``probe_interval_s`` — recovery is automatic and observable
   (``metrics.degraded`` counters).

Per-row scoring errors (a genuinely broken row crashing a transform) fail
only that row's future — in BOTH paths: the compiled path falls back to
row-scoring the batch when it raises, and the row path isolates exceptions
per request.
"""

from __future__ import annotations

import time
import warnings
from concurrent.futures import Future
from typing import Any, Optional, Sequence

from transmogrifai_tpu.local.scoring import (
    check_row, make_score_function, required_raw_keys,
)
from transmogrifai_tpu.serving.batcher import BackpressureError, MicroBatcher
from transmogrifai_tpu.serving.compiled import CompiledScorer
from transmogrifai_tpu.serving.metrics import ServingMetrics
from transmogrifai_tpu.utils.events import events
from transmogrifai_tpu.utils.retry import with_device_retry

__all__ = ["ScoringServer"]

#: reserved request-row key carrying a per-request explain top-K through
#: the batcher (popped before scoring; never a raw feature)
_EXPLAIN_K = "__explain_top_k__"

#: reserved batcher-item key carrying one decoded wire frame's host
#: columns through the SAME admission queue as row requests (one queue
#: slot per frame): backpressure, deadlines, and zero-drop semantics
#: apply to framed batches unchanged
_FRAME_K = "__wire_frame__"


class ScoringServer:
    """Thread-based online scorer for a fitted ``WorkflowModel``.

    Usage::

        with ScoringServer(model, max_batch=256, max_wait_ms=2) as srv:
            fut = srv.submit({"age": 31.0, "sex": "female", ...})
            scores = fut.result(timeout=1.0)
    """

    def __init__(self, model, *, max_batch: int = 256,
                 max_wait_ms: float = 2.0, queue_capacity: int = 1024,
                 default_timeout_ms: Optional[float] = None,
                 strict: bool = True, min_bucket: int = 8,
                 retries: int = 2, retry_backoff_s: float = 0.05,
                 probe_interval_s: float = 1.0,
                 donate: Optional[bool] = None,
                 metrics_max_samples: int = 8192,
                 metrics_port: Optional[int] = None,
                 metrics_host: str = "127.0.0.1",
                 access_log_sample: float = 0.0,
                 slo=None, event_label: Optional[str] = None,
                 program_cache=None, fingerprint: Optional[str] = None,
                 explain: bool = False, explain_top_k: int = 5,
                 explain_mask_chunk: Optional[int] = None,
                 precision: str = "f32",
                 precision_tolerance: float = 5e-2,
                 precision_backoff: int = 50):
        from transmogrifai_tpu.utils.precision import ladder_for
        self.model = model
        #: precision-ladder target (``"f32"`` | ``"bf16"`` | ``"int8"`` |
        #: ``"auto"``). Serving always STARTS at the f32 master rung;
        #: lower rungs are reached only through the per-model shadow gate
        #: (promotion) or the resource ladder (forced demotion) — see
        #: ``_precision_candidate`` / ``_shed_and_retry``
        self.precision_target = str(precision)
        self._ladder = ladder_for(precision)
        #: max ``fleet.score_diff`` between the f32 reference and a
        #: candidate rung's scores for the candidate to be promoted
        self.precision_tolerance = float(precision_tolerance)
        #: dispatches to wait after a gate rejection before re-trying the
        #: candidate (NaN / out-of-tolerance rungs must not double every
        #: batch's work retrying forever)
        self.precision_backoff = int(precision_backoff)
        self._precision_backoff_left = 0
        #: label stamped on this server's flight-recorder events (the
        #: fleet sets the model id; a standalone server has none)
        self.event_label = event_label
        #: SLO objectives (utils/slo.py): a list of SLObjective/dicts, a
        #: config path, or a prebuilt SLOEngine — evaluated over this
        #: server's own metrics, exported as transmogrifai_slo_* and
        #: folded into /healthz readiness
        self.slo_engine = None
        if slo is not None:
            from transmogrifai_tpu.utils.slo import SLOEngine
            self.slo_engine = SLOEngine.for_serving(
                slo, lambda: [self.metrics])
        self.strict = strict
        self.required_keys = required_raw_keys(model)
        self.retries = int(retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.probe_interval_s = float(probe_interval_s)
        #: fleet seam: with ``program_cache`` (serving/fleet.ProgramCache)
        #: this server's fused programs live in the shared cross-model LRU
        #: keyed by ``fingerprint`` (see CompiledScorer)
        self.scorer = CompiledScorer(model, max_batch=max_batch,
                                     min_bucket=min_bucket, donate=donate,
                                     program_cache=program_cache,
                                     fingerprint=fingerprint)
        self.row_score = make_score_function(model, strict=False)
        self.batcher = MicroBatcher(
            self._dispatch, max_batch=max_batch, max_wait_ms=max_wait_ms,
            queue_capacity=queue_capacity,
            default_timeout_ms=default_timeout_ms,
            on_complete=lambda settled:
                self.metrics.record_requests_done(settled),
            on_expired=lambda n: self.metrics.record_expired(n))
        self.metrics = ServingMetrics(
            max_samples=metrics_max_samples,
            queue_depth_fn=lambda: self.batcher.queue_depth,
            queue_capacity=queue_capacity,
            compile_counters=self.scorer.counters)
        #: the EXPLAIN lane (opt-in): its own compiled explainer (sharing
        #: the scoring lane's program cache + fingerprint, so the plain
        #: layers' compiled entries are literally shared), its own
        #: micro-batcher (an expensive explain batch must never add
        #: latency to plain scoring traffic), and its own ServingMetrics
        #: (the transmogrifai_explain_* series)
        self.explainer = None
        self.explain_batcher = None
        self.explain_metrics = None
        if explain:
            from transmogrifai_tpu.serving.explain import CompiledExplainer
            self.explainer = CompiledExplainer(
                model, top_k=explain_top_k,
                mask_chunk=explain_mask_chunk, max_batch=max_batch,
                min_bucket=min_bucket, donate=donate,
                program_cache=program_cache,
                fingerprint=self.scorer.fingerprint)
            self.explain_batcher = MicroBatcher(
                self._explain_dispatch, max_batch=max_batch,
                max_wait_ms=max_wait_ms, queue_capacity=queue_capacity,
                default_timeout_ms=default_timeout_ms,
                on_complete=lambda settled:
                    self.explain_metrics.record_requests_done(settled),
                on_expired=lambda n:
                    self.explain_metrics.record_expired(n))
            self.explain_metrics = ServingMetrics(
                max_samples=metrics_max_samples,
                queue_depth_fn=lambda: self.explain_batcher.queue_depth,
                queue_capacity=queue_capacity,
                compile_counters=self.explainer.counters)
        self._warmup_explain_compiles: dict = {}
        self._degraded_since: Optional[float] = None
        self._last_probe = 0.0
        #: scrape endpoint (/metrics + /healthz), started with the server
        #: when ``metrics_port`` is not None (0 = ephemeral port; the
        #: bound port is ``self.metrics_http.port``). ``metrics_host``
        #: defaults to loopback; bind "0.0.0.0" for an external scraper
        self.metrics_http = None
        self._metrics_port = metrics_port
        self._metrics_host = metrics_host
        self._access_log_sample = float(access_log_sample)
        #: lifecycle for fleet readiness reporting: warming -> ready ->
        #: (draining ->) stopped; "degraded" overlays ready while the row
        #: path serves (see the ``state`` property)
        self._lifecycle = "warming"
        #: per-bucket compile counts at the end of start()'s warmup — the
        #: baseline ``post_warmup_compiles`` subtracts, making "did
        #: steady-state traffic recompile?" a one-call question
        self._warmup_compiles: dict = {}

    # -- lifecycle -----------------------------------------------------------
    def start(self, warmup_row: Optional[dict] = None,
              warmup_buckets: Optional[Sequence[int]] = None
              ) -> "ScoringServer":
        """Start the dispatch worker; with ``warmup_row``, pre-compile every
        padding bucket before accepting traffic. Warmup is an optimization:
        a bad warmup row (e.g. the first row of a replay file is the
        malformed one) must not keep the server from starting — buckets
        then compile lazily on first traffic."""
        if warmup_row is not None:
            # warming EVERY rung of the configured ladder is what makes
            # later promotions/demotions compile-free: rung transitions
            # re-dispatch against already-traced programs (0 post-warmup
            # compiles per (bucket, precision))
            rungs = self._ladder if len(self._ladder) > 1 else None
            try:
                self.scorer.warmup(warmup_row, buckets=warmup_buckets,
                                   precisions=rungs)
            except Exception as e:  # noqa: BLE001 — degrade to lazy compile
                warnings.warn(
                    f"serving: warmup failed ({type(e).__name__}: "
                    f"{str(e)[:140]}); padding buckets will compile lazily",
                    RuntimeWarning)
            if self.explainer is not None:
                try:
                    self.explainer.warmup(warmup_row,
                                          buckets=warmup_buckets,
                                          precisions=rungs)
                except Exception as e:  # noqa: BLE001 — degrade to lazy compile
                    warnings.warn(
                        f"serving: explain warmup failed "
                        f"({type(e).__name__}: {str(e)[:140]}); explain "
                        "buckets will compile lazily", RuntimeWarning)
        # bind the scrape endpoint BEFORE the worker starts: a port-bind
        # failure (EADDRINUSE) must fail start() cleanly, not leave a
        # half-started server with a running batcher thread behind it
        if self._metrics_port is not None and self.metrics_http is None:
            from transmogrifai_tpu.serving.http import MetricsServer
            from transmogrifai_tpu.utils.prometheus import build_registry
            registry = build_registry(serving=self.metrics, server=self,
                                      slo=self.slo_engine)
            self.metrics_http = MetricsServer(
                render_fn=registry.render,
                health_fn=self.health,
                port=self._metrics_port,
                host=self._metrics_host,
                access_log_sample=self._access_log_sample).start()
        self.batcher.start()
        if self.explain_batcher is not None:
            self.explain_batcher.start()
            self._warmup_explain_compiles = dict(
                self.explainer.counters.compiles_by_bucket())
        self._warmup_compiles = dict(self.scorer.counters
                                     .compiles_by_bucket())
        self._lifecycle = "ready"
        return self

    def stop(self, drain: bool = True) -> None:
        self._lifecycle = "draining"
        if self.explain_batcher is not None:
            self.explain_batcher.stop(drain=drain)
        self.batcher.stop(drain=drain)
        self._lifecycle = "stopped"
        if self.metrics_http is not None:
            self.metrics_http.stop()
            self.metrics_http = None

    def __enter__(self) -> "ScoringServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def bound_metrics_port(self) -> Optional[int]:
        """The ACTUAL port the scrape endpoint bound — with
        ``metrics_port=0`` (ephemeral: multi-process tests and benches
        must not race on fixed ports) this is the kernel-assigned one;
        None while no endpoint is running."""
        return self.metrics_http.port if self.metrics_http else None

    @property
    def degraded(self) -> bool:
        return self._degraded_since is not None

    @property
    def state(self) -> str:
        """warming | ready | degraded | draining | stopped — the
        readiness word ``/healthz`` reports per model."""
        if self._lifecycle != "ready":
            return self._lifecycle
        return "degraded" if self.degraded else "ready"

    def health(self) -> dict:
        """The ``/healthz`` body: liveness + readiness. ``ready`` is the
        load-balancer bit — it drops when the server leaves the ready
        state OR a fast-burn SLO alert fires (an endpoint burning its
        error budget at page rate should shed traffic before it pages)."""
        from transmogrifai_tpu.utils.resources import pressure_state
        from transmogrifai_tpu.utils.slo import fold_health
        doc = {"status": "ok" if self.state == "ready" else self.state,
               "degraded": self.degraded,
               "queueDepth": self.batcher.queue_depth,
               "ready": self.state in ("ready", "degraded"),
               "resources": pressure_state()}
        fold_health(self.slo_engine, doc)
        return doc

    def post_warmup_compiles(self) -> dict:
        """Per-bucket fused-program compiles since start()'s warmup — the
        compile-storm bound: 0 everywhere means steady-state traffic
        never recompiled (cache evictions under a too-small shared
        budget show up here as recompiles)."""
        now = self.scorer.counters.compiles_by_bucket()
        return {b: n - self._warmup_compiles.get(b, 0)
                for b, n in now.items()
                if n - self._warmup_compiles.get(b, 0)}

    def post_warmup_explain_compiles(self) -> dict:
        """The explain lane's compile-storm bound: per-bucket explain
        compiles since warmup (0 everywhere = steady-state explained
        traffic never recompiled)."""
        if self.explainer is None:
            return {}
        now = self.explainer.counters.compiles_by_bucket()
        return {b: n - self._warmup_explain_compiles.get(b, 0)
                for b, n in now.items()
                if n - self._warmup_explain_compiles.get(b, 0)}

    # -- request API ---------------------------------------------------------
    def submit(self, row: dict,
               timeout_ms: Optional[float] = None,
               trace_id: Optional[str] = None) -> Future:
        """Admit one request. Raises ``KeyError`` (strict validation) or
        ``BackpressureError`` (queue full) instead of queueing doomed
        work. ``trace_id`` carries the request-scoped trace context
        through the batcher into the flight recorder."""
        if self.strict:
            try:
                check_row(row, self.required_keys)
            except KeyError:
                self.metrics.record_rejected(invalid=True)
                raise
        try:
            fut = self.batcher.submit(row, timeout_ms=timeout_ms,
                                      trace_id=trace_id)
        except BackpressureError as e:
            self.metrics.record_rejected(invalid=False)
            # the flight recorder sees overload, rate-limited: sustained
            # backpressure fires at request rate and must not flood the
            # ring it exists to fill with useful history
            events.emit_limited(
                f"bp:{id(self)}", 1.0, "serving.backpressure_reject",
                trace_id=trace_id, model=self.event_label,
                queueDepth=self.batcher.queue_depth,
                retryAfterS=round(e.retry_after_s, 4))
            raise
        self.metrics.record_admitted()
        return fut

    def submit_blocking(self, row: dict,
                        timeout_ms: Optional[float] = None,
                        max_wait_s: Optional[float] = None,
                        trace_id: Optional[str] = None) -> Future:
        """``submit`` that absorbs backpressure
        (``batcher.absorb_backpressure``): the client loop for replay
        drivers (runner SERVE, ``cli serve``); strict-validation
        ``KeyError`` still raises immediately."""
        from transmogrifai_tpu.serving.batcher import absorb_backpressure
        return absorb_backpressure(
            lambda: self.submit(row, timeout_ms=timeout_ms,
                                trace_id=trace_id),
            max_wait_s=max_wait_s)

    def submit_frame(self, frame,
                     timeout_ms: Optional[float] = None,
                     trace_id: Optional[str] = None) -> Future:
        """Admit one decoded wire frame (``wireformat.WireFrame``): the
        columnar analog of ``submit``. The column build happens HERE on
        the caller's thread (zero-copy from the wire buffers), so a
        malformed frame fails fast — ``KeyError`` for a missing raw
        feature, ``WireFormatError``/``FeatureTypeValueError`` for a
        type mismatch — without ever queueing. The future resolves to
        ``("columns", {name: ndarray|list})`` on the compiled path or
        ``("rows", [doc | Exception])`` when the batch row-served
        (degraded mode / data-error isolation) — either way every row
        settles (``wireformat.reply_columns`` / ``rows_to_reply_
        columns`` encode both shapes)."""
        if frame.n_rows == 0:
            fut: Future = Future()
            fut.set_result(("columns", {}))
            return fut
        cols, n = self.scorer.host_columns_from_wire(frame)
        try:
            # weight=n: a frame that already fills max_batch dispatches
            # immediately instead of sitting out the coalescing wait
            fut = self.batcher.submit({_FRAME_K: (cols, n)},
                                      timeout_ms=timeout_ms,
                                      trace_id=trace_id, weight=n)
        except BackpressureError as e:
            self.metrics.record_rejected(invalid=False)
            events.emit_limited(
                f"bp:{id(self)}", 1.0, "serving.backpressure_reject",
                trace_id=trace_id, model=self.event_label,
                queueDepth=self.batcher.queue_depth,
                retryAfterS=round(e.retry_after_s, 4))
            raise
        self.metrics.record_admitted()
        return fut

    def submit_explain(self, row: dict, top_k: Optional[int] = None,
                       timeout_ms: Optional[float] = None,
                       trace_id: Optional[str] = None) -> Future:
        """Admit one EXPLAIN request: the future resolves to the score
        document PLUS an ordered ``"explanations"`` top-K LOCO
        attribution list. Its own lane (queue, batcher, metrics): an
        expensive explain batch never blocks plain scoring traffic.
        ``top_k`` overrides the lane's default for this request."""
        if self.explain_batcher is None:
            raise ValueError(
                "explain lane is disabled; construct the server with "
                "explain=True")
        if self.strict:
            try:
                check_row(row, self.required_keys)
            except KeyError:
                self.explain_metrics.record_rejected(invalid=True)
                raise
        row = dict(row)
        if top_k is not None:
            row[_EXPLAIN_K] = int(top_k)
        try:
            fut = self.explain_batcher.submit(row, timeout_ms=timeout_ms,
                                              trace_id=trace_id)
        except BackpressureError as e:
            self.explain_metrics.record_rejected(invalid=False)
            events.emit_limited(
                f"bpx:{id(self)}", 1.0, "serving.backpressure_reject",
                trace_id=trace_id, model=self.event_label, lane="explain",
                queueDepth=self.explain_batcher.queue_depth,
                retryAfterS=round(e.retry_after_s, 4))
            raise
        self.explain_metrics.record_admitted()
        return fut

    def submit_explain_blocking(self, row: dict,
                                top_k: Optional[int] = None,
                                timeout_ms: Optional[float] = None,
                                max_wait_s: Optional[float] = None,
                                trace_id: Optional[str] = None) -> Future:
        """``submit_explain`` that absorbs backpressure (the shared
        ``batcher.absorb_backpressure`` client loop)."""
        from transmogrifai_tpu.serving.batcher import absorb_backpressure
        return absorb_backpressure(
            lambda: self.submit_explain(row, top_k=top_k,
                                        timeout_ms=timeout_ms,
                                        trace_id=trace_id),
            max_wait_s=max_wait_s)

    def explain(self, row: dict, top_k: Optional[int] = None,
                timeout_s: Optional[float] = None,
                trace_id: Optional[str] = None) -> dict:
        return self.submit_explain(row, top_k=top_k,
                                   trace_id=trace_id).result(
                                       timeout=timeout_s)

    def score(self, row: dict, timeout_s: Optional[float] = None,
              trace_id: Optional[str] = None) -> dict:
        return self.submit(row, trace_id=trace_id).result(timeout=timeout_s)

    def score_many(self, rows: Sequence[dict],
                   timeout_s: Optional[float] = None) -> list[dict]:
        futures = [self.submit(r) for r in rows]
        return [f.result(timeout=timeout_s) for f in futures]

    # -- dispatch (batcher worker thread) ------------------------------------
    def _dispatch(self, rows: Sequence[dict]) -> list[Any]:
        """Batcher worker entry: partition the coalesced batch into
        plain rows and framed-columnar items (``_FRAME_K`` sentinels,
        one per wire frame), serve each through the same compiled /
        degrade / row-fallback ladder, and settle every future."""
        t0 = time.monotonic()
        frame_ix = [i for i, r in enumerate(rows) if _FRAME_K in r]
        if not frame_ix:
            results, degraded = self._dispatch_rows(rows)
            self.metrics.record_batch(len(rows),
                                      time.monotonic() - t0,
                                      degraded=degraded)
            return results
        out: list[Any] = [None] * len(rows)
        degraded = False
        plain_ix = [i for i in range(len(rows)) if i not in
                    set(frame_ix)]
        if plain_ix:
            res, deg = self._dispatch_rows([rows[i] for i in plain_ix])
            degraded |= deg
            for i, r in zip(plain_ix, res):
                out[i] = r
        for i in frame_ix:
            cols, n = rows[i][_FRAME_K]
            try:
                out[i], deg = self._dispatch_frame(cols, n)
                degraded |= deg
            except Exception as e:  # noqa: BLE001 — harness errors re-raised inside
                from transmogrifai_tpu.utils.faults import (
                    FaultHarnessError,
                )
                if isinstance(e, FaultHarnessError):
                    raise
                out[i] = e
        self.metrics.record_batch(len(rows), time.monotonic() - t0,
                                  degraded=degraded)
        return out

    def _dispatch_rows(self, rows: Sequence[dict]
                       ) -> tuple[list[Any], bool]:
        from transmogrifai_tpu.types.feature_types import (
            FeatureTypeValueError,
        )
        from transmogrifai_tpu.utils.tracing import span
        degraded = True
        if self._compiled_eligible():
            try:
                with span("serving.compiled_dispatch", rows=len(rows)):
                    results = self._compiled_dispatch(rows)
                degraded = False
            except FeatureTypeValueError:
                # a DATA error: strict admission checks key presence, not
                # types, so a wrong-typed row can fail the batch's column
                # build. That is the requester's fault, not the device's —
                # row-score the batch (isolating the poison row to its own
                # future) WITHOUT entering degraded mode, or a trickle of
                # bad rows would pin every client on the slow path
                degraded = False
                self.metrics.record_data_error_batch()
                results = self._row_dispatch(rows)
            except Exception as e:  # noqa: BLE001 — any OTHER compiled-path
                # failure is infrastructure: degrade, re-serve below —
                # EXCEPT harness errors (simulated preemption, misconfigured
                # fault plan), which must surface (the batcher fails the
                # batch's futures with it), never become degradation
                from transmogrifai_tpu.utils.faults import FaultHarnessError
                if isinstance(e, FaultHarnessError):
                    raise
                shed_results = self._shed_and_retry(rows, e)
                if shed_results is not None:
                    # the degradation ladder re-served the batch compiled
                    # at a smaller shape: not row-path degradation — the
                    # server stays on the (narrower) compiled path. If
                    # this batch was a degraded-mode PROBE, the success
                    # is a recovery: clear the mode now, not at the next
                    # probe interval
                    self._exit_degraded()
                    degraded = False
                    results = shed_results
                else:
                    self._enter_degraded(e)
                    results = self._row_dispatch(rows)
        else:
            results = self._row_dispatch(rows)
        return results, degraded

    # -- framed-columnar dispatch (batcher worker thread) --------------------
    def _dispatch_frame(self, cols: dict, n: int) -> tuple[Any, bool]:
        """One wire frame through the serving ladder. Compiled success
        returns ``("columns", result_cols)`` — no row dicts anywhere.
        Every fallback (data error, degraded mode, shed-ladder
        exhaustion) converts the columns to rows ONCE and re-serves
        through the existing row machinery, returning ``("rows",
        [doc | Exception])`` — per-row faults isolate, zero drops."""
        from transmogrifai_tpu.types.feature_types import (
            FeatureTypeValueError,
        )
        from transmogrifai_tpu.utils.tracing import span
        if self._compiled_eligible():
            try:
                with span("serving.compiled_dispatch", rows=n,
                          wire="frame"):
                    return (("columns",
                             self._compiled_frame_dispatch(cols, n)),
                            False)
            except FeatureTypeValueError:
                self.metrics.record_data_error_batch()
                return ("rows", self._row_dispatch(
                    self._cols_to_rows(cols, n))), False
            except Exception as e:  # noqa: BLE001 — same ladder as _dispatch_rows
                from transmogrifai_tpu.utils.faults import (
                    FaultHarnessError,
                )
                if isinstance(e, FaultHarnessError):
                    raise
                rows = self._cols_to_rows(cols, n)
                shed_results = self._shed_and_retry(rows, e)
                if shed_results is not None:
                    self._exit_degraded()
                    return ("rows", shed_results), False
                self._enter_degraded(e)
                return ("rows", self._row_dispatch(rows)), True
        return ("rows", self._row_dispatch(
            self._cols_to_rows(cols, n))), True

    def _compiled_frame_dispatch(self, cols: dict, n: int) -> dict:
        """``_compiled_dispatch`` for a columnar batch: same devicewatch
        ledger/guard, chaos seam, and transient retry around
        ``scorer.score_columns``."""
        from transmogrifai_tpu.utils import devicewatch
        from transmogrifai_tpu.utils.faults import fault_point
        attempts = {"n": 0}

        def attempt():
            attempts["n"] += 1
            fault_point("serving.dispatch")
            return self.scorer.score_columns(cols, n)

        eid = devicewatch.dispatch_ledger.register(
            "serving.dispatch", rows=n, model=self.event_label)
        try:
            with devicewatch.guard("serving.dispatch",
                                   site="serving.dispatch", rows=n):
                result = with_device_retry(
                    attempt, retries=self.retries,
                    backoff_s=self.retry_backoff_s)
        finally:
            devicewatch.dispatch_ledger.complete(eid)
            if attempts["n"] > 1:
                self.metrics.record_retry(attempts["n"] - 1)
        self._exit_degraded()
        return result

    @staticmethod
    def _cols_to_rows(cols: dict, n: int) -> list[dict]:
        """Host columns back to request rows — the fallback seam: the
        row path's closure wants python values, and a frame that hit a
        degraded/poisoned batch pays the conversion exactly once."""
        names = list(cols)
        return [{name: cols[name].python_value(i) for name in names}
                for i in range(n)]

    def _compiled_eligible(self) -> bool:
        if self._degraded_since is None:
            return True
        now = time.monotonic()
        if now - self._last_probe >= self.probe_interval_s:
            self._last_probe = now  # probe with the live batch
            return True
        return False

    def _compiled_dispatch(self, rows: Sequence[dict]) -> list[Any]:
        from transmogrifai_tpu.utils import devicewatch
        from transmogrifai_tpu.utils.faults import fault_point
        attempts = {"n": 0}

        def attempt():
            attempts["n"] += 1
            # chaos seam: injected transient faults exercise the retry
            # path, anything else the degrade-to-row-path machinery —
            # inside attempt() so serving's own retry metrics see it
            fault_point("serving.dispatch")
            cand = self._precision_candidate()
            if cand is not None:
                return self._gated_score(rows, cand)
            return self.scorer.score_batch(rows)

        # devicewatch: one ledger entry + one armed stall deadline per
        # BATCH dispatch (never per request) — a wedged device turns into
        # a device.stall autopsy naming this batch instead of a silent
        # worker hang; cost is two dict ops at batch granularity
        eid = devicewatch.dispatch_ledger.register(
            "serving.dispatch", rows=len(rows), model=self.event_label)
        try:
            with devicewatch.guard("serving.dispatch",
                                   site="serving.dispatch",
                                   rows=len(rows)):
                results = with_device_retry(
                    attempt, retries=self.retries,
                    backoff_s=self.retry_backoff_s)
        finally:
            devicewatch.dispatch_ledger.complete(eid)
            if attempts["n"] > 1:
                self.metrics.record_retry(attempts["n"] - 1)
        self._exit_degraded()
        return list(results)

    # -- precision ladder (dispatcher thread) --------------------------------
    def _precision_candidate(self) -> Optional[str]:
        """The next rung of the configured ladder beyond the active one,
        or None when there is nothing to promote to (ladder floor, or a
        rejection backoff window is still open). Called once per compiled
        dispatch attempt — the f32-only default returns None on the
        first comparison, costing nothing."""
        if len(self._ladder) <= 1:
            return None
        active = self.scorer.precision
        try:
            i = self._ladder.index(active)
        except ValueError:
            return None
        if i + 1 >= len(self._ladder):
            return None
        if self._precision_backoff_left > 0:
            self._precision_backoff_left -= 1
            return None
        return self._ladder[i + 1]

    def _set_precision(self, precision: str) -> str:
        """Flip the active rung on BOTH compiled lanes (the explain
        lane's attributions must be computed at the precision the scores
        were served at). Returns the previous rung."""
        prev = self.scorer.set_precision(precision)
        if self.explainer is not None:
            self.explainer.set_precision(precision)
        return prev

    def _gated_score(self, rows: Sequence[dict], cand: str) -> list:
        """The shadow gate: score the batch on the live f32 master lane,
        shadow-score the SAME rows at the candidate rung, and promote
        only when the max ``fleet.score_diff`` is within tolerance.
        A rejected (or crashed, or NaN-scoring) candidate serves the f32
        results BIT-IDENTICALLY — the gate can never cost a request — and
        opens a ``precision_backoff``-dispatch window before retrying.
        Harness errors surface (a chaos plan at ``serving.precision``
        exercises exactly this rejection path via non-harness kinds)."""
        from transmogrifai_tpu.serving.fleet import score_diff
        from transmogrifai_tpu.utils.faults import (
            FaultHarnessError, fault_point,
        )
        ref = self.scorer.score_batch(rows, precision="f32")
        out = None
        try:
            fault_point("serving.precision")
            out = self.scorer.score_batch(rows, precision=cand)
            diff = max((score_diff(a, b) for a, b in zip(ref, out)),
                       default=0.0)
        except FaultHarnessError:
            raise
        except Exception as e:  # noqa: BLE001 — a crashing candidate is a rejection
            diff = float("inf")
            events.emit("serving.precision_error", model=self.event_label,
                        precision=cand,
                        error=f"{type(e).__name__}: {str(e)[:200]}")
        if diff <= self.precision_tolerance and out is not None:
            self._set_precision(cand)
            self.metrics.record_precision(cand, promoted=True)
            events.emit("serving.precision_promoted",
                        model=self.event_label, precision=cand,
                        scoreDiff=round(diff, 9),
                        tolerance=self.precision_tolerance)
            return out
        self.metrics.record_precision(cand, rejected=True)
        self._precision_backoff_left = self.precision_backoff
        events.emit("serving.precision_rejected", model=self.event_label,
                    precision=cand,
                    scoreDiff=None if diff == float("inf")
                    else round(diff, 9),
                    tolerance=self.precision_tolerance,
                    backoffDispatches=self.precision_backoff)
        return ref

    def demote_precision(self) -> Optional[str]:
        """Force one precision-ladder demotion without an exception in
        hand — the FLEET pressure path's entry point (the tier store's
        shed prefers degrading every lane's quality one rung over
        COLD-paging a tenant out). Returns the new rung or None at the
        ladder floor."""
        return self._demote_precision(None, 0)

    def _demote_precision(self, err: Optional[BaseException],
                          n_rows: int) -> Optional[str]:
        """The resource ladder's precision rung — taken BEFORE any
        bucket is shed: advance the active rung one step down the
        configured ladder WITHOUT the shadow gate (pressure cannot wait
        for a parity check), evict the demoted-from rung's programs so
        their accounted HBM actually releases, and let the caller retry
        the same batch. Returns the new rung, or None at the ladder
        floor (then buckets shed as before)."""
        from transmogrifai_tpu.utils.resources import record_degradation
        active = self.scorer.precision
        try:
            i = self._ladder.index(active)
        except ValueError:
            return None
        if i + 1 >= len(self._ladder):
            return None
        nxt = self._ladder[i + 1]
        prev = self._set_precision(nxt)
        freed = self.scorer.evict_precision(prev)
        if self.explainer is not None:
            freed += self.explainer.evict_precision(prev)
        self.metrics.record_precision(nxt, demoted=True)
        record_degradation(
            "serving.dispatch", f"demote_precision_{nxt}", error=err,
            model=self.event_label, rows=n_rows, evicted=freed)
        return nxt

    def _exit_degraded(self) -> None:
        """A compiled-path success while degraded IS the recovery —
        shared by the probe path and the OOM-shed rung (whose success
        proves the compiled path good at the smaller shape)."""
        if self._degraded_since is not None:
            down_s = time.monotonic() - self._degraded_since
            self._degraded_since = None
            self.metrics.record_recovery()
            events.emit("serving.degraded_exit", model=self.event_label,
                        downSeconds=round(down_s, 3))
            warnings.warn(
                f"serving: compiled path recovered after {down_s:.1f}s "
                "degraded", RuntimeWarning)

    def _shed_and_retry(self, rows: Sequence[dict],
                        err: BaseException) -> Optional[list]:
        """The serving degradation ladder (utils/resources.py): when the
        compiled dispatch died of a genuine allocation failure, shed HBM
        — evict the coldest half of the shared compiled-program cache
        (other models' idle buckets before anyone's live traffic), drop
        this scorer's largest padding bucket — and retry the SAME batch
        compiled at the smaller shape, rung by rung down to the smallest
        bucket. Returns the batch's results, or None when the rungs are
        exhausted (caller then row-serves; zero requests dropped either
        way). Runs on the dispatcher thread; every rung is counted,
        event-logged, and spanned."""
        from transmogrifai_tpu.utils.resources import (
            is_resource_exhausted, ladder_enabled, record_degradation,
        )
        from transmogrifai_tpu.utils.tracing import span
        if not ladder_enabled() or not is_resource_exhausted(err):
            return None
        # precision rung FIRST: a narrower rung keeps every padding
        # bucket (full batch shapes, no re-splitting) while roughly
        # halving the live working set — strictly gentler than shedding
        # a bucket. Only when the ladder floor is reached (or the rung
        # still OOMs) does bucket shedding start.
        while True:
            demoted = self._demote_precision(err, len(rows))
            if demoted is None:
                break
            try:
                with span("resource.degrade", site="serving.dispatch",
                          rung=f"demote_precision_{demoted}",
                          rows=len(rows)):
                    return list(self.scorer.score_batch(rows))
            except Exception as e:  # noqa: BLE001 — next rung / fall through to shed
                from transmogrifai_tpu.utils.faults import (
                    FaultHarnessError,
                )
                if isinstance(e, FaultHarnessError):
                    raise
                if not is_resource_exhausted(e):
                    return None
                err = e
        cache = self.scorer.program_cache
        if cache is not None:
            # fleet pressure rung: cold (fingerprint, layer, bucket)
            # entries go first — an idle model's warm programs are
            # cheaper to recompile later than any live request is to slow
            # down now
            cache.evict_cold(cache.current_bytes // 2)
        last = err
        while True:
            shed = self.scorer.shed_largest_bucket()
            if shed is None:
                return None  # bucket floor reached: row path serves
            record_degradation(
                "serving.dispatch", f"shed_bucket_{shed}", error=last,
                model=self.event_label, rows=len(rows),
                bucketsLeft=len(self.scorer.buckets))
            try:
                with span("resource.degrade", site="serving.dispatch",
                          rung=f"shed_bucket_{shed}", rows=len(rows)):
                    return list(self.scorer.score_batch(rows))
            except Exception as e:  # noqa: BLE001 — next rung or give up to the row path
                from transmogrifai_tpu.utils.faults import (
                    FaultHarnessError,
                )
                if isinstance(e, FaultHarnessError):
                    raise
                if not is_resource_exhausted(e):
                    return None
                last = e

    def _enter_degraded(self, err: BaseException) -> None:
        if self._degraded_since is None:
            self._degraded_since = time.monotonic()
            self._last_probe = self._degraded_since
            self.metrics.record_degraded_entry()
            events.emit("serving.degraded_enter", model=self.event_label,
                        error=f"{type(err).__name__}: {str(err)[:200]}")
            warnings.warn(
                "serving: compiled scorer failed "
                f"({type(err).__name__}: {str(err)[:140]}); degrading to "
                "the local row path until a probe succeeds", RuntimeWarning)

    # -- explain dispatch (explain batcher worker thread) --------------------
    def _explain_dispatch(self, rows: Sequence[dict]) -> list[Any]:
        """The explain lane's batch dispatch: compiled forward + LOCO
        program, transient retry, then the ``serving.explain`` resource
        ladder (mask-chunk halving, re-serving the SAME batch at the
        smaller chunk). When every rung is exhausted — or the failure is
        not an allocation — the batch degrades to plain ROW-PATH scores
        with a per-row ``explanationsError`` note: an admitted explain
        request always settles with its score, never drops."""
        from transmogrifai_tpu.utils import devicewatch
        from transmogrifai_tpu.utils.faults import (
            FaultHarnessError, fault_point,
        )
        from transmogrifai_tpu.utils.tracing import span
        t0 = time.monotonic()
        rows = [dict(r) for r in rows]
        ks = [r.pop(_EXPLAIN_K, None) for r in rows]
        attempts = {"n": 0}

        def attempt():
            attempts["n"] += 1
            fault_point("serving.explain")
            docs, exps = self.explainer.explain_batch(rows, top_k=ks)
            for doc, exp in zip(docs, exps):
                doc["explanations"] = exp
            return docs

        degraded = True
        try:
            eid = devicewatch.dispatch_ledger.register(
                "serving.explain", rows=len(rows),
                model=self.event_label)
            try:
                with span("serving.explain_dispatch", rows=len(rows)), \
                        devicewatch.guard("serving.explain",
                                          site="serving.explain",
                                          rows=len(rows)):
                    results = with_device_retry(
                        attempt, retries=self.retries,
                        backoff_s=self.retry_backoff_s)
                degraded = False
            finally:
                devicewatch.dispatch_ledger.complete(eid)
                if attempts["n"] > 1:
                    self.explain_metrics.record_retry(attempts["n"] - 1)
        except FaultHarnessError:
            raise
        except Exception as e:  # noqa: BLE001 — ladder rungs, then row-path floor
            results = self._explain_shed_and_retry(rows, ks, e)
            if results is not None:
                degraded = False
            else:
                self.explain_metrics.record_degraded_entry()
                events.emit("serving.explain_degraded",
                            model=self.event_label,
                            error=f"{type(e).__name__}: {str(e)[:200]}")
                note = f"{type(e).__name__}: {str(e)[:200]}"
                results = []
                for r in self._row_dispatch(rows):
                    if isinstance(r, BaseException):
                        results.append(r)
                    else:
                        doc = dict(r)
                        doc["explanations"] = None
                        doc["explanationsError"] = note
                        results.append(doc)
        self.explain_metrics.record_batch(
            len(rows), time.monotonic() - t0, degraded=degraded)
        return results

    def _explain_shed_and_retry(self, rows: Sequence[dict], ks,
                                err: BaseException) -> Optional[list]:
        """The explain degradation ladder: on a genuine allocation
        failure, halve the LOCO mask-chunk width (the masked-input peak
        halves with it) and re-serve the SAME batch, rung by rung down
        to chunk 1. Returns results or None when exhausted."""
        from transmogrifai_tpu.utils.resources import (
            is_resource_exhausted, ladder_enabled, record_degradation,
        )
        from transmogrifai_tpu.utils.tracing import span
        if not ladder_enabled() or not is_resource_exhausted(err):
            return None
        last = err
        while True:
            chunk = self.explainer.shrink_mask_chunk()
            if chunk is None:
                return None  # chunk floor: the row-path score serves
            record_degradation(
                "serving.explain", f"mask_chunk_{chunk}", error=last,
                model=self.event_label, rows=len(rows))
            try:
                with span("resource.degrade", site="serving.explain",
                          rung=f"mask_chunk_{chunk}", rows=len(rows)):
                    docs, exps = self.explainer.explain_batch(
                        rows, top_k=ks)
                for doc, exp in zip(docs, exps):
                    doc["explanations"] = exp
                return docs
            except Exception as e:  # noqa: BLE001 — next rung or give up to the row path
                from transmogrifai_tpu.utils.faults import (
                    FaultHarnessError,
                )
                if isinstance(e, FaultHarnessError):
                    raise
                if not is_resource_exhausted(e):
                    return None
                last = e

    def _row_dispatch(self, rows: Sequence[dict]) -> list[Any]:
        from transmogrifai_tpu.utils.tracing import span
        out: list[Any] = []
        with span("serving.row_dispatch", rows=len(rows)):
            for r in rows:
                try:
                    out.append(self.row_score(r))
                except Exception as e:  # noqa: BLE001 — isolate per-row faults
                    out.append(e)
        return out

    # -- observability -------------------------------------------------------
    def snapshot(self, mirror_to_profiler: bool = True) -> dict:
        doc = self.metrics.snapshot(mirror_to_profiler=mirror_to_profiler)
        doc["config"] = {
            "maxBatch": self.scorer.max_batch,
            "buckets": list(self.scorer.buckets),
            "maxWaitMs": self.batcher.max_wait_s * 1e3,
            "queueCapacity": self.batcher.queue_capacity,
            "strict": self.strict,
            "retries": self.retries,
            "probeIntervalSeconds": self.probe_interval_s,
            "donate": self.scorer.donate,
            "precision": {
                "target": self.precision_target,
                "active": self.scorer.precision,
                "ladder": list(self._ladder),
                "tolerance": self.precision_tolerance,
            },
        }
        doc["degraded"]["active"] = self.degraded
        doc["state"] = self.state
        doc["postWarmupCompiles"] = {
            str(b): n for b, n in self.post_warmup_compiles().items()}
        if self.explain_metrics is not None:
            xdoc = self.explain_metrics.snapshot(mirror_to_profiler=False)
            xdoc["config"] = {
                "topK": self.explainer.top_k,
                "maskChunk": self.explainer.mask_chunk,
                "groups": self.explainer.n_groups,
            }
            xdoc["postWarmupCompiles"] = {
                str(b): n
                for b, n in self.post_warmup_explain_compiles().items()}
            doc["explain"] = xdoc
        return doc
