"""Shared event-loop HTTP/1.1 core (stdlib asyncio, no dependencies).

One implementation of the wire behavior that serving/http.py,
scaleout/router.py and scaleout/stub_worker.py used to copy-paste
around ``BaseHTTPRequestHandler`` — header parsing, bounded bodies,
keep-alive, and the error statuses that keep a persistent connection
from desyncing:

- **HTTP/1.1 keep-alive** by default: a router or load harness reuses
  one connection per replica instead of paying a TCP handshake per
  request. Every reply carries ``Content-Length``; replies that could
  leave an unread body on the socket (413 and friends) close the
  connection instead of desyncing it.
- **bounded buffering**: request bodies are refused 413 above
  ``max_body_bytes`` WITHOUT reading, chunked bodies 411 (no
  ``Content-Length`` means no bound), malformed/negative lengths 400.
- **event loop, not thread-per-connection**: a single daemon thread
  runs an asyncio loop; N idle keep-alive connections cost N parked
  coroutines, not N parked OS threads. Handlers are async; legacy
  blocking callbacks (a fleet's ``score_fn`` blocking on a batcher
  future) run on the server's bounded thread pool via
  :meth:`AsyncHTTPServer.run_blocking`.
- ``TCP_NODELAY`` on every connection: replies are single small
  documents; a delayed-ACK stall per request is pure loss.
- **slow-client defenses** (the netchaos failure domain): the first
  request line must arrive within ``idle_timeout_s`` (idle keep-alive
  reaping), and once it does the REST of the request — headers and
  body — must complete within ``read_timeout_s`` or the client is shed
  with 408 and a hard teardown. A slowloris trickling one byte per
  second therefore holds exactly one connection slot for one deadline,
  never pins the event loop, and never starves framed traffic.
- **write deadlines**: every reply ``drain()`` is bounded by
  ``write_timeout_s``; a dead or black-holed peer gets its transport
  aborted instead of parking a coroutine (and its buffer) forever.
- **bounded accept**: at most ``max_connections`` concurrent
  connections; excess connects are shed with ``503 + Retry-After``
  instead of queueing unboundedly behind a flood.

The public surface mirrors the old servers': synchronous ``start()`` /
``stop()`` and a ``port`` property, so owners (MetricsServer, Router,
the stub worker) keep their APIs unchanged.

This module also hosts the two tiny network-robustness primitives the
rest of the data plane shares (they must stay importable from the
jax-free stub worker): :data:`net_counters`, the process-global
``transmogrifai_net_*`` accounting every Prometheus registry exports,
and :class:`DedupeRing`, the idempotency-key ring replicas use so a
router's retried frame is never double-scored (see docs/WIRE.md).

Deliberately jax-free and framework-free: the stub worker imports this
plus ``scaleout/wire.py`` and nothing else.
"""

from __future__ import annotations

import asyncio
import socket
import threading
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Optional

__all__ = ["AsyncHTTPServer", "Request", "Response", "DedupeRing",
           "NetCounters", "net_counters", "DEFAULT_MAX_BODY_BYTES"]

#: default request-body bound (bytes) — one JSON request row or one
#: columnar frame, with slack
DEFAULT_MAX_BODY_BYTES = 1 << 20

#: request line + headers may not exceed this many bytes total
MAX_HEADER_BYTES = 32 << 10

_REASON = {200: "OK", 400: "Bad Request", 404: "Not Found",
           408: "Request Timeout", 409: "Conflict",
           411: "Length Required", 413: "Request Entity Too Large",
           500: "Internal Server Error", 503: "Service Unavailable",
           504: "Gateway Timeout"}

#: Retry-After advertised by the connection gate's 503 shed
SHED_RETRY_AFTER_S = 1


class NetCounters:
    """Process-global network-robustness accounting, exported as
    ``transmogrifai_net_*`` on EVERY Prometheus registry (the network
    failure domain is process-wide, like the flight recorder's own
    counters). Plain attribute increments — GIL-atomic, same idiom as
    the serving metrics objects."""

    FIELDS = ("accepted", "shed_connections", "slow_clients_shed",
              "idle_closed", "write_timeouts", "faults_injected",
              "dedupe_hits", "dedupe_waits", "hedges", "resets_retried",
              "refusals_spilled")

    def __init__(self) -> None:
        for f in self.FIELDS:
            setattr(self, f, 0)

    def to_json(self) -> dict:
        out = {}
        for f in self.FIELDS:
            head, *rest = f.split("_")
            out[head + "".join(p.title() for p in rest)] = \
                getattr(self, f)
        return out


#: the process-global instance (import and increment; never re-bind)
net_counters = NetCounters()


def _emit_net(kind: str, **attrs) -> None:
    """Flight-recorder emission, imported lazily so the stub worker's
    import set stays tiny and a broken recorder can't break the wire."""
    try:
        from transmogrifai_tpu.utils.events import events
        events.emit(kind, **attrs)
    except Exception:  # noqa: BLE001 — observability must not break serving
        pass


class _DedupeEntry:
    __slots__ = ("event", "response")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.response: Optional[Response] = None


class DedupeRing:
    """Bounded idempotency-key ring: ``request_id -> cached 2xx reply``.

    A router that retries a mid-request reset cannot know whether the
    upstream already scored the frame — the reply may have died on the
    wire AFTER the work was done. Replicas therefore keep this small
    ring keyed by the request's idempotency key (``X-Request-Id``
    header / frame-meta ``request_id``): a retried frame is answered
    from the ring instead of being scored twice, and a retry racing the
    original waits for the in-flight result instead of double-running
    it.

    Only SUCCESSFUL (cached) executions count toward ``scored`` — so a
    fleet-wide ``sum(scored) == distinct requests`` equality is the
    bench's proof of zero double-scores AND zero drops. Failed attempts
    are abandoned (entry removed, waiters released) so the client's
    retry can re-execute legitimately.
    """

    def __init__(self, capacity: int = 512):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _DedupeEntry]" = OrderedDict()
        self.hits = 0        # answered from cache
        self.waits = 0       # coalesced onto an in-flight execution
        self.scored = 0      # actual completed executions
        self.evicted = 0

    def begin(self, request_id: str):
        """Claim ``request_id``. Returns one of:

        - ``("mine", entry)`` — caller executes, then MUST call
          :meth:`complete` or :meth:`abandon` with the entry;
        - ``("hit", response)`` — a finished duplicate: reply directly;
        - ``("wait", entry)`` — an in-flight duplicate: wait on
          ``entry.event`` (off-loop!), then re-check ``entry.response``.
        """
        with self._lock:
            e = self._entries.get(request_id)
            if e is None:
                e = _DedupeEntry()
                self._entries[request_id] = e
                while len(self._entries) > self.capacity:
                    # evict oldest COMPLETED entry; skip in-flight ones
                    for k, old in self._entries.items():
                        if old.response is not None or old is e:
                            break
                    if old is e:  # ring full of in-flight work: give up
                        break
                    del self._entries[k]
                    self.evicted += 1
                return ("mine", e)
            if e.response is not None:
                self.hits += 1
                net_counters.dedupe_hits += 1
                return ("hit", e.response)
            self.waits += 1
            net_counters.dedupe_waits += 1
            return ("wait", e)

    def complete(self, request_id: str, entry: _DedupeEntry,
                 response: "Response") -> None:
        with self._lock:
            entry.response = response
            self.scored += 1
        entry.event.set()

    def abandon(self, request_id: str, entry: _DedupeEntry) -> None:
        """The execution failed before producing a cacheable reply:
        forget the key so a client retry can legitimately re-run."""
        with self._lock:
            if self._entries.get(request_id) is entry:
                del self._entries[request_id]
        entry.event.set()

    def to_json(self) -> dict:
        with self._lock:
            size = len(self._entries)
        return {"hits": self.hits, "waits": self.waits,
                "scored": self.scored, "evicted": self.evicted,
                "size": size, "capacity": self.capacity}


@dataclass
class Request:
    method: str
    target: str                       # raw request target (may carry ?query)
    headers: dict                     # lower-cased header name -> value
    body: bytes = b""

    @property
    def path(self) -> str:
        return self.target.split("?")[0]

    def header(self, name: str, default=None):
        return self.headers.get(name.lower(), default)


@dataclass
class Response:
    status: int = 200
    body: bytes = b""
    ctype: str = "application/json"
    headers: dict = field(default_factory=dict)
    #: close the connection after this reply (error replies that may
    #: leave an unread request body MUST set this)
    close: bool = False

    @staticmethod
    def error(status: int, message: str,
              close: bool = True) -> "Response":
        import json
        body = (json.dumps({"error": message}) + "\n").encode()
        return Response(status, body, "application/json", close=close)


class _BadRequest(Exception):
    """Protocol-level refusal decided before the handler runs."""

    def __init__(self, response: Response):
        self.response = response


class AsyncHTTPServer:
    """One asyncio HTTP/1.1 server on a daemon thread.

    ``handler`` is ``async (Request) -> Response``; it runs on the
    event loop, so anything blocking inside it must go through
    :meth:`run_blocking`. Construction does not bind; ``start()``
    binds (port 0 = ephemeral) and returns once ``port`` is live.
    """

    def __init__(self, handler: Callable[[Request],
                                         Awaitable[Response]],
                 port: int = 0, host: str = "127.0.0.1",
                 max_body_bytes: int = DEFAULT_MAX_BODY_BYTES,
                 name: str = "transmogrifai-http",
                 executor_workers: int = 32,
                 idle_timeout_s: float = 75.0,
                 read_timeout_s: float = 30.0,
                 write_timeout_s: float = 30.0,
                 max_connections: int = 1024):
        self.handler = handler
        self.max_body_bytes = int(max_body_bytes)
        #: keep-alive idle bound: how long a connection may sit between
        #: requests (and how long the FIRST request line may take)
        self.idle_timeout_s = float(idle_timeout_s)
        #: slow-client bound: once the request line lands, the rest of
        #: the request (headers + body) must complete within this
        self.read_timeout_s = float(read_timeout_s)
        self.write_timeout_s = float(write_timeout_s)
        self.max_connections = int(max_connections)
        self._host = host
        self._requested_port = int(port)
        self._name = name
        self._executor_workers = int(executor_workers)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._executor: Optional[ThreadPoolExecutor] = None
        self._port: Optional[int] = None
        self._writers: set = set()

    # -- lifecycle -----------------------------------------------------------
    @property
    def port(self) -> Optional[int]:
        return self._port

    def start(self) -> "AsyncHTTPServer":
        if self._thread is not None:
            return self
        ready = threading.Event()
        boot_err: list = []

        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            self._executor = ThreadPoolExecutor(
                max_workers=self._executor_workers,
                thread_name_prefix=f"{self._name}-blk")

            async def boot():
                try:
                    self._server = await asyncio.start_server(
                        self._serve_connection, self._host,
                        self._requested_port, limit=MAX_HEADER_BYTES)
                    self._port = \
                        self._server.sockets[0].getsockname()[1]
                except Exception as e:  # noqa: BLE001 — surfaced to start()
                    boot_err.append(e)
                finally:
                    ready.set()

            loop.run_until_complete(boot())
            if not boot_err:
                try:
                    loop.run_forever()
                finally:
                    # drain cancelled tasks so their closers run
                    pending = asyncio.all_tasks(loop)
                    for t in pending:
                        t.cancel()
                    if pending:
                        loop.run_until_complete(asyncio.gather(
                            *pending, return_exceptions=True))
            loop.close()

        self._thread = threading.Thread(target=run, name=self._name,
                                        daemon=True)
        self._thread.start()
        ready.wait(timeout=10.0)
        if boot_err:
            self._thread.join(timeout=5.0)
            self._thread = None
            raise boot_err[0]
        if self._port is None:
            raise RuntimeError(f"{self._name}: server failed to bind")
        return self

    def stop(self) -> None:
        loop, thread = self._loop, self._thread
        if loop is None or thread is None:
            return

        def shutdown():
            if self._server is not None:
                self._server.close()
            for w in list(self._writers):
                try:
                    w.close()
                except Exception:  # noqa: BLE001 — already gone
                    pass
            loop.stop()

        loop.call_soon_threadsafe(shutdown)
        thread.join(timeout=5.0)
        if self._executor is not None:
            self._executor.shutdown(wait=False)
        self._loop = None
        self._server = None
        self._thread = None
        self._executor = None
        self._port = None

    def run_blocking(self, fn, *args):
        """Awaitable running ``fn(*args)`` on the server's thread pool —
        the seam for legacy blocking callbacks (render/score/control
        functions that block on locks or batcher futures)."""
        return asyncio.get_running_loop().run_in_executor(
            self._executor, fn, *args)

    # -- protocol ------------------------------------------------------------
    async def _bounded(self, aw, deadline: float):
        """Await ``aw`` under the request's read deadline; a client that
        trickles past it is shed with 408 (counted + flight-recorded)."""
        remaining = deadline - asyncio.get_running_loop().time()
        if remaining > 0:
            try:
                return await asyncio.wait_for(aw, remaining)
            except asyncio.TimeoutError:
                pass
        else:
            # consume the coroutine so asyncio doesn't warn
            asyncio.ensure_future(aw).cancel()
        net_counters.slow_clients_shed += 1
        _emit_net("net.slow_client_shed", reason="read_deadline",
                  server=self._name, timeoutS=self.read_timeout_s)
        raise _BadRequest(Response.error(
            408, f"request not completed within "
                 f"{self.read_timeout_s:g}s"))

    async def _read_request(self, reader) -> Optional[Request]:
        """One request off the stream, or None at clean EOF. Raises
        ``_BadRequest`` carrying the refusal reply for protocol-level
        errors (bad Content-Length, chunked, oversized, slow-client
        deadline). The FIRST line is bounded by the keep-alive idle
        timeout; everything after it by ``read_timeout_s``."""
        try:
            line = await asyncio.wait_for(reader.readline(),
                                          self.idle_timeout_s)
        except asyncio.TimeoutError:
            # nothing (or a partial line) arrived within the idle bound:
            # reap the parked connection silently
            net_counters.idle_closed += 1
            return None
        except (asyncio.LimitOverrunError, ValueError):
            raise _BadRequest(Response.error(
                400, "request line too long")) from None
        if not line:
            return None
        deadline = asyncio.get_running_loop().time() + self.read_timeout_s
        try:
            parts = line.decode("latin-1").rstrip("\r\n").split()
            method, target = parts[0], parts[1]
        except (IndexError, UnicodeDecodeError):
            raise _BadRequest(Response.error(
                400, "malformed request line")) from None
        headers: dict = {}
        total = len(line)
        while True:
            try:
                hline = await self._bounded(reader.readline(), deadline)
            except (asyncio.LimitOverrunError, ValueError):
                raise _BadRequest(Response.error(
                    400, "header line too long")) from None
            total += len(hline)
            if total > MAX_HEADER_BYTES:
                raise _BadRequest(Response.error(
                    400, "request headers too large"))
            if hline in (b"\r\n", b"\n", b""):
                break
            try:
                k, _, v = hline.decode("latin-1").partition(":")
            except UnicodeDecodeError:
                raise _BadRequest(Response.error(
                    400, "malformed header")) from None
            headers[k.strip().lower()] = v.strip()
        if headers.get("transfer-encoding"):
            # an unread chunked body would desync keep-alive; close
            raise _BadRequest(Response.error(
                411, "chunked bodies unsupported; send Content-Length"))
        try:
            n = int(headers.get("content-length", 0))
        except ValueError:
            raise _BadRequest(Response.error(
                400, "malformed Content-Length")) from None
        if n < 0:
            raise _BadRequest(Response.error(
                400, "negative Content-Length"))
        if n > self.max_body_bytes:
            # refused WITHOUT reading: the reply closes the connection,
            # so the unread body can't desync keep-alive
            raise _BadRequest(Response.error(
                413, f"request body {n} bytes exceeds the "
                     f"{self.max_body_bytes}-byte bound"))
        body = b""
        if n:
            try:
                body = await self._bounded(reader.readexactly(n),
                                           deadline)
            except asyncio.IncompleteReadError:
                return None  # client died mid-body: nothing to answer
        return Request(method, target, headers, body)

    @staticmethod
    def _render(resp: Response) -> bytes:
        reason = _REASON.get(resp.status, "Unknown")
        head = [f"HTTP/1.1 {resp.status} {reason}",
                f"Content-Type: {resp.ctype}",
                f"Content-Length: {len(resp.body)}"]
        for k, v in resp.headers.items():
            if k.lower() in ("content-length", "content-type",
                             "connection", "transfer-encoding"):
                continue
            head.append(f"{k}: {v}")
        if resp.close:
            head.append("Connection: close")
        return ("\r\n".join(head) + "\r\n\r\n").encode("latin-1") \
            + resp.body

    async def _drain(self, writer) -> bool:
        """Bounded reply flush. Returns False (after aborting the
        transport) when the peer would not take our bytes within
        ``write_timeout_s`` — the dead-peer / black-holed-client case."""
        try:
            await asyncio.wait_for(writer.drain(), self.write_timeout_s)
            return True
        except asyncio.TimeoutError:
            net_counters.write_timeouts += 1
            _emit_net("net.slow_client_shed", reason="write_deadline",
                      server=self._name, timeoutS=self.write_timeout_s)
            self._abort(writer)
            return False

    @staticmethod
    def _abort(writer) -> None:
        """Hard transport teardown: no lingering buffers for a peer that
        already proved it will not cooperate."""
        try:
            writer.transport.abort()
        except Exception:  # noqa: BLE001 — transport already gone
            pass

    async def _serve_connection(self, reader, writer) -> None:
        sock = writer.get_extra_info("socket")
        if sock is not None:
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                                1)
            except OSError:
                pass
        if len(self._writers) >= self.max_connections:
            # bounded accept: shed instead of queueing unboundedly. The
            # 503 carries Retry-After so well-behaved clients back off.
            net_counters.shed_connections += 1
            _emit_net("net.slow_client_shed", reason="connection_gate",
                      server=self._name, limit=self.max_connections)
            resp = Response.error(
                503, f"connection limit {self.max_connections} reached")
            resp.headers["Retry-After"] = str(SHED_RETRY_AFTER_S)
            try:
                writer.write(self._render(resp))
                await self._drain(writer)
            except (ConnectionError, OSError):
                pass
            finally:
                self._abort(writer)
            return
        net_counters.accepted += 1
        self._writers.add(writer)
        shed = False
        try:
            while True:
                try:
                    req = await self._read_request(reader)
                except _BadRequest as e:
                    shed = e.response.status == 408
                    writer.write(self._render(e.response))
                    await self._drain(writer)
                    break
                if req is None:
                    break
                try:
                    resp = await self.handler(req)
                except Exception as e:  # noqa: BLE001 — a handler crash must not kill the loop
                    resp = Response.error(
                        500, f"{type(e).__name__}: {str(e)[:200]}")
                want_close = resp.close or \
                    req.header("connection", "").lower() == "close"
                resp.close = want_close
                writer.write(self._render(resp))
                if not await self._drain(writer):
                    break
                if want_close:
                    break
        except (ConnectionError, asyncio.CancelledError, OSError):
            pass
        finally:
            self._writers.discard(writer)
            if shed:
                # a shed slow client gets a hard abort so its window of
                # unread bytes can't keep the socket half-alive
                self._abort(writer)
            try:
                writer.close()
            except Exception:  # noqa: BLE001 — socket already dead
                pass
