"""Shared event-loop HTTP/1.1 core (stdlib asyncio, no dependencies).

One implementation of the wire behavior that serving/http.py,
scaleout/router.py and scaleout/stub_worker.py used to copy-paste
around ``BaseHTTPRequestHandler`` — header parsing, bounded bodies,
keep-alive, and the error statuses that keep a persistent connection
from desyncing:

- **HTTP/1.1 keep-alive** by default: a router or load harness reuses
  one connection per replica instead of paying a TCP handshake per
  request. Every reply carries ``Content-Length``; replies that could
  leave an unread body on the socket (413 and friends) close the
  connection instead of desyncing it.
- **bounded buffering**: request bodies are refused 413 above
  ``max_body_bytes`` WITHOUT reading, chunked bodies 411 (no
  ``Content-Length`` means no bound), malformed/negative lengths 400.
- **event loop, not thread-per-connection**: a single daemon thread
  runs an asyncio loop; N idle keep-alive connections cost N parked
  coroutines, not N parked OS threads. Handlers are async; legacy
  blocking callbacks (a fleet's ``score_fn`` blocking on a batcher
  future) run on the server's bounded thread pool via
  :meth:`AsyncHTTPServer.run_blocking`.
- ``TCP_NODELAY`` on every connection: replies are single small
  documents; a delayed-ACK stall per request is pure loss.

The public surface mirrors the old servers': synchronous ``start()`` /
``stop()`` and a ``port`` property, so owners (MetricsServer, Router,
the stub worker) keep their APIs unchanged.

Deliberately jax-free and framework-free: the stub worker imports this
plus ``scaleout/wire.py`` and nothing else.
"""

from __future__ import annotations

import asyncio
import socket
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Optional

__all__ = ["AsyncHTTPServer", "Request", "Response",
           "DEFAULT_MAX_BODY_BYTES"]

#: default request-body bound (bytes) — one JSON request row or one
#: columnar frame, with slack
DEFAULT_MAX_BODY_BYTES = 1 << 20

#: request line + headers may not exceed this many bytes total
MAX_HEADER_BYTES = 32 << 10

_REASON = {200: "OK", 400: "Bad Request", 404: "Not Found",
           409: "Conflict", 411: "Length Required",
           413: "Request Entity Too Large", 500: "Internal Server Error",
           503: "Service Unavailable", 504: "Gateway Timeout"}


@dataclass
class Request:
    method: str
    target: str                       # raw request target (may carry ?query)
    headers: dict                     # lower-cased header name -> value
    body: bytes = b""

    @property
    def path(self) -> str:
        return self.target.split("?")[0]

    def header(self, name: str, default=None):
        return self.headers.get(name.lower(), default)


@dataclass
class Response:
    status: int = 200
    body: bytes = b""
    ctype: str = "application/json"
    headers: dict = field(default_factory=dict)
    #: close the connection after this reply (error replies that may
    #: leave an unread request body MUST set this)
    close: bool = False

    @staticmethod
    def error(status: int, message: str,
              close: bool = True) -> "Response":
        import json
        body = (json.dumps({"error": message}) + "\n").encode()
        return Response(status, body, "application/json", close=close)


class _BadRequest(Exception):
    """Protocol-level refusal decided before the handler runs."""

    def __init__(self, response: Response):
        self.response = response


class AsyncHTTPServer:
    """One asyncio HTTP/1.1 server on a daemon thread.

    ``handler`` is ``async (Request) -> Response``; it runs on the
    event loop, so anything blocking inside it must go through
    :meth:`run_blocking`. Construction does not bind; ``start()``
    binds (port 0 = ephemeral) and returns once ``port`` is live.
    """

    def __init__(self, handler: Callable[[Request],
                                         Awaitable[Response]],
                 port: int = 0, host: str = "127.0.0.1",
                 max_body_bytes: int = DEFAULT_MAX_BODY_BYTES,
                 name: str = "transmogrifai-http",
                 executor_workers: int = 32):
        self.handler = handler
        self.max_body_bytes = int(max_body_bytes)
        self._host = host
        self._requested_port = int(port)
        self._name = name
        self._executor_workers = int(executor_workers)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._executor: Optional[ThreadPoolExecutor] = None
        self._port: Optional[int] = None
        self._writers: set = set()

    # -- lifecycle -----------------------------------------------------------
    @property
    def port(self) -> Optional[int]:
        return self._port

    def start(self) -> "AsyncHTTPServer":
        if self._thread is not None:
            return self
        ready = threading.Event()
        boot_err: list = []

        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            self._executor = ThreadPoolExecutor(
                max_workers=self._executor_workers,
                thread_name_prefix=f"{self._name}-blk")

            async def boot():
                try:
                    self._server = await asyncio.start_server(
                        self._serve_connection, self._host,
                        self._requested_port, limit=MAX_HEADER_BYTES)
                    self._port = \
                        self._server.sockets[0].getsockname()[1]
                except Exception as e:  # noqa: BLE001 — surfaced to start()
                    boot_err.append(e)
                finally:
                    ready.set()

            loop.run_until_complete(boot())
            if not boot_err:
                try:
                    loop.run_forever()
                finally:
                    # drain cancelled tasks so their closers run
                    pending = asyncio.all_tasks(loop)
                    for t in pending:
                        t.cancel()
                    if pending:
                        loop.run_until_complete(asyncio.gather(
                            *pending, return_exceptions=True))
            loop.close()

        self._thread = threading.Thread(target=run, name=self._name,
                                        daemon=True)
        self._thread.start()
        ready.wait(timeout=10.0)
        if boot_err:
            self._thread.join(timeout=5.0)
            self._thread = None
            raise boot_err[0]
        if self._port is None:
            raise RuntimeError(f"{self._name}: server failed to bind")
        return self

    def stop(self) -> None:
        loop, thread = self._loop, self._thread
        if loop is None or thread is None:
            return

        def shutdown():
            if self._server is not None:
                self._server.close()
            for w in list(self._writers):
                try:
                    w.close()
                except Exception:  # noqa: BLE001 — already gone
                    pass
            loop.stop()

        loop.call_soon_threadsafe(shutdown)
        thread.join(timeout=5.0)
        if self._executor is not None:
            self._executor.shutdown(wait=False)
        self._loop = None
        self._server = None
        self._thread = None
        self._executor = None
        self._port = None

    def run_blocking(self, fn, *args):
        """Awaitable running ``fn(*args)`` on the server's thread pool —
        the seam for legacy blocking callbacks (render/score/control
        functions that block on locks or batcher futures)."""
        return asyncio.get_running_loop().run_in_executor(
            self._executor, fn, *args)

    # -- protocol ------------------------------------------------------------
    async def _read_request(self, reader) -> Optional[Request]:
        """One request off the stream, or None at clean EOF. Raises
        ``_BadRequest`` carrying the refusal reply for protocol-level
        errors (bad Content-Length, chunked, oversized)."""
        try:
            line = await reader.readline()
        except (asyncio.LimitOverrunError, ValueError):
            raise _BadRequest(Response.error(
                400, "request line too long")) from None
        if not line:
            return None
        try:
            parts = line.decode("latin-1").rstrip("\r\n").split()
            method, target = parts[0], parts[1]
        except (IndexError, UnicodeDecodeError):
            raise _BadRequest(Response.error(
                400, "malformed request line")) from None
        headers: dict = {}
        total = len(line)
        while True:
            try:
                hline = await reader.readline()
            except (asyncio.LimitOverrunError, ValueError):
                raise _BadRequest(Response.error(
                    400, "header line too long")) from None
            total += len(hline)
            if total > MAX_HEADER_BYTES:
                raise _BadRequest(Response.error(
                    400, "request headers too large"))
            if hline in (b"\r\n", b"\n", b""):
                break
            try:
                k, _, v = hline.decode("latin-1").partition(":")
            except UnicodeDecodeError:
                raise _BadRequest(Response.error(
                    400, "malformed header")) from None
            headers[k.strip().lower()] = v.strip()
        if headers.get("transfer-encoding"):
            # an unread chunked body would desync keep-alive; close
            raise _BadRequest(Response.error(
                411, "chunked bodies unsupported; send Content-Length"))
        try:
            n = int(headers.get("content-length", 0))
        except ValueError:
            raise _BadRequest(Response.error(
                400, "malformed Content-Length")) from None
        if n < 0:
            raise _BadRequest(Response.error(
                400, "negative Content-Length"))
        if n > self.max_body_bytes:
            # refused WITHOUT reading: the reply closes the connection,
            # so the unread body can't desync keep-alive
            raise _BadRequest(Response.error(
                413, f"request body {n} bytes exceeds the "
                     f"{self.max_body_bytes}-byte bound"))
        body = b""
        if n:
            try:
                body = await reader.readexactly(n)
            except asyncio.IncompleteReadError:
                return None  # client died mid-body: nothing to answer
        return Request(method, target, headers, body)

    @staticmethod
    def _render(resp: Response) -> bytes:
        reason = _REASON.get(resp.status, "Unknown")
        head = [f"HTTP/1.1 {resp.status} {reason}",
                f"Content-Type: {resp.ctype}",
                f"Content-Length: {len(resp.body)}"]
        for k, v in resp.headers.items():
            if k.lower() in ("content-length", "content-type",
                             "connection", "transfer-encoding"):
                continue
            head.append(f"{k}: {v}")
        if resp.close:
            head.append("Connection: close")
        return ("\r\n".join(head) + "\r\n\r\n").encode("latin-1") \
            + resp.body

    async def _serve_connection(self, reader, writer) -> None:
        sock = writer.get_extra_info("socket")
        if sock is not None:
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                                1)
            except OSError:
                pass
        self._writers.add(writer)
        try:
            while True:
                try:
                    req = await self._read_request(reader)
                except _BadRequest as e:
                    writer.write(self._render(e.response))
                    await writer.drain()
                    break
                if req is None:
                    break
                try:
                    resp = await self.handler(req)
                except Exception as e:  # noqa: BLE001 — a handler crash must not kill the loop
                    resp = Response.error(
                        500, f"{type(e).__name__}: {str(e)[:200]}")
                want_close = resp.close or \
                    req.header("connection", "").lower() == "close"
                resp.close = want_close
                writer.write(self._render(resp))
                await writer.drain()
                if want_close:
                    break
        except (ConnectionError, asyncio.CancelledError, OSError):
            pass
        finally:
            self._writers.discard(writer)
            try:
                writer.close()
            except Exception:  # noqa: BLE001 — socket already dead
                pass
