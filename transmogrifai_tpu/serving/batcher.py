"""Dynamic micro-batching request queue with bounded admission.

The serving front door: concurrent ``submit(row) -> Future`` calls coalesce
into one device dispatch of up to ``max_batch`` rows. The first request of
a batch waits at most ``max_wait_ms`` for companions — the latency the
batcher is allowed to spend buying throughput. Admission is BOUNDED: when
``queue_capacity`` requests are already waiting, ``submit`` raises
``BackpressureError`` (carrying a ``retry_after_s`` hint sized from the
observed drain rate) instead of buffering without limit — overload sheds
load at the door, it does not grow memory until the process dies. Each
request can carry a deadline; requests that expire while queued complete
exceptionally with ``RequestTimeout`` rather than occupying a batch slot.

The dispatch function returns one result per row (an ``Exception`` instance
marks a per-row failure); the worker settles every future either way — an
accepted request ALWAYS completes, with a value or an error. Fault handling
(retry, degraded mode) lives in ``serving/server.py``; the batcher treats
``dispatch`` as infallible and fails the whole batch's futures if it raises
anyway.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

from transmogrifai_tpu.utils.events import events
from transmogrifai_tpu.utils.tracing import recorder, span

__all__ = ["MicroBatcher", "BackpressureError", "RequestTimeout",
           "absorb_backpressure"]


class BackpressureError(RuntimeError):
    """Admission queue full: retry after ``retry_after_s`` (load shed)."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


def absorb_backpressure(submit_fn: Callable[[], Any],
                        max_wait_s: Optional[float] = None):
    """Run ``submit_fn`` until it stops raising ``BackpressureError``:
    wait out each rejection's retry-after hint (capped at 0.5s per
    attempt, ``max_wait_s`` overall, re-raising at the deadline). The
    ONE client flow-control loop behind ``ScoringServer`` and
    ``FleetServer``'s ``submit_blocking`` — any other admission error
    (strict-validation ``KeyError``, unknown model) raises immediately."""
    deadline = None if max_wait_s is None \
        else time.monotonic() + max_wait_s
    while True:
        try:
            return submit_fn()
        except BackpressureError as e:
            wait = min(e.retry_after_s, 0.5)
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise
                wait = min(wait, remaining)
            time.sleep(wait)


class RequestTimeout(TimeoutError):
    """The request's deadline expired before a batch picked it up."""


@dataclass
class _Pending:
    row: dict
    future: Future
    t_submit: float
    deadline: Optional[float]  # monotonic seconds, None = no deadline
    trace_id: Optional[str] = None  # request-scoped trace context
    #: how many device rows this item contributes to a batch — 1 for a
    #: plain row, n for a columnar wire frame. A full frame must not
    #: sit out max_wait waiting for companions it cannot admit anyway.
    weight: int = 1


@dataclass
class _Stats:
    """Rolling dispatch-rate estimate feeding the retry-after hint."""
    batch_walls: float = 0.0
    batch_rows: int = 0
    lock: threading.Lock = field(default_factory=threading.Lock)

    def record(self, wall_s: float, rows: int) -> None:
        with self.lock:
            # exponential forget so the hint tracks the current regime
            self.batch_walls = 0.9 * self.batch_walls + wall_s
            self.batch_rows = int(0.9 * self.batch_rows) + rows

    def seconds_per_row(self) -> float:
        with self.lock:
            if self.batch_rows <= 0:
                return 1e-3
            return max(self.batch_walls / self.batch_rows, 1e-6)


class MicroBatcher:
    """Single-worker dynamic batcher: queue -> coalesce -> dispatch."""

    def __init__(self, dispatch: Callable[[Sequence[dict]], Sequence[Any]],
                 *, max_batch: int = 256, max_wait_ms: float = 2.0,
                 queue_capacity: int = 1024,
                 default_timeout_ms: Optional[float] = None,
                 on_complete: Optional[
                     Callable[[Sequence[tuple[float, bool]]], None]] = None,
                 on_expired: Optional[Callable[[int], None]] = None):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if queue_capacity < 1:
            raise ValueError("queue_capacity must be >= 1")
        self.dispatch = dispatch
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.queue_capacity = int(queue_capacity)
        self.default_timeout_ms = default_timeout_ms
        #: called once per dispatched batch with [(latency_s, ok), ...] —
        #: one metrics update per batch, not one lock fight per request
        self.on_complete = on_complete
        self.on_expired = on_expired
        self._q: "queue.Queue[_Pending]" = queue.Queue(maxsize=queue_capacity)
        self._stats = _Stats()
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "MicroBatcher":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._drained.clear()
            self._thread = threading.Thread(
                target=self._loop, name="transmogrifai-serving-batcher",
                daemon=True)
            self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Stop the worker. With ``drain`` (default) every already-accepted
        request is dispatched first — a graceful stop drops nothing."""
        if self._thread is None:
            return
        if not drain:  # fail whatever is still queued, then exit
            self._fail_queued()
        self._stop.set()
        self._thread.join(timeout=timeout_s)
        self._thread = None
        # settle anything that slipped in between the worker's final empty
        # check and a racing submit() that had already passed the stop
        # check — an accepted Future must never dangle unsettled forever
        self._fail_queued()

    def _fail_queued(self) -> None:
        try:
            while True:
                p = self._q.get_nowait()
                _settle(p.future, RuntimeError("batcher stopped"))
        except queue.Empty:
            pass

    def __enter__(self) -> "MicroBatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- admission -----------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return self._q.qsize()

    def retry_after_s(self) -> float:
        """Hint: time to drain the current backlog at the observed rate."""
        depth = max(self._q.qsize(), 1)
        return max(depth * self._stats.seconds_per_row(),
                   self.max_wait_s)

    def submit(self, row: dict,
               timeout_ms: Optional[float] = None,
               trace_id: Optional[str] = None,
               weight: int = 1) -> Future:
        """``trace_id`` (optional) rides the request through the queue:
        the worker stamps it into the batch's flight-recorder events and
        the dispatch span's member list, so one id greps the request's
        whole path (admission -> batch -> dispatch -> reply).
        ``weight`` is the item's device-row count (1 for a plain row,
        n for a columnar frame) — it feeds the coalescing bound, so an
        already-full frame dispatches immediately instead of burning
        ``max_wait_ms`` waiting for companions."""
        if self._stop.is_set() or self._thread is None:
            raise RuntimeError("batcher is not running")
        t = time.monotonic()
        timeout_ms = timeout_ms if timeout_ms is not None \
            else self.default_timeout_ms
        deadline = None if timeout_ms is None else t + timeout_ms / 1e3
        pending = _Pending(row=row, future=Future(), t_submit=t,
                           deadline=deadline, trace_id=trace_id,
                           weight=max(int(weight), 1))
        try:
            self._q.put_nowait(pending)
        except queue.Full:
            hint = self.retry_after_s()
            raise BackpressureError(
                f"serving queue full ({self.queue_capacity} waiting); "
                f"retry in ~{hint:.3f}s", retry_after_s=hint) from None
        # close the submit/stop race: if stop() completed between the
        # entry check and the put, the worker is gone and nothing will
        # ever serve this queue — settle it (a still-alive worker drains
        # accepted items itself, and stop() sweeps once more after join)
        t = self._thread
        if self._stop.is_set() and (t is None or not t.is_alive()):
            self._fail_queued()
        return pending.future

    # -- worker --------------------------------------------------------------
    def _collect(self) -> list[_Pending]:
        """Block for the first request, then coalesce companions for up
        to ``max_wait_s`` — or until the batch's WEIGHT (device rows,
        not queue items) reaches ``max_batch``. A frame arriving full
        therefore dispatches with zero coalescing wait."""
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        batch = [first]
        rows = first.weight
        t_end = time.monotonic() + self.max_wait_s
        while rows < self.max_batch:
            # burst-drain whatever is already queued (no condition-variable
            # wait per item — at saturation this is the whole batch)
            try:
                while rows < self.max_batch:
                    p = self._q.get_nowait()
                    batch.append(p)
                    rows += p.weight
            except queue.Empty:
                pass
            if rows >= self.max_batch:
                break
            remaining = t_end - time.monotonic()
            if remaining <= 0:
                break
            try:
                p = self._q.get(timeout=remaining)
                batch.append(p)
                rows += p.weight
            except queue.Empty:
                break
        return batch

    def _loop(self) -> None:
        while True:
            batch = self._collect()
            if not batch:
                if self._stop.is_set() and self._q.empty():
                    break
                continue
            now = time.monotonic()
            live: list[_Pending] = []
            expired = 0
            expired_traced: list[str] = []
            for p in batch:
                if p.deadline is not None and now > p.deadline:
                    expired += 1
                    if p.trace_id is not None:
                        expired_traced.append(p.trace_id)
                    _settle(p.future, RequestTimeout(
                        "request expired after "
                        f"{(now - p.t_submit) * 1e3:.1f}ms in queue"))
                else:
                    live.append(p)
            if expired and self.on_expired is not None:
                self.on_expired(expired)
            if expired_traced and events.enabled:
                events.emit("serve.expired", traceIds=expired_traced)
            if not live:
                continue
            t0 = time.monotonic()
            # the batch's queue-wait as a retroactive span (known only
            # now): oldest admission -> dispatch start, monotonic clock
            # rebased onto the epoch so it aligns with the other spans
            epoch_off = time.time() - t0
            recorder.add("serving.queue_wait",
                         epoch_off + min(p.t_submit for p in live),
                         epoch_off + t0, rows=len(live))
            # request-scoped trace context: requests carrying a trace id
            # get their path recorded as batch-scope wide events (one
            # batch/dispatch/reply event per batch, members listed —
            # per-request emission would cost the hot path ~tens of
            # percent at saturation; amortized member lists stay well
            # under 1us/req). serve.batch carries ONLY the id list (a
            # C-speed comprehension): per-request timing rides in
            # serve.reply's members, built inside the settle loop that
            # already iterates per-pending anyway — admission epoch
            # reconstructs as reply ts - latencyMs, and queue wait as
            # reply latency minus the batch's dispatch wallMs
            traced = [p.trace_id for p in live if p.trace_id is not None]
            if traced and events.enabled:
                events.emit("serve.batch", t=epoch_off + t0,
                            rows=len(live), traceIds=traced)
            span_attrs = {"rows": len(live)}
            if traced:
                # the batch span records its member trace ids: a span
                # drill-down names exactly which requests shared the batch
                span_attrs["trace_ids"] = traced
            try:
                with span("serving.dispatch", **span_attrs):
                    results = list(self.dispatch([p.row for p in live]))
                if len(results) != len(live):
                    raise RuntimeError(
                        f"dispatch returned {len(results)} results for "
                        f"{len(live)} rows")
            except Exception as e:  # noqa: BLE001 — server handles faults;
                results = [e] * len(live)  # this is the belt-and-braces path
            wall = time.monotonic() - t0
            self._stats.record(wall, len(live))
            if traced and events.enabled:
                events.emit("serve.dispatch", rows=len(live),
                            wallMs=round(wall * 1e3, 3), traceIds=traced)
            done_t = time.monotonic()
            settled = []
            with span("serving.settle", rows=len(live)):
                for p, r in zip(live, results):
                    ok = not isinstance(r, BaseException)
                    _settle(p.future, r, is_error=not ok)
                    settled.append((done_t - p.t_submit, ok))
                if self.on_complete is not None:
                    self.on_complete(settled)
            if traced and events.enabled:
                # columnar (traceIds[i] <-> latenciesMs[i]), built after
                # the settle loop, reusing the fan-in id list and raw
                # float ms: per-member [id, ok, round(ms)] rows would
                # triple the list allocations and pay ~150ns/round on
                # this worker thread (digits only cost the background
                # spill writer). The all-traced batch — every HTTP
                # request carries an id — skips the alignment filter.
                if len(traced) == len(live):
                    lats = [s[0] * 1e3 for s in settled]
                else:
                    lats = [lat * 1e3 for p, (lat, ok)
                            in zip(live, settled)
                            if p.trace_id is not None]
                failed = [p.trace_id for p, (lat, ok)
                          in zip(live, settled)
                          if not ok and p.trace_id is not None]
                events.emit("serve.reply", traceIds=traced,
                            latenciesMs=lats, failedIds=failed)
        self._drained.set()


def _settle(future: Future, value: Any, is_error: Optional[bool] = None
            ) -> None:
    """Resolve a future exactly once, tolerating caller-side cancellation."""
    try:
        if is_error or (is_error is None and isinstance(value, BaseException)):
            future.set_exception(value)
        else:
            future.set_result(value)
    except Exception:  # already cancelled/settled: the caller gave up first (failure-ok)
        pass
