"""Model registry: many fitted workflows behind one serving fleet.

Production AutoML serves MANY fitted models (per-tenant, per-scenario,
old/new versions of the same endpoint), not the one-model-one-server
binding of ``ScoringServer``. The registry is the fleet's source of truth:
every registered model is a :class:`ModelEntry` keyed by ``(model_id,
version)`` and identified by the **fingerprint** of its saved checkpoint
(``checkpoint.model_fingerprint`` over the ``save_model`` manifest +
array bytes) — the same key the shared compiled-program cache uses, so
"two registrations of the same checkpoint dir" provably share compiled
entries while schema-identical-but-differently-fitted models provably
don't.

Per model id, exactly one version is **active** (the alias live traffic
routes to). ``promote(model_id, version)`` flips the alias atomically —
one dict assignment under the registry lock — which is the primitive
``FleetServer.hot_swap`` builds zero-downtime promotion on.

Directory layouts ``register_dir`` understands::

    models/
      churn/            # <id>/model.json            -> (churn, v1)
        model.json
      ctr/              # <id>/<version>/model.json  -> (ctr, v1), (ctr, v2)
        v1/model.json
        v2/model.json
        ACTIVE.json     # durable alias: {"version": "v2"} (optional)

**Durable alias** (multi-process serving): an in-memory ``promote`` is
invisible to every OTHER process serving the same directory — a replica
respawned after a fleet-wide rolling promotion would regress to ``v1``.
``write_active_alias``/``read_active_alias`` persist the per-id alias as
``<id>/ACTIVE.json``, written via tmp-file + ``os.replace`` so a
concurrent reader observes either the old or the new alias, NEVER a
torn or truncated one; ``register_dir`` activates the alias's version
when present (falling back to the lowest version with a warning when it
names a version that doesn't exist).

**Program artifacts**: ``attach_artifacts`` binds a fingerprint-keyed
artifact store (``scaleout/artifacts.py``) so compiled-program warmup
recipes publish THROUGH the registry — the cross-process analog of the
in-process ``ProgramCache``: one replica compiles, every replica maps.
"""

from __future__ import annotations

import os
import re
import threading
import time
import warnings
from dataclasses import dataclass, field
from typing import Optional

__all__ = ["ModelEntry", "ModelRegistry", "ModelState",
           "UnknownModelError", "ACTIVE_JSON", "write_active_alias",
           "read_active_alias", "stat_fingerprint"]

#: durable per-model-id active-version alias file (versioned layout)
ACTIVE_JSON = "ACTIVE.json"


def write_active_alias(root: str, model_id: str, version: str) -> str:
    """Persist ``<root>/<model_id>/ACTIVE.json`` atomically (tmp-file +
    rename — concurrent readers can never observe a torn alias).
    Returns the path. The single-writer here is the promotion
    coordinator (a fleet hot-swap, the scale-out rolling roll); replicas
    only read."""
    from transmogrifai_tpu.utils.durable import atomic_json_dump
    id_dir = os.path.join(root, model_id)
    os.makedirs(id_dir, exist_ok=True)
    path = os.path.join(id_dir, ACTIVE_JSON)
    atomic_json_dump({"modelId": model_id, "version": version,
                      "promotedAt": time.time()}, path)
    return path


def read_active_alias(id_dir: str) -> Optional[str]:
    """The durably promoted version of ``<id_dir>/ACTIVE.json``, or None
    (missing file, or corrupt — warn-and-None: a broken alias must not
    keep a replica from serving SOMETHING)."""
    path = os.path.join(id_dir, ACTIVE_JSON)
    try:
        import json
        with open(path) as fh:
            doc = json.load(fh)
        version = doc.get("version")
        return str(version) if version else None
    except FileNotFoundError:
        return None
    except Exception as e:  # noqa: BLE001 — corrupt alias: warn and fall back
        warnings.warn(
            f"registry: unreadable active alias {path!r} "
            f"({type(e).__name__}: {e}); falling back to the lowest "
            "version", RuntimeWarning)
        return None


class ModelState:
    """Lifecycle states a registered model moves through (reported by
    ``/healthz`` and the ``transmogrifai_fleet_model_state`` gauge)."""
    WARMING = "warming"     # registered, padding buckets compiling
    READY = "ready"         # serving on the compiled path
    DEGRADED = "degraded"   # serving on the row path (device fault)
    DRAINING = "draining"   # demoted; finishing in-flight requests
    STOPPED = "stopped"     # fleet stopped; model still loaded
    UNLOADED = "unloaded"   # drained and dropped; kept for audit
    COLD = "cold"           # registered lazily or tier-demoted; pages
    #                       # in (disk -> RAM -> HBM) on first score


class UnknownModelError(KeyError):
    """Routing key names no registered model (or no active version)."""


def stat_fingerprint(path: str) -> str:
    """A ``"lazy:"``-prefixed placeholder fingerprint from the stat
    signature (abspath + size + mtime_ns) of a checkpoint's
    ``model.json`` and ``arrays.npz`` — registering 1000 models must
    not read 1000 array files. The prefix keeps a placeholder from
    EVER colliding with a content fingerprint in the shared
    compiled-program cache; the true ``model_fingerprint`` replaces it
    at first page-in, before anything compiles. Raises
    ``FileNotFoundError`` when the manifest is missing (a lazy register
    still validates the checkpoint EXISTS)."""
    import hashlib

    from transmogrifai_tpu.serialization import ARRAYS_NPZ, MODEL_JSON
    h = hashlib.sha256()
    manifest = os.path.join(path, MODEL_JSON)
    if not os.path.exists(manifest):
        raise FileNotFoundError(
            f"no {MODEL_JSON} under {path!r}: not a saved model dir")
    for name in (MODEL_JSON, ARRAYS_NPZ):
        fpath = os.path.join(path, name)
        try:
            st = os.stat(fpath)
        except OSError:
            continue
        h.update(f"{os.path.abspath(fpath)}|{st.st_size}|"
                 f"{st.st_mtime_ns}\n".encode())
    return "lazy:" + h.hexdigest()[:16]


@dataclass
class ModelEntry:
    """One registered fitted workflow."""
    model_id: str
    version: str
    path: Optional[str]       # None for in-memory registrations
    fingerprint: str
    model: object = field(repr=False, default=None)
    state: str = ModelState.WARMING
    registered_at: float = field(default_factory=time.time)

    def to_json(self) -> dict:
        return {"modelId": self.model_id, "version": self.version,
                "path": self.path, "fingerprint": self.fingerprint,
                "state": self.state, "registeredAt": self.registered_at}


class ModelRegistry:
    """Thread-safe ``(model_id, version) -> ModelEntry`` store with an
    atomic per-id active-version alias."""

    def __init__(self):
        self._lock = threading.RLock()
        #: model_id -> {version: ModelEntry}
        self._entries: dict[str, dict[str, ModelEntry]] = {}
        #: model_id -> active version (the alias live traffic follows)
        self._active: dict[str, str] = {}
        #: fingerprint-keyed program-artifact store (scaleout/artifacts.
        #: ArtifactStore-shaped: publish/get); None = not attached
        self.artifacts = None
        #: RAM-tier store (tenancy.TieredModelStore-shaped:
        #: note_unloaded); None = no tiering
        self.tier_store = None
        #: bumps on every mutation (register/promote/unload/state
        #: change via touch) — the invalidation key for rendered-list
        #: and /healthz caches, so a 1000-model fleet is not O(n) JSON
        #: per probe
        self._seq = 0
        self._list_cache: Optional[tuple[int, list[dict]]] = None

    # -- program artifacts ---------------------------------------------------
    def attach_artifacts(self, store) -> "ModelRegistry":
        """Bind a program-artifact store so compiled-program warmup
        recipes publish through the registry (compile-once,
        map-everywhere across replica processes)."""
        self.artifacts = store
        return self

    def publish_program_artifact(self, fingerprint: str,
                                 doc: dict) -> Optional[str]:
        """Publish one model's compiled-program artifact manifest
        (no-op returning None without an attached store)."""
        if self.artifacts is None:
            return None
        return self.artifacts.publish(fingerprint, doc)

    def program_artifact(self, fingerprint: str) -> Optional[dict]:
        if self.artifacts is None:
            return None
        return self.artifacts.get(fingerprint)

    def attach_tier_store(self, store) -> "ModelRegistry":
        """Bind the RAM-tier store so explicit ``unload`` releases the
        tier's accounted bytes (and the model's compiled programs) —
        not just the entry's model reference."""
        self.tier_store = store
        return self

    # -- mutation sequence ---------------------------------------------------
    @property
    def mutation_seq(self) -> int:
        with self._lock:
            return self._seq

    def touch(self) -> int:
        """Bump the mutation sequence (and drop the rendered-list
        cache). Callers that mutate entry state OUTSIDE registry
        methods — the fleet flipping ``entry.state``, a tier demotion
        dropping ``entry.model`` — must touch so cached ``/healthz``
        blocks invalidate."""
        with self._lock:
            self._seq += 1
            self._list_cache = None
            return self._seq

    # -- registration --------------------------------------------------------
    def register(self, path: Optional[str] = None, *,
                 model=None, model_id: Optional[str] = None,
                 version: Optional[str] = None,
                 activate: Optional[bool] = None,
                 lazy: bool = False) -> ModelEntry:
        """Load (``path``: a ``serialization.save_model`` dir) or adopt
        (``model``: an in-memory fitted workflow) one model. ``model_id``
        defaults to the dir basename; ``version`` to the next ``v<n>``
        for that id. The FIRST version of an id activates automatically;
        later versions stay inactive until :meth:`promote` (or
        ``activate=True``) — registering a candidate never moves live
        traffic by itself.

        ``lazy=True`` (path registrations only) records the entry COLD:
        the checkpoint is stat-validated but NOTHING is read — no
        ``np.load``, no manifest parse — and the fingerprint is a
        stat-derived placeholder until first page-in resolves the true
        content fingerprint. This is what lets ``register_dir`` admit
        thousands of tenant dirs in milliseconds."""
        from transmogrifai_tpu.checkpoint import model_fingerprint
        if path is None and model is None:
            raise ValueError("register() needs a path or a model")
        if path is not None:
            if lazy and model is None:
                fingerprint = stat_fingerprint(path)
            else:
                from transmogrifai_tpu.workflow import load_model
                fingerprint = model_fingerprint(path=path)
                if model is None:
                    model = load_model(path)
            if model_id is None:
                base = os.path.basename(os.path.normpath(path))
                # <id>/<version>/ layout: the version dir is not the id
                model_id = base
        else:
            fingerprint = model_fingerprint(model=model)
            if model_id is None:
                raise ValueError("in-memory register() needs a model_id")
        with self._lock:
            versions = self._entries.setdefault(model_id, {})
            if version is None:
                # next AFTER the highest existing v<n> — a count-based
                # name collides whenever versions aren't dense v1..vN
                # (retired versions deleted, unload(forget=True))
                highest = 0
                for v in versions:
                    m = re.match(r"^v(\d+)$", v)
                    if m:
                        highest = max(highest, int(m.group(1)))
                version = f"v{max(highest, len(versions)) + 1}"
            if version in versions:
                raise ValueError(
                    f"model {model_id!r} version {version!r} is already "
                    f"registered (fingerprint "
                    f"{versions[version].fingerprint})")
            entry = ModelEntry(model_id=model_id, version=version,
                               path=path, fingerprint=fingerprint,
                               model=model)
            if entry.model is None:
                entry.state = ModelState.COLD
            versions[version] = entry
            if activate or (activate is None
                            and model_id not in self._active):
                self._active[model_id] = version
            self._seq += 1
            self._list_cache = None
            return entry

    def register_dir(self, root: str, *,
                     lazy: bool = False) -> list[ModelEntry]:
        """Register every fingerprinted checkpoint under ``root`` (flat
        ``<id>/model.json`` or versioned ``<id>/<version>/model.json``
        layouts; see module docstring). Version subdirs register in
        sorted order, so ``v1`` activates and later versions await
        promotion — unless a durable ``ACTIVE.json`` alias names the
        promoted version, in which case THAT version activates (the
        respawned-replica path: a fleet-wide rolling promotion must
        survive any one process's restart). Returns the new entries.

        ``lazy=True`` registers every checkpoint COLD (stat only, zero
        array reads — see :meth:`register`): the thousand-tenant
        startup path."""
        from transmogrifai_tpu.serialization import MODEL_JSON
        if os.path.exists(os.path.join(root, MODEL_JSON)):
            return [self.register(root, lazy=lazy)]

        def version_key(name: str):
            # NATURAL order: lexical sort puts v10 before v2, and the
            # first registered version auto-activates — a ten-version
            # history must not silently route live traffic to the
            # newest unpromoted candidate on restart
            m = re.match(r"^v(\d+)$", name)
            return (0, int(m.group(1)), name) if m else (1, 0, name)

        entries: list[ModelEntry] = []
        for sub in sorted(os.listdir(root)):
            subdir = os.path.join(root, sub)
            if not os.path.isdir(subdir):
                continue
            if os.path.exists(os.path.join(subdir, MODEL_JSON)):
                entries.append(self.register(
                    subdir, model_id=sub, lazy=lazy))
                continue
            registered: list[str] = []
            for ver in sorted(os.listdir(subdir), key=version_key):
                vdir = os.path.join(subdir, ver)
                if os.path.exists(os.path.join(vdir, MODEL_JSON)):
                    entries.append(self.register(
                        vdir, model_id=sub, version=ver, lazy=lazy))
                    registered.append(ver)
            alias = read_active_alias(subdir) if registered else None
            if alias is not None:
                if alias in registered:
                    self.promote(sub, alias)
                else:
                    warnings.warn(
                        f"registry: ACTIVE.json of {sub!r} names "
                        f"unregistered version {alias!r} (have "
                        f"{registered}); keeping the lowest version "
                        "active", RuntimeWarning)
        return entries

    # -- lookup --------------------------------------------------------------
    def get(self, model_id: str,
            version: Optional[str] = None) -> ModelEntry:
        """The entry for ``version`` (default: the active alias)."""
        with self._lock:
            versions = self._entries.get(model_id)
            if not versions:
                raise UnknownModelError(
                    f"unknown model {model_id!r}; registered: "
                    f"{sorted(self._entries) or 'none'}")
            if version is None:
                version = self._active.get(model_id)
                if version is None:
                    raise UnknownModelError(
                        f"model {model_id!r} has no active version")
            entry = versions.get(version)
            if entry is None:
                raise UnknownModelError(
                    f"model {model_id!r} has no version {version!r}; "
                    f"registered: {sorted(versions)}")
            return entry

    def active_version(self, model_id: str) -> Optional[str]:
        with self._lock:
            return self._active.get(model_id)

    def fingerprint_in_use(self, fingerprint: str) -> bool:
        """True while ANY loaded entry (any id, any version) carries
        this fingerprint — its shared compiled-cache entries are still
        someone's warm programs and must not be evicted on unload."""
        with self._lock:
            return any(e.fingerprint == fingerprint and e.model is not None
                       for versions in self._entries.values()
                       for e in versions.values())

    def model_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._entries)

    def list(self) -> list[dict]:
        """Every registered version, active-flagged — the inventory the
        CLI and ``/healthz`` report. The rendered block is CACHED
        against the mutation sequence: at 1000+ models a fresh O(n)
        JSON render per health probe is what a scraper notices, and
        between mutations the answer cannot change. Callers get a
        shallow per-doc copy (mutating a returned doc must not poison
        the cache)."""
        with self._lock:
            cached = self._list_cache
            if cached is not None and cached[0] == self._seq:
                return [dict(doc) for doc in cached[1]]
            out = []
            for model_id in sorted(self._entries):
                active = self._active.get(model_id)
                for version in sorted(self._entries[model_id]):
                    doc = self._entries[model_id][version].to_json()
                    doc["active"] = version == active
                    out.append(doc)
            self._list_cache = (self._seq, out)
            return [dict(doc) for doc in out]

    # -- lifecycle -----------------------------------------------------------
    def promote(self, model_id: str, version: str) -> tuple:
        """Atomically flip the active alias of ``model_id`` to
        ``version``. Returns ``(old_version, new_version)`` — the old
        may equal the new (idempotent re-promote) or be None (first
        activation)."""
        with self._lock:
            if version not in self._entries.get(model_id, {}):
                raise UnknownModelError(
                    f"cannot promote {model_id!r} to unregistered "
                    f"version {version!r}")
            old = self._active.get(model_id)
            self._active[model_id] = version
            self._seq += 1
            self._list_cache = None
            return old, version

    def unload(self, model_id: str, version: Optional[str] = None,
               forget: bool = False) -> ModelEntry:
        """Release ``version`` (default: active): drop the model object
        (the fitted arrays — the memory that matters) and mark the entry
        UNLOADED, keeping its metadata for audit unless ``forget``.
        Unloading the active version clears the alias — routing to the
        id fails until another version is promoted."""
        entry = self.get(model_id, version)
        with self._lock:
            entry.model = None
            entry.state = ModelState.UNLOADED
            if self._active.get(model_id) == entry.version:
                del self._active[model_id]
            if forget:
                self._entries[model_id].pop(entry.version, None)
                if not self._entries[model_id]:
                    del self._entries[model_id]
            self._seq += 1
            self._list_cache = None
        if self.tier_store is not None:
            # AFTER entry.model dropped: the tier must release its
            # accounted bytes and the fingerprint's compiled programs
            # (when no other loaded entry shares it) — an unload that
            # only clears the reference leaks the RAM-tier budget
            self.tier_store.note_unloaded(entry)
        return entry
