"""Multi-model serving fleet: shared compiled-program cache, routing,
and zero-downtime hot-swap.

``ScoringServer`` binds one fitted workflow to one endpoint;
``FleetServer`` puts MANY behind one front door:

- **routing**: ``submit(model_id, row)`` resolves the id through the
  :class:`~transmogrifai_tpu.serving.registry.ModelRegistry`'s active
  alias to that model's **lane** — a full ``ScoringServer`` (its own
  ``MicroBatcher`` admission queue, deadlines, backpressure,
  ``ServingMetrics``, graceful degradation), so one overloaded or
  degraded model never blocks another's queue.
- **shared compiled-program cache**: every lane's fused layer programs
  live in ONE :class:`ProgramCache` — an LRU keyed ``(model
  fingerprint, layer, padding bucket)`` with explicit HBM budget
  accounting (the serving generalization of the sweep's
  ``tree_stack_bytes`` guard): models loaded from the same checkpoint
  share entries; schema-identical but differently-fitted models can't
  collide; and when the working set exceeds the budget the
  least-recently-dispatched (model, bucket) entry is evicted (counted
  per model in ``ServingCounters.evictions``) instead of HBM growing
  with fleet size.
- **zero-downtime hot-swap**: :meth:`FleetServer.hot_swap` warms a new
  version behind the live alias, optionally **shadow-scores** recent
  live rows on both versions (a parity gate: promotion aborts — old
  version untouched — if scores diverge beyond tolerance), then flips
  the alias atomically and drains the old lane to completion. In-flight
  requests on the old version all settle; zero dropped requests, by
  construction and by chaos test (fault site ``serving.swap``).

Observability: a ``fleet.swap`` span per promotion, swap/parity/eviction
counters in ``/metrics`` (``transmogrifai_fleet_*`` plus every serving
series labeled ``model=...``), and per-model readiness in ``/healthz``.
See ``docs/SERVING.md`` ("Serving fleet").
"""

from __future__ import annotations

import collections
import math
import os
import threading
import time
import warnings
from typing import Any, Callable, Optional, Sequence

from transmogrifai_tpu.serving.registry import (
    ModelEntry, ModelRegistry, ModelState, UnknownModelError,
)
from transmogrifai_tpu.serving.server import ScoringServer
from transmogrifai_tpu.utils.events import events

__all__ = ["FleetServer", "FleetMetrics", "ProgramCache",
           "ShadowParityError", "UnknownModelError"]

#: fleet-wide compiled-program HBM budget (bytes) when the caller doesn't
#: pass one; unset = accounted but unbounded
HBM_BUDGET_ENV = "TRANSMOGRIFAI_SERVING_HBM_BUDGET"


class ShadowParityError(RuntimeError):
    """The shadow-scoring gate failed: the candidate version's scores
    diverge from the live version's beyond tolerance. The swap was
    aborted and the OLD version keeps serving, untouched."""

    def __init__(self, msg: str, max_abs_diff: float):
        super().__init__(msg)
        self.max_abs_diff = float(max_abs_diff)


class _CacheEntry:
    __slots__ = ("program", "bytes", "counters", "bucket")

    def __init__(self, program, nbytes, counters, bucket):
        self.program = program
        self.bytes = int(nbytes)
        self.counters = counters
        self.bucket = bucket


class ProgramCache:
    """Cross-model LRU over compiled serving programs with HBM budget
    accounting.

    One entry per ``(model fingerprint, layer, padding bucket)`` — the
    granularity at which serving compiles — each carrying the scorer's
    byte estimate for its resident footprint. ``get`` returns the cached
    program or inserts ``factory()``; an insertion is counted as one
    compile on the owning scorer's ``ServingCounters`` (per-bucket
    program instances trace exactly once, on first dispatch). When
    ``budget_bytes`` is set and the accounted total exceeds it, oldest
    entries are evicted (never the one just inserted) and the eviction
    is attributed to the EVICTED entry's owner — the model whose next
    dispatch at that bucket will recompile.

    Thread-safe: lanes dispatch concurrently. Eviction only drops the
    cache's reference — a dispatch already holding the program finishes
    unharmed.
    """

    def __init__(self, budget_bytes: Optional[int] = None):
        if budget_bytes is None:
            env = os.environ.get(HBM_BUDGET_ENV)
            budget_bytes = int(float(env)) if env else None
        self.budget_bytes = budget_bytes
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[Any, _CacheEntry]" = \
            collections.OrderedDict()
        self.current_bytes = 0
        self.hits = 0
        self.insertions = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def keys(self) -> list:
        with self._lock:
            return list(self._entries)

    def get(self, key, factory: Callable[[], Any], *, bytes_est=0,
            counters=None, bucket: Optional[int] = None):
        """``bytes_est`` may be an int or a zero-arg callable — pass a
        thunk when the estimate itself costs something (walking a big
        model's param pytree): it is only evaluated on a miss, keeping
        the steady-state hit path one dict probe."""
        evicted: list[_CacheEntry] = []
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                if counters is not None:
                    # attribution follows the CURRENT user: an entry
                    # inserted by a throwaway prewarm scorer must charge
                    # its eventual eviction to the live lane now serving
                    # on it, not to a discarded counters object
                    entry.counters = counters
                return entry.program
            if callable(bytes_est):
                bytes_est = bytes_est()
            entry = _CacheEntry(factory(), bytes_est, counters, bucket)
            self._entries[key] = entry
            self.insertions += 1
            self.current_bytes += entry.bytes
            if counters is not None and bucket is not None:
                counters.count(bucket, compiles=1)
            if self.budget_bytes is not None:
                # never evict the entry just inserted: a budget smaller
                # than one program still serves (it just can't cache)
                while self.current_bytes > self.budget_bytes \
                        and len(self._entries) > 1:
                    _, old = self._entries.popitem(last=False)
                    self.current_bytes -= old.bytes
                    self.evictions += 1
                    evicted.append(old)
            program = entry.program
        for old in evicted:  # attribute outside the lock
            if old.counters is not None and old.bucket is not None:
                old.counters.count(old.bucket, evictions=1)
        return program

    def evict_bucket(self, fingerprint: Optional[str], bucket: int) -> int:
        """Drop every entry of one (model, padding bucket) — the shed
        rung of the serving degradation ladder releases the bucket's
        accounted HBM immediately. ``fingerprint=None`` sheds the bucket
        across ALL models (fleet-wide pressure)."""
        evicted: list[_CacheEntry] = []
        with self._lock:
            for key in [k for k in self._entries
                        if isinstance(k, tuple) and len(k) == 3
                        and k[2] == bucket
                        and (fingerprint is None or k[0] == fingerprint)]:
                old = self._entries.pop(key)
                self.current_bytes -= old.bytes
                self.evictions += 1
                evicted.append(old)
        for old in evicted:
            if old.counters is not None and old.bucket is not None:
                old.counters.count(old.bucket, evictions=1)
        return len(evicted)

    def evict_matching(self, pred: Callable[[Any], bool]) -> int:
        """Drop every entry whose key satisfies ``pred`` — the explain
        lane's mask-chunk rung uses this to release the superseded
        chunk's programs (their accounted HBM must free NOW, that is the
        rung's whole point). Evictions attribute to each entry's owner
        like every other eviction path."""
        evicted: list[_CacheEntry] = []
        with self._lock:
            for key in [k for k in self._entries if pred(k)]:
                old = self._entries.pop(key)
                self.current_bytes -= old.bytes
                self.evictions += 1
                evicted.append(old)
        for old in evicted:
            if old.counters is not None and old.bucket is not None:
                old.counters.count(old.bucket, evictions=1)
        return len(evicted)

    def evict_cold(self, bytes_to_free: int) -> int:
        """Evict least-recently-dispatched entries until at least
        ``bytes_to_free`` accounted bytes are released (or one entry
        remains — the cache never empties itself under pressure: the
        live lane's current program must survive). The under-pressure
        analog of the budget LRU, callable without a budget configured.
        Returns the bytes actually freed."""
        freed = 0
        evicted: list[_CacheEntry] = []
        with self._lock:
            while freed < bytes_to_free and len(self._entries) > 1:
                _, old = self._entries.popitem(last=False)
                self.current_bytes -= old.bytes
                self.evictions += 1
                freed += old.bytes
                evicted.append(old)
        for old in evicted:
            if old.counters is not None and old.bucket is not None:
                old.counters.count(old.bucket, evictions=1)
        return freed

    def evict_model(self, fingerprint: str) -> int:
        """Drop every entry of one model (an unload releases its share
        of the budget immediately instead of waiting for LRU aging).
        Keyed entries are ``(fingerprint, layer, bucket)`` tuples."""
        n = 0
        with self._lock:
            for key in [k for k in self._entries
                        if isinstance(k, tuple) and k
                        and k[0] == fingerprint]:
                old = self._entries.pop(key)
                self.current_bytes -= old.bytes
                n += 1
        return n

    def to_json(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "bytes": self.current_bytes,
                    "budgetBytes": self.budget_bytes,
                    "hits": self.hits,
                    "insertions": self.insertions,
                    "evictions": self.evictions}


class FleetMetrics:
    """Fleet-lifecycle counters (per-request metrics live on each lane's
    ``ServingMetrics``): swaps, aborted swaps, shadow-parity failures."""

    def __init__(self):
        self._lock = threading.Lock()
        self.swaps = 0
        self.swap_failures = 0
        self.shadow_parity_failures = 0
        self.models_registered = 0
        self.models_unloaded = 0
        self.swap_wall_s = 0.0
        self.last_swap_at: Optional[float] = None

    def record_registered(self) -> None:
        with self._lock:
            self.models_registered += 1

    def record_unloaded(self) -> None:
        with self._lock:
            self.models_unloaded += 1

    def record_swap(self, wall_s: float) -> None:
        with self._lock:
            self.swaps += 1
            self.swap_wall_s += wall_s
            self.last_swap_at = time.time()

    def record_swap_failure(self, parity: bool = False) -> None:
        with self._lock:
            self.swap_failures += 1
            if parity:
                self.shadow_parity_failures += 1

    def to_json(self) -> dict:
        with self._lock:
            return {"swaps": self.swaps,
                    "swapFailures": self.swap_failures,
                    "shadowParityFailures": self.shadow_parity_failures,
                    "modelsRegistered": self.models_registered,
                    "modelsUnloaded": self.models_unloaded,
                    "swapWallSeconds": round(self.swap_wall_s, 6),
                    "lastSwapAt": self.last_swap_at}


def _nan_inf(x: float) -> float:
    """NaN compares False against everything, so a plain ``max``/``>``
    chain would let a NaN-scoring candidate SLIP THROUGH the parity
    gate — the exact model the gate exists to block. Any NaN diff is
    +inf: never promotable."""
    return float("inf") if math.isnan(x) else x


def score_diff(a: dict, b: dict) -> float:
    """Max abs numeric difference between two score documents (the shadow
    gate's comparator). Mismatched keys or shapes compare as +inf — a
    candidate whose result schema changed can never pass the gate — and
    so does any NaN on either side."""
    if set(a) != set(b):
        return float("inf")
    d = 0.0
    for k, av in a.items():
        bv = b[k]
        if isinstance(av, dict) or isinstance(bv, dict):
            if not (isinstance(av, dict) and isinstance(bv, dict)):
                return float("inf")
            d = max(d, score_diff(av, bv))
        elif isinstance(av, (list, tuple)) or isinstance(bv, (list, tuple)):
            if not (isinstance(av, (list, tuple))
                    and isinstance(bv, (list, tuple))) or len(av) != len(bv):
                return float("inf")
            d = max(d, max((_nan_inf(abs(float(x) - float(z)))
                            for x, z in zip(av, bv)), default=0.0))
        elif av is None or bv is None:
            if av is not bv:
                return float("inf")
        elif isinstance(av, str) or isinstance(bv, str):
            if av != bv:
                return float("inf")
        else:
            d = max(d, _nan_inf(abs(float(av) - float(bv))))
    return d


class FleetServer:
    """Many fitted workflows behind one endpoint: registry-routed
    per-model lanes over one shared compiled-program cache.

    Usage::

        fleet = FleetServer(cache_hbm_budget=2 << 30)
        fleet.register("models/churn")            # -> (churn, v1), active
        fleet.register("models/ctr")
        fleet.start(warmup_rows={"churn": row_a, "ctr": row_b})
        fut = fleet.submit("churn", {"age": 31.0, ...})
        fleet.hot_swap("churn", "models/churn_retrained")  # zero downtime
        fleet.stop()
    """

    def __init__(self, registry: Optional[ModelRegistry] = None, *,
                 cache_hbm_budget: Optional[int] = None,
                 shadow_rows: int = 16, shadow_tolerance: float = 1e-4,
                 shadow_timeout_s: float = 30.0,
                 http_timeout_s: float = 30.0,
                 recent_rows: int = 64,
                 route_field: str = "model",
                 metrics_port: Optional[int] = None,
                 metrics_host: str = "127.0.0.1",
                 access_log_sample: float = 0.0,
                 slo=None,
                 wire: str = "binary",
                 tenancy=None,
                 **lane_kwargs):
        """``lane_kwargs`` (``max_batch``, ``max_wait_ms``,
        ``queue_capacity``, ``default_timeout_ms``, ``strict``,
        ``retries``, ``probe_interval_s``, ``donate``, ...) configure
        every per-model ``ScoringServer`` lane. ``slo`` (a list of
        ``utils.slo.SLObjective``/dicts, a config path, or a prebuilt
        ``SLOEngine``) evaluates burn-rate objectives over the whole
        fleet's lanes; firing fast-burn alerts flip ``/healthz``
        readiness. ``wire`` (default ``"binary"``) keeps the HTTP
        endpoint negotiating the binary columnar frame wire alongside
        JSON/NDJSON; ``wire="json"`` pins the endpoint JSON-only
        (``application/x-tmog-frame`` POSTs answer 400) for operators
        who must guarantee no binary clients.

        ``tenancy`` (a ``tenancy.TenancyConfig``, or ``True`` for the
        defaults) turns on multi-tenant tiering: lazy COLD
        registration, demand paging (disk -> RAM -> HBM) on first
        score, a byte-budgeted host-RAM tier that demotes
        least-recently-scored models, per-tenant token-bucket
        admission in front of lane backpressure, and popularity-driven
        prewarm."""
        bad = {"metrics_port", "metrics_host", "program_cache",
               "fingerprint", "event_label", "slo"} & set(lane_kwargs)
        if bad:
            raise ValueError(f"lane kwargs {sorted(bad)} are fleet-managed")
        self.registry = registry if registry is not None else ModelRegistry()
        self.program_cache = ProgramCache(cache_hbm_budget)
        self.metrics = FleetMetrics()
        self.shadow_rows = int(shadow_rows)
        self.shadow_tolerance = float(shadow_tolerance)
        self.shadow_timeout_s = float(shadow_timeout_s)
        #: client-facing POST /score result-wait bound — its OWN knob:
        #: the shadow bound sizes an internal swap step, and widening
        #: one must not silently widen the other
        self.http_timeout_s = float(http_timeout_s)
        self.route_field = route_field
        if wire not in ("binary", "json"):
            raise ValueError(f"wire must be 'binary' or 'json', "
                             f"got {wire!r}")
        self.wire = wire
        self._lane_kwargs = dict(lane_kwargs)
        self._lock = threading.RLock()
        #: (model_id, version) -> ScoringServer lane
        self._lanes: dict[tuple, ScoringServer] = {}
        #: per-model hot-swap mutual exclusion: two racing swaps of one
        #: id would both promote (last alias write wins) and leak the
        #: loser's running lane + pinned arrays
        self._swap_locks: dict[str, threading.Lock] = {}
        #: model_id -> ring of recently admitted rows (shadow-gate feed)
        self._recent: dict[str, collections.deque] = {}
        self._recent_rows = int(recent_rows)
        self._started = False
        self.metrics_http = None
        self._metrics_port = metrics_port
        self._metrics_host = metrics_host
        self._access_log_sample = float(access_log_sample)
        #: fleet-wide SLO engine: availability/latency objectives sum
        #: over every ACTIVE lane (counter resets at hot-swap lane drops
        #: are clamped by the engine's delta accounting)
        self.slo_engine = None
        if slo is not None:
            from transmogrifai_tpu.utils.slo import SLOEngine
            self.slo_engine = SLOEngine.for_serving(
                slo, lambda: [lane.metrics
                              for lane in self.active_lanes().values()])
        #: /healthz static fragment (models without a running lane),
        #: cached against the registry mutation sequence — at 1000+
        #: registered models re-rendering every COLD entry per probe is
        #: the O(n) the scraper notices
        self._health_static: Optional[tuple] = None
        #: multi-tenant tiering (None = classic eager fleet)
        self.tenancy = None
        self.tenancy_store = None
        self.admission = None
        self.popularity = None
        self._prewarm_daemon = None
        if tenancy:
            from transmogrifai_tpu.tenancy import (
                PopularityTracker,
                TenancyConfig,
                TenantAdmission,
                TieredModelStore,
            )
            cfg = TenancyConfig() if tenancy is True else tenancy
            self.tenancy = cfg
            if getattr(cfg, "precision", "f32") != "f32" \
                    and "precision" not in self._lane_kwargs:
                self._lane_kwargs["precision"] = cfg.precision
            self.tenancy_store = TieredModelStore(
                self.registry, self.program_cache,
                ram_budget_bytes=cfg.ram_budget_bytes,
                on_demote=self._demote_lane,
                on_precision_demote=self._demote_fleet_precision)
            if cfg.rate_per_s:
                self.admission = TenantAdmission(
                    cfg.rate_per_s, cfg.burst, weights=cfg.weights)
            self.popularity = PopularityTracker(cfg.half_life_s)

    # -- registration --------------------------------------------------------
    def _lazy_default(self, lazy: Optional[bool]) -> bool:
        if lazy is None:
            return bool(self.tenancy is not None and self.tenancy.lazy
                        and self.tenancy_store is not None)
        if lazy and self.tenancy_store is None:
            raise ValueError(
                "lazy registration needs tenancy enabled (a COLD entry "
                "only becomes servable through demand paging)")
        return lazy

    def register(self, path: Optional[str] = None, *, model=None,
                 model_id: Optional[str] = None,
                 version: Optional[str] = None,
                 warmup_row: Optional[dict] = None,
                 lazy: Optional[bool] = None) -> ModelEntry:
        """Register one model (see ``ModelRegistry.register``). If the
        fleet is already serving and the new version becomes the active
        one (first version of its id), its lane starts — warmed with
        ``warmup_row`` when given — before this returns. ``lazy``
        defaults to the tenancy config's policy (False without
        tenancy): a lazily registered model is COLD — stat-validated
        only, no lane — and pages in on first score."""
        entry = self.registry.register(path, model=model,
                                       model_id=model_id, version=version,
                                       lazy=self._lazy_default(lazy))
        self.metrics.record_registered()
        if self._started and entry.model is not None and \
                self.registry.active_version(entry.model_id) == entry.version:
            self._start_lane(entry, warmup_row=warmup_row)
        return entry

    def register_dir(self, root: str, *,
                     lazy: Optional[bool] = None) -> list[ModelEntry]:
        """Register every fingerprinted checkpoint under ``root``
        (``ModelRegistry.register_dir`` layouts). ``lazy`` as in
        :meth:`register` — the thousand-tenant startup registers COLD
        in milliseconds and pages in on demand."""
        entries = self.registry.register_dir(
            root, lazy=self._lazy_default(lazy))
        for entry in entries:
            self.metrics.record_registered()
            if self._started and entry.model is not None \
                    and self.registry.active_version(
                        entry.model_id) == entry.version:
                self._start_lane(entry)
        return entries

    def _make_lane(self, entry: ModelEntry) -> ScoringServer:
        return ScoringServer(entry.model,
                             program_cache=self.program_cache,
                             fingerprint=entry.fingerprint,
                             event_label=entry.model_id,
                             **self._lane_kwargs)

    def prewarm(self, model_id: str, version: Optional[str] = None,
                row: Optional[dict] = None) -> list:
        """Compile an INACTIVE version's padding-bucket programs into the
        shared cache without routing any traffic to it — the operator's
        prep step before :meth:`hot_swap`. Because cache entries are
        keyed by the model fingerprint, the candidate's lane later warms
        on pure cache hits: the swap's serving-visible CPU burst (jit
        trace + XLA compile racing live dispatches) moves to whenever
        the operator chooses. ``row`` defaults to the model's newest
        live row. Returns the buckets warmed."""
        from transmogrifai_tpu.serving.compiled import CompiledScorer
        entry = self.registry.get(model_id, version)
        if entry.model is None:
            raise ValueError(
                f"version {entry.version!r} of {model_id!r} is unloaded")
        if row is None:
            recent = self._recent.get(model_id)
            if not recent:
                raise ValueError(
                    f"prewarm of {model_id!r} needs a row (no live "
                    "traffic seen yet)")
            row = dict(recent[-1])
        kw = {k: v for k, v in self._lane_kwargs.items()
              if k in ("max_batch", "min_bucket", "donate")}
        # precision-ladder fleets prewarm EVERY rung the lanes may
        # promote/demote to: a post-swap rung transition must be a pure
        # cache hit, exactly like a post-swap score
        from transmogrifai_tpu.utils.precision import ladder_for
        rungs = ladder_for(self._lane_kwargs.get("precision", "f32"))
        precisions = rungs if len(rungs) > 1 else None
        scorer = CompiledScorer(entry.model,
                                program_cache=self.program_cache,
                                fingerprint=entry.fingerprint, **kw)
        warmed = scorer.warmup(row, precisions=precisions)
        if self._lane_kwargs.get("explain"):
            # explain-enabled fleets prewarm the candidate's explain
            # programs too — a post-swap explain request must be a pure
            # cache hit, exactly like a post-swap score
            from transmogrifai_tpu.serving.explain import CompiledExplainer
            explainer = CompiledExplainer(
                entry.model, program_cache=self.program_cache,
                fingerprint=entry.fingerprint,
                top_k=int(self._lane_kwargs.get("explain_top_k", 5)),
                mask_chunk=self._lane_kwargs.get("explain_mask_chunk"),
                **kw)
            explainer.warmup(row, precisions=precisions)
        return warmed

    def _start_lane(self, entry: ModelEntry,
                    warmup_row: Optional[dict] = None) -> ScoringServer:
        lane = self._make_lane(entry)
        entry.state = ModelState.WARMING
        lane.start(warmup_row=warmup_row)
        entry.state = ModelState.READY
        with self._lock:
            self._lanes[(entry.model_id, entry.version)] = lane
        self.registry.touch()
        return lane

    # -- demand paging (tenancy) ---------------------------------------------
    def _page_in(self, entry: ModelEntry) -> ScoringServer:
        """Walk a COLD entry up the residency ladder — disk -> RAM
        (``tenancy_store.touch``: checkpoint load + true-fingerprint
        resolution) -> HBM (lane start; programs compile lazily on
        first dispatch) — and return the running lane. Single-flighted
        per ``(model_id, version)`` on the store's page lock; the
        measured wall is the model's COLD-START latency (the
        first-score SLA). A resource-exhausted lane start sheds the RAM
        tier once and retries — tier demotion is the pressure rung that
        runs BEFORE giving up on a tenant."""
        from transmogrifai_tpu.utils.resources import (
            is_resource_exhausted, record_degradation,
        )
        from transmogrifai_tpu.utils.tracing import span
        store = self.tenancy_store
        key = (entry.model_id, entry.version)
        with store.page_lock(key):
            with self._lock:
                lane = self._lanes.get(key)
                if lane is not None:
                    return lane
            t0 = time.monotonic()
            with span("tenancy.cold_start", model=entry.model_id,
                      version=entry.version):
                store.touch(entry)
                try:
                    lane = self._start_lane(entry)
                except Exception as e:
                    if not is_resource_exhausted(e):
                        raise
                    budget = store.ram_budget_bytes or store.ram_bytes
                    record_degradation(
                        "tenancy.page_in", "shed_retry", error=e,
                        model=entry.model_id)
                    store.shed(max(budget // 4, 1))
                    lane = self._start_lane(entry)
            wall = time.monotonic() - t0
            store.metrics.note_promotion_hbm()
            store.metrics.note_cold_start(wall)
            if self.admission is not None:
                self.admission.metrics.note_cold_start_wait(wall)
            events.emit("tenancy.cold_start", model=entry.model_id,
                        version=entry.version,
                        wallMs=round(wall * 1e3, 3))
            return lane

    def _demote_fleet_precision(self) -> int:
        """The fleet pressure path's PRECISION rung (the tier store's
        ``on_precision_demote`` hook, called at the top of ``shed``):
        demote every active lane one rung down its configured precision
        ladder — each eviction of the demoted-from rung's programs
        releases accounted HBM while every tenant keeps serving.
        Returns the program-cache bytes released (0 when no lane had a
        rung left to give — the store then COLD-pages as before)."""
        before = self.program_cache.current_bytes
        demoted = 0
        for lane in self.active_lanes().values():
            if lane.demote_precision() is not None:
                demoted += 1
        if not demoted:
            return 0
        freed = max(before - self.program_cache.current_bytes, 0)
        events.emit("fleet.precision_demoted", lanes=demoted,
                    bytesFreed=freed)
        return freed

    def _demote_lane(self, entry: ModelEntry) -> None:
        """Tier-store demotion hook (called under the victim's page
        lock): drop the victim's lane from routing first, then drain it
        — every admitted request settles before the model object goes
        away. Demotion is load shedding, not an outage."""
        with self._lock:
            lane = self._lanes.pop((entry.model_id, entry.version), None)
        if lane is None:
            return
        entry.state = ModelState.DRAINING
        lane.stop(drain=True)
        self.registry.touch()

    def ensure_hot(self, model_id: str,
                   version: Optional[str] = None) -> bool:
        """Page ``model_id``'s active (or named) version in NOW if it
        is COLD — the prewarm daemon's entry point, also useful ahead
        of a known traffic shift. True when a page-in happened."""
        if self.tenancy_store is None or not self._started:
            return False
        if version is None:
            version = self.registry.active_version(model_id)
            if version is None:
                return False
        with self._lock:
            if (model_id, version) in self._lanes:
                return False
        try:
            entry = self.registry.get(model_id, version)
        except UnknownModelError:
            return False
        if entry.state == ModelState.UNLOADED or (
                entry.model is None and entry.path is None):
            return False
        self._page_in(entry)
        return True

    # -- lifecycle -----------------------------------------------------------
    def start(self, warmup_rows: Optional[dict] = None) -> "FleetServer":
        """Start a lane for every model's ACTIVE version (inactive
        versions stay cold until promoted). ``warmup_rows`` maps model
        id -> one representative row to pre-compile that lane's padding
        buckets before traffic."""
        warmup_rows = warmup_rows or {}
        self._started = True
        for model_id in self.registry.model_ids():
            version = self.registry.active_version(model_id)
            if version is None:
                continue
            entry = self.registry.get(model_id, version)
            if entry.model is None:
                # COLD (lazy/demoted) entries start no lane here: a
                # 1000-model fleet starting in bounded time is the
                # point — first score (or prewarm) pages them in
                continue
            if (model_id, version) not in self._lanes:
                self._start_lane(entry, warmup_row=warmup_rows.get(model_id))
        if self.tenancy is not None and self.tenancy.prewarm_top_k > 0 \
                and self._prewarm_daemon is None:
            from transmogrifai_tpu.tenancy import PrewarmDaemon
            self._prewarm_daemon = PrewarmDaemon(
                self, self.popularity,
                top_k=self.tenancy.prewarm_top_k,
                interval_s=self.tenancy.prewarm_interval_s).start()
        if self._metrics_port is not None and self.metrics_http is None:
            from transmogrifai_tpu.serving.http import MetricsServer
            from transmogrifai_tpu.utils.prometheus import build_registry
            registry = build_registry(fleet=self, slo=self.slo_engine)
            self.metrics_http = MetricsServer(
                render_fn=registry.render, health_fn=self.health,
                score_fn=self._http_score,
                frame_fn=self._http_frame
                if self.wire == "binary" else None,
                port=self._metrics_port, host=self._metrics_host,
                access_log_sample=self._access_log_sample).start()
        return self

    def stop(self, drain: bool = True) -> None:
        if self._prewarm_daemon is not None:
            self._prewarm_daemon.stop()
            self._prewarm_daemon = None
        with self._lock:
            lanes = dict(self._lanes)
            # drop the lane objects: their worker threads are about to
            # die, and a later start() must build FRESH lanes (the
            # "(id, version) not in _lanes" guard would otherwise skip
            # restarting them, leaving a "started" fleet whose every
            # submit hits a dead batcher)
            self._lanes.clear()
        for (model_id, version), lane in lanes.items():
            try:
                entry = self.registry.get(model_id, version)
            except UnknownModelError:
                entry = None
            if entry is not None:
                entry.state = ModelState.DRAINING
            lane.stop(drain=drain)
            if entry is not None:
                # a clean shutdown must not read as an in-progress
                # drain forever: the model stays loaded, just unserved
                entry.state = ModelState.STOPPED
        self._started = False
        self.registry.touch()
        if self.metrics_http is not None:
            self.metrics_http.stop()
            self.metrics_http = None

    def __enter__(self) -> "FleetServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- routing -------------------------------------------------------------
    def _resolve(self, model_id: str) -> tuple:
        with self._lock:
            version = self.registry.active_version(model_id)
            if version is None:
                # raises UnknownModelError with the precise reason
                self.registry.get(model_id)
            lane = self._lanes.get((model_id, version))
            if lane is not None:
                return lane, version
        # no running lane: with tenancy, a registered-but-COLD model is
        # a PAGE-IN, not an error — the miss walks disk -> RAM -> HBM
        # (outside the fleet lock: a cold start must not stall routing
        # of every hot model)
        if self.tenancy_store is not None and self._started:
            entry = self.registry.get(model_id, version)
            if entry.state != ModelState.UNLOADED and (
                    entry.model is not None or entry.path is not None):
                return self._page_in(entry), version
        raise UnknownModelError(
            f"model {model_id!r} version {version!r} has no "
            "running lane (fleet not started?)")

    def _remember(self, model_id: str, row: dict) -> None:
        ring = self._recent.get(model_id)
        if ring is None:
            ring = self._recent.setdefault(
                model_id, collections.deque(maxlen=self._recent_rows))
        ring.append(row)

    def submit(self, model_id: str, row: dict,
               timeout_ms: Optional[float] = None,
               trace_id: Optional[str] = None):
        """Route one request to ``model_id``'s active version. Raises
        ``UnknownModelError`` (no such id / no active version),
        ``KeyError`` (strict admission) or ``BackpressureError`` (that
        lane's queue is full) — per-model backpressure: one hot model
        sheds load without touching its neighbors' queues."""
        return self._submit_routed(model_id, row, timeout_ms,
                                   trace_id)[0]

    def submit_explain(self, model_id: str, row: dict,
                       top_k: Optional[int] = None,
                       timeout_ms: Optional[float] = None,
                       trace_id: Optional[str] = None):
        """Route one EXPLAIN request (score + top-K LOCO attributions) to
        ``model_id``'s active version's explain lane. Requires the fleet
        to be built with ``explain=True`` in the lane kwargs."""
        return self._submit_routed(model_id, row, timeout_ms, trace_id,
                                   explain=True, top_k=top_k)[0]

    def submit_frame(self, model_id: str, frame,
                     timeout_ms: Optional[float] = None,
                     trace_id: Optional[str] = None):
        """Route one decoded binary wire frame
        (``wireformat.WireFrame`` of batched columns) to ``model_id``'s
        active version — the columnar analog of :meth:`submit`. The
        future resolves to ``("columns", {name: values})`` on the
        column fast path, or ``("rows", [doc | exception, ...])`` when
        the batch fell back to the row lane."""
        return self._submit_frame_routed(model_id, frame, timeout_ms,
                                         trace_id)[0]

    def _submit_routed(self, model_id: str, row: dict,
                       timeout_ms: Optional[float] = None,
                       trace_id: Optional[str] = None,
                       explain: bool = False,
                       top_k: Optional[int] = None) -> tuple:
        """``submit`` that also returns which version admitted the
        request — the lineage a reply must carry is the version that
        SCORED it, which during a hot swap is not necessarily the
        version that is active when the reply is assembled."""
        # popularity BEFORE admission: a throttled tenant is still
        # demand, and the prewarm ranking must see it
        if self.popularity is not None:
            self.popularity.record(model_id)
        if self.admission is not None:
            self.admission.admit(model_id)
        for _ in range(8):
            lane, version = self._resolve(model_id)
            try:
                if explain:
                    fut = lane.submit_explain(row, top_k=top_k,
                                              timeout_ms=timeout_ms,
                                              trace_id=trace_id)
                else:
                    fut = lane.submit(row, timeout_ms=timeout_ms,
                                      trace_id=trace_id)
            except RuntimeError:
                # the lane stopped between resolve and submit — a swap
                # demoted it (the alias flips BEFORE the old lane drains,
                # so a re-resolve lands on the new version). Anything
                # else is a real error: re-raise.
                if self.registry.active_version(model_id) == version:
                    raise
                continue
            self._remember(model_id, row)
            return fut, version
        raise RuntimeError(
            f"model {model_id!r}: could not route (lanes kept stopping)")

    def submit_blocking(self, model_id: str, row: dict,
                        timeout_ms: Optional[float] = None,
                        max_wait_s: Optional[float] = None,
                        trace_id: Optional[str] = None):
        """``submit`` that absorbs backpressure (the shared
        ``batcher.absorb_backpressure`` loop)."""
        from transmogrifai_tpu.serving.batcher import absorb_backpressure
        return absorb_backpressure(
            lambda: self.submit(model_id, row, timeout_ms=timeout_ms,
                                trace_id=trace_id),
            max_wait_s=max_wait_s)

    def submit_explain_blocking(self, model_id: str, row: dict,
                                top_k: Optional[int] = None,
                                timeout_ms: Optional[float] = None,
                                max_wait_s: Optional[float] = None,
                                trace_id: Optional[str] = None):
        """``submit_explain`` that absorbs backpressure."""
        from transmogrifai_tpu.serving.batcher import absorb_backpressure
        return absorb_backpressure(
            lambda: self.submit_explain(model_id, row, top_k=top_k,
                                        timeout_ms=timeout_ms,
                                        trace_id=trace_id),
            max_wait_s=max_wait_s)

    def score(self, model_id: str, row: dict,
              timeout_s: Optional[float] = None,
              trace_id: Optional[str] = None) -> dict:
        return self.submit(model_id, row,
                           trace_id=trace_id).result(timeout=timeout_s)

    def lineage(self, model_id: str,
                version: Optional[str] = None) -> dict:
        """A serving model's lineage — ``(modelId, version,
        fingerprint)`` of ``version`` (default: the ACTIVE one): which
        exact fitted checkpoint scored the request. With the continuous
        loop's ``continuous.promoted`` lineage events, this links any
        response back to the drift window and retrain that produced its
        model."""
        if version is None:
            version = self.registry.active_version(model_id)
            if version is None:
                self.registry.get(model_id)  # raises the precise reason
        entry = self.registry.get(model_id, version)
        return {"modelId": model_id, "version": version,
                "fingerprint": entry.fingerprint}

    def _http_score(self, model_id: Optional[str], row: dict,
                    trace_id: Optional[str] = None) -> dict:
        """POST /score[/model_id] adapter: path id wins, else the row's
        ``route_field``, else the sole registered model. The returned
        document is stamped with the trace id and the scoring model's
        lineage (the response-side half of request-scoped tracing).

        Opt-in explainability: an ``"explain"`` field on the request row
        (popped before admission — it is a directive, not a raw feature)
        routes through the model's explain lane; ``true`` uses the lane's
        default top-K, an integer asks for that many attributions. The
        reply gains an ordered ``"explanations"`` list alongside the
        score, under the same trace id + lineage stamp."""
        explain = row.pop("explain", False)
        if model_id is None:
            model_id = row.pop(self.route_field, None)
        if model_id is None:
            ids = self.registry.model_ids()
            if len(ids) != 1:
                raise ValueError(
                    f"request names no model (field {self.route_field!r} "
                    f"or /score/<id> path) and the fleet serves "
                    f"{len(ids)} models")
            model_id = ids[0]
        top_k = explain if isinstance(explain, int) \
            and not isinstance(explain, bool) and explain > 0 else None
        fut, version = self._submit_routed(model_id, row,
                                           trace_id=trace_id,
                                           explain=bool(explain),
                                           top_k=top_k)
        doc = dict(fut.result(timeout=self.http_timeout_s))
        if trace_id is not None:
            doc["traceId"] = trace_id
        # lineage of the version that ADMITTED the request (a hot swap
        # may have flipped the active alias while it was in flight)
        try:
            doc["lineage"] = self.lineage(model_id, version)
        except UnknownModelError:
            # the scoring version was unloaded before the reply was
            # assembled (swap/unregister race). A SCORED request must
            # never turn into an error reply over missing metadata:
            # fall back to active lineage, else version-only
            try:
                doc["lineage"] = self.lineage(model_id)
            except UnknownModelError:
                doc["lineage"] = {"modelId": model_id,
                                  "version": version,
                                  "fingerprint": None}
        # the rung the scores were computed at is part of lineage: an
        # auditor replaying this reply must reproduce it at the SAME
        # precision, not just the same fingerprint
        doc["lineage"]["precision"] = self._lane_precision(model_id,
                                                           version)
        return doc

    def _lane_precision(self, model_id: str, version) -> Optional[str]:
        """Active precision rung of the lane that scored — None when
        its lane is already gone (swap/demotion race)."""
        with self._lock:
            lane = self._lanes.get((model_id, version))
        return lane.scorer.precision if lane is not None else None

    def _submit_frame_routed(self, model_id: str, frame,
                             timeout_ms: Optional[float] = None,
                             trace_id: Optional[str] = None) -> tuple:
        """``_submit_routed`` for a decoded wire frame: same
        lane-stopped retry loop (a hot swap mid-flight re-resolves onto
        the promoted version), same lineage contract. Admission meters
        a frame at its ROW count — a tenant must not dodge its rate by
        batching."""
        n_rows = max(int(getattr(frame, "n_rows", 1) or 1), 1)
        if self.popularity is not None:
            self.popularity.record(model_id, n_rows)
        if self.admission is not None:
            self.admission.admit(model_id, n_rows)
        for _ in range(8):
            lane, version = self._resolve(model_id)
            try:
                fut = lane.submit_frame(frame, timeout_ms=timeout_ms,
                                        trace_id=trace_id)
            except RuntimeError:
                if self.registry.active_version(model_id) == version:
                    raise
                continue
            return fut, version
        raise RuntimeError(
            f"model {model_id!r}: could not route (lanes kept stopping)")

    def _frame_lineage_meta(self, model_id: str, version,
                            trace_id: Optional[str]) -> dict:
        meta: dict = {}
        if trace_id is not None:
            meta["traceId"] = trace_id
        # lineage of the version that ADMITTED the frame, with the same
        # swap-race fallbacks as the JSON reply path
        try:
            meta["lineage"] = self.lineage(model_id, version)
        except UnknownModelError:
            try:
                meta["lineage"] = self.lineage(model_id)
            except UnknownModelError:
                meta["lineage"] = {"modelId": model_id,
                                   "version": version,
                                   "fingerprint": None}
        meta["lineage"]["precision"] = self._lane_precision(model_id,
                                                            version)
        return meta

    def _http_frame(self, model_id: Optional[str], frame_bytes: bytes,
                    trace_id: Optional[str] = None) -> bytes:
        """``application/x-tmog-frame`` adapter: one binary columnar
        request frame in, one framed columnar reply out. Model
        resolution: path id wins, else the frame header's model id,
        else the sole registered model. The reply's meta carries the
        trace id + lineage stamp (the framed analog of the JSON reply's
        ``traceId``/``lineage`` fields); a request-level failure raises
        and maps to an HTTP status exactly like the JSON path
        (``WireFormatError`` is a ``ValueError`` -> 400).

        ``{"explain": true | K}`` in the request meta routes the batch
        through the explain lane — attributions ride the same framed
        reply as an ``explanations`` JSON column."""
        from transmogrifai_tpu.serving import wireformat as wf
        frame = wf.decode_frame(frame_bytes)
        if model_id is None:
            model_id = frame.model_id or None
        if model_id is None:
            ids = self.registry.model_ids()
            if len(ids) != 1:
                raise ValueError(
                    "request frame names no model (header model id or "
                    f"/score/<id> path) and the fleet serves "
                    f"{len(ids)} models")
            model_id = ids[0]
        explain = frame.meta.get("explain", False)
        if explain:
            # the explain lane batches rows, not columns: convert once
            # (LOCO dwarfs the conversion) and fan through the lane so
            # attributions ride the framed reply
            top_k = explain if isinstance(explain, int) \
                and not isinstance(explain, bool) and explain > 0 \
                else None
            rows = wf.frame_to_rows(frame)
            futs = []
            version = None
            for r in rows:
                fut, version = self._submit_routed(
                    model_id, r, trace_id=trace_id, explain=True,
                    top_k=top_k)
                futs.append(fut)
            docs = [f.result(timeout=self.http_timeout_s)
                    for f in futs]
            return wf.encode_frame(
                model_id, wf.rows_to_reply_columns(docs), len(docs),
                kind=wf.KIND_REPLY,
                meta=self._frame_lineage_meta(model_id, version,
                                              trace_id))
        fut, version = self._submit_frame_routed(model_id, frame,
                                                 trace_id=trace_id)
        kind, result = fut.result(timeout=self.http_timeout_s)
        if kind == "columns":
            cols = wf.reply_columns(result, frame.n_rows)
        else:
            # degraded/row-fallback batch: per-row docs (or isolated
            # per-row exceptions, carried as an ``error`` column)
            cols = wf.rows_to_reply_columns(result)
        return wf.encode_frame(
            model_id, cols, frame.n_rows, kind=wf.KIND_REPLY,
            meta=self._frame_lineage_meta(model_id, version, trace_id))

    # -- hot swap ------------------------------------------------------------
    def hot_swap(self, model_id: str, path: Optional[str] = None, *,
                 model=None, version: Optional[str] = None,
                 shadow_rows: Optional[int] = None,
                 tolerance: Optional[float] = None,
                 warmup_row: Optional[dict] = None) -> dict:
        """Promote a new version behind the live ``model_id`` with zero
        downtime and zero dropped requests.

        1. **load + warm**: the candidate (``path``/``model``, or an
           already-registered inactive ``version``) gets its own lane,
           started and bucket-warmed while the old version keeps serving.
        2. **shadow gate** (``shadow_rows > 0`` and live rows seen): the
           newest admitted rows score on BOTH versions; max abs score
           difference above ``tolerance`` aborts — the candidate is
           unloaded, the old version never stops, and
           ``ShadowParityError`` carries the measured divergence.
        3. **atomic flip**: the registry alias moves to the new version
           (one assignment under the registry lock) — every subsequent
           ``submit`` routes new. 4. **drain**: the old lane stops with
           ``drain=True``, settling every in-flight and queued request,
           then unloads.

        Any failure before the flip (warmup crash, injected fault at
        site ``serving.swap``, parity) leaves the old version serving,
        untouched. Returns a report dict; raises on abort.
        """
        shadow_rows = self.shadow_rows if shadow_rows is None \
            else int(shadow_rows)
        tolerance = self.shadow_tolerance if tolerance is None \
            else float(tolerance)
        with self._lock:
            swap_lock = self._swap_locks.setdefault(
                model_id, threading.Lock())
        if not swap_lock.acquire(blocking=False):
            raise RuntimeError(
                f"a hot-swap of {model_id!r} is already in progress; "
                "concurrent swaps of one model would double-promote")
        try:
            return self._hot_swap_locked(
                model_id, path, model=model, version=version,
                shadow_rows=shadow_rows, tolerance=tolerance,
                warmup_row=warmup_row)
        finally:
            swap_lock.release()

    def _hot_swap_locked(self, model_id: str, path: Optional[str], *,
                         model, version: Optional[str],
                         shadow_rows: int, tolerance: float,
                         warmup_row: Optional[dict]) -> dict:
        from transmogrifai_tpu.utils.faults import fault_point
        from transmogrifai_tpu.utils.tracing import span
        t0 = time.monotonic()
        old_lane, old_version = self._resolve(model_id)
        if path is None and model is None:
            if version is None:
                raise ValueError(
                    "hot_swap needs a path, a model, or an "
                    "already-registered version")
            entry = self.registry.get(model_id, version)
            if entry.model is None:
                raise ValueError(
                    f"version {version!r} of {model_id!r} is unloaded")
            pre_registered = True
        else:
            entry = self.registry.register(
                path, model=model, model_id=model_id, version=version,
                activate=False)
            self.metrics.record_registered()
            pre_registered = False
        if entry.version == old_version:
            raise ValueError(
                f"model {model_id!r} version {entry.version!r} is "
                "already active")

        with span("fleet.swap", model=model_id,
                  from_version=old_version, to_version=entry.version,
                  fingerprint=entry.fingerprint):
            new_lane = None
            try:
                rows = list(self._recent.get(model_id, ()))
                if warmup_row is None and rows:
                    warmup_row = dict(rows[-1])
                entry.state = ModelState.WARMING
                new_lane = self._make_lane(entry)
                new_lane.start(warmup_row=warmup_row)
                # chaos seam: a fault here is MID-swap — candidate warm,
                # alias not yet flipped; the abort path below must leave
                # the old version serving with nothing dropped
                fault_point("serving.swap")
                max_diff = self._shadow_gate(
                    model_id, old_lane, new_lane,
                    rows[-shadow_rows:] if shadow_rows > 0 else [],
                    tolerance)
            except BaseException as e:
                parity = isinstance(e, ShadowParityError)
                self.metrics.record_swap_failure(parity=parity)
                if parity:
                    # the gate REJECTION is its own flight-recorder
                    # event: incident dumps key on it
                    events.emit(
                        "fleet.gate_rejected", model=model_id,
                        fromVersion=old_version,
                        candidateVersion=entry.version,
                        maxAbsDiff=getattr(e, "max_abs_diff", None),
                        tolerance=tolerance)
                else:
                    events.emit(
                        "fleet.swap_failed", model=model_id,
                        fromVersion=old_version,
                        candidateVersion=entry.version,
                        error=f"{type(e).__name__}: {str(e)[:200]}")
                if new_lane is not None:
                    try:
                        new_lane.stop(drain=False)
                    except Exception:  # noqa: BLE001 — abort cleanup is best-effort (failure-ok)
                        pass
                if not pre_registered:
                    # forget the failed candidate so a retried swap can
                    # re-register the same version id cleanly
                    self.registry.unload(model_id, entry.version,
                                         forget=True)
                else:
                    entry.state = ModelState.WARMING
                raise
            # -- atomic flip: lane routable first, then one alias write --
            with self._lock:
                self._lanes[(model_id, entry.version)] = new_lane
            entry.state = ModelState.READY
            self.registry.promote(model_id, entry.version)
            # -- drain: every request the old lane admitted settles ------
            old_entry = self.registry.get(model_id, old_version)
            old_entry.state = ModelState.DRAINING
            with span("fleet.drain", model=model_id, version=old_version):
                old_lane.stop(drain=True)
            with self._lock:
                self._lanes.pop((model_id, old_version), None)
            self.registry.unload(model_id, old_version)
            self.metrics.record_unloaded()
            if not self.registry.fingerprint_in_use(
                    old_entry.fingerprint):
                # release the demoted version's budget share — but only
                # when NO loaded entry (this id's new version, or any
                # other id registered from the same checkpoint bytes)
                # still serves on those entries: they'd be someone's
                # warm programs, and dropping them forces mid-traffic
                # recompiles on an unswapped model
                self.program_cache.evict_model(old_entry.fingerprint)
            wall = time.monotonic() - t0
            self.metrics.record_swap(wall)
            events.emit("fleet.swap", model=model_id,
                        fromVersion=old_version, toVersion=entry.version,
                        fingerprint=entry.fingerprint,
                        wallSeconds=round(wall, 6))
        return {"modelId": model_id, "fromVersion": old_version,
                "toVersion": entry.version,
                "fingerprint": entry.fingerprint,
                "shadowRows": min(shadow_rows, len(rows)),
                "shadowMaxAbsDiff": max_diff,
                "wallSeconds": round(wall, 6)}

    def _shadow_gate(self, model_id: str, old_lane, new_lane,
                     rows: Sequence[dict], tolerance: float
                     ) -> Optional[float]:
        from transmogrifai_tpu.utils.tracing import span
        if not rows:
            warnings.warn(
                f"fleet: hot-swap of {model_id!r} has no live rows to "
                "shadow-score; promoting without the parity gate",
                RuntimeWarning)
            return None
        with span("fleet.shadow", model=model_id, rows=len(rows)):
            # the candidate lane is idle (plain submit can't shed); the
            # LIVE lane may be at queue capacity — the very moment an
            # operator wants a better model in — so absorb backpressure
            # instead of aborting the swap on a full queue
            new_futs = [new_lane.submit(dict(r)) for r in rows]
            old_futs = [old_lane.submit_blocking(
                dict(r), max_wait_s=self.shadow_timeout_s) for r in rows]
            max_diff = 0.0
            for of, nf in zip(old_futs, new_futs):
                max_diff = max(max_diff, score_diff(
                    of.result(timeout=self.shadow_timeout_s),
                    nf.result(timeout=self.shadow_timeout_s)))
        if max_diff > tolerance:
            raise ShadowParityError(
                f"shadow gate: candidate for {model_id!r} diverges from "
                f"the live version by {max_diff:.6g} > tolerance "
                f"{tolerance:g} on {len(rows)} live rows; swap aborted, "
                "old version still serving", max_abs_diff=max_diff)
        return max_diff

    @property
    def bound_metrics_port(self) -> Optional[int]:
        """The ACTUAL port the scrape/score endpoint bound (ephemeral
        with ``metrics_port=0``); None while no endpoint runs."""
        return self.metrics_http.port if self.metrics_http else None

    def queue_depths(self) -> dict:
        """model id -> requests waiting in its active lane's admission
        queue — the scale-out drain/quiesce probe (a replica reports
        drained when every lane reads 0) and the autoscaler's
        queue-pressure signal."""
        return {mid: lane.batcher.queue_depth
                for mid, lane in self.active_lanes().items()}

    # -- observability -------------------------------------------------------
    def active_lanes(self) -> dict:
        """model id -> its active version's running lane."""
        with self._lock:
            out = {}
            for model_id in self.registry.model_ids():
                version = self.registry.active_version(model_id)
                lane = self._lanes.get((model_id, version))
                if lane is not None:
                    out[model_id] = lane
            return out

    # fleet status = the worst lane's OWN state word (not a coarse
    # bucket): "warming" and "draining" point operators at opposite
    # ends of a model's lifecycle and must never alias. COLD sits just
    # above ok — an unpaged tenant is a tiered fleet's NORMAL state
    _SEVERITY = {"ok": 0, "cold": 1, "warming": 2, "draining": 3,
                 "stopped": 4, "degraded": 5, "unloaded": 6}

    def _health_static_fragment(self, lanes: dict) -> tuple:
        """The ``/healthz`` contribution of every model WITHOUT a
        running lane (retired, COLD, stopped): pure registry state, so
        it cannot change between registry mutations — cached against
        ``registry.mutation_seq`` (lane starts/stops touch the
        registry). Returns ``(models, worst, serving_worst,
        pageable)``."""
        severity = self._SEVERITY
        models: dict = {}
        worst = serving_worst = "ok"
        pageable = 0
        for model_id in self.registry.model_ids():
            version = self.registry.active_version(model_id)
            if version is None:
                # a retired model kept for audit: it colors the status
                # word but must NOT drag readiness down — a deliberately
                # unloaded entry would otherwise shed traffic from every
                # healthy lane forever
                models[model_id] = {"state": ModelState.UNLOADED,
                                    "version": None}
                worst = max(worst, ModelState.UNLOADED,
                            key=lambda s: severity.get(s, 4))
                continue
            if (model_id, version) in lanes:
                continue    # live: rendered fresh per probe
            entry = self.registry.get(model_id, version)
            state = entry.state
            models[model_id] = {"state": state, "version": version,
                                "fingerprint": entry.fingerprint}
            word = "ok" if state == "ready" else state
            worst = max(worst, word, key=lambda s: severity.get(s, 4))
            if state == ModelState.COLD and self.tenancy_store \
                    is not None and (entry.model is not None
                                     or entry.path is not None):
                # COLD is one demand-paged score away from serving: it
                # counts toward "the fleet can serve" and must not drag
                # the readiness bit (unlike stopped/warming)
                pageable += 1
            else:
                serving_worst = max(serving_worst, word,
                                    key=lambda s: severity.get(s, 4))
        return models, worst, serving_worst, pageable

    def health(self) -> dict:
        """Per-model readiness + overall fleet status (the ``/healthz``
        body): ``ok`` only when every active lane is on the compiled
        path; ``warming``/``degraded`` name the worst offender state.
        Laneless models render from a mutation-seq-keyed cache — at
        1000+ registered tenants the O(n) JSON per probe is what a
        scraper notices; live lanes stay fresh every call."""
        severity = self._SEVERITY
        with self._lock:
            lanes = dict(self._lanes)
        seq = self.registry.mutation_seq
        cached = self._health_static
        if cached is None or cached[0] != seq:
            cached = (seq, self._health_static_fragment(lanes))
            self._health_static = cached
        static_models, worst, serving_worst, pageable = cached[1]
        models = dict(static_models)
        any_active = False
        for (model_id, version), lane in lanes.items():
            if self.registry.active_version(model_id) != version:
                continue    # a swap's draining loser: not the alias
            any_active = True
            try:
                entry = self.registry.get(model_id, version)
            except UnknownModelError:
                continue
            state = lane.state
            models[model_id] = {"state": state, "version": version,
                                "fingerprint": entry.fingerprint,
                                "queueDepth": lane.batcher.queue_depth}
            word = "ok" if state == "ready" else state
            worst = max(worst, word, key=lambda s: severity.get(s, 4))
            serving_worst = max(serving_worst, word,
                                key=lambda s: severity.get(s, 4))
        from transmogrifai_tpu.utils.slo import fold_health

        # readiness: the load-balancer bit, over ACTIVE lanes only.
        # Degraded still serves (slowly); a firing fast-burn SLO alert
        # flips it (fold_health); a fleet with nothing active isn't
        # ready — but a started tiered fleet whose models are all COLD
        # is (they page in on first score)
        if pageable and self._started:
            any_active = True
        from transmogrifai_tpu.utils.resources import pressure_state
        doc = {"status": worst, "models": models,
               "fleet": self.metrics.to_json(),
               "cache": self.program_cache.to_json(),
               "resources": pressure_state(),
               "ready": any_active
               and serving_worst in ("ok", "degraded")}
        if self.tenancy_store is not None:
            tdoc = self.tenancy_store.to_json()
            if self.admission is not None:
                tdoc["fairness"] = self.admission.to_json()
            doc["tenancy"] = tdoc
        fold_health(self.slo_engine, doc)
        return doc

    def snapshot(self) -> dict:
        """One JSON document: fleet counters, shared-cache accounting,
        and every active lane's full serving snapshot keyed by model."""
        doc = {"fleet": self.metrics.to_json(),
               "cache": self.program_cache.to_json(),
               "registry": self.registry.list(),
               "models": {}}
        if self.tenancy_store is not None:
            doc["tenancy"] = self.tenancy_store.to_json()
            if self.admission is not None:
                doc["tenancy"]["fairness"] = self.admission.to_json()
            if self.popularity is not None:
                doc["tenancy"]["popularity"] = self.popularity.to_json()
        for model_id, lane in self.active_lanes().items():
            lane_doc = lane.snapshot(mirror_to_profiler=False)
            lane_doc["state"] = lane.state
            lane_doc["version"] = self.registry.active_version(model_id)
            doc["models"][model_id] = lane_doc
        return doc
