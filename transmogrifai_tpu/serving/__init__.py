"""Online serving: micro-batched jit scoring with backpressure, graceful
degradation, and latency metrics.

The TPU-native half of the serving story: where ``local/scoring.py``
reproduces the reference's engine-free row closure
(``OpWorkflowModelLocal``), this package serves the fitted DAG as a
compiled batch program at production request rates. See ``docs/SERVING.md``.

- ``CompiledScorer`` — padding-bucket jit cache over the fused device DAG
- ``CompiledExplainer`` — the scorer plus per-request LOCO attributions
  compiled into the same padded-bucket programs (line-rate "why this
  score"; see ``docs/INSIGHTS.md``)
- ``MicroBatcher`` — dynamic request coalescing, bounded queue, deadlines
- ``ScoringServer`` — the service: admission, retry, row-path degradation
  (+ an opt-in explain lane with its own batcher and metrics)
- ``ServingMetrics`` — p50/p95/p99 latency, throughput, degradation counters
- ``ModelRegistry``/``FleetServer``/``ProgramCache`` — the multi-model
  fleet: fingerprint-keyed registry, per-model routed lanes over one
  HBM-budgeted shared compiled-program cache, zero-downtime hot-swap

Attribute access is LAZY (like the top-level package): the jax-free
members of this package — ``serving.wireformat`` (the binary columnar
wire codec) and ``serving.aiohttp_core`` (the shared event-loop HTTP
front) — must stay importable without dragging jax in, because the
scale-out router and the stdlib-only stub worker import them.
"""

_LAZY = {
    "BackpressureError": ("transmogrifai_tpu.serving.batcher",
                          "BackpressureError"),
    "MicroBatcher": ("transmogrifai_tpu.serving.batcher", "MicroBatcher"),
    "RequestTimeout": ("transmogrifai_tpu.serving.batcher",
                       "RequestTimeout"),
    "UNKNOWN_TOKEN": ("transmogrifai_tpu.serving.compiled",
                      "UNKNOWN_TOKEN"),
    "CompiledScorer": ("transmogrifai_tpu.serving.compiled",
                       "CompiledScorer"),
    "CompiledExplainer": ("transmogrifai_tpu.serving.explain",
                          "CompiledExplainer"),
    "FleetServer": ("transmogrifai_tpu.serving.fleet", "FleetServer"),
    "ProgramCache": ("transmogrifai_tpu.serving.fleet", "ProgramCache"),
    "ShadowParityError": ("transmogrifai_tpu.serving.fleet",
                          "ShadowParityError"),
    "ServingMetrics": ("transmogrifai_tpu.serving.metrics",
                       "ServingMetrics"),
    "ModelRegistry": ("transmogrifai_tpu.serving.registry",
                      "ModelRegistry"),
    "ModelState": ("transmogrifai_tpu.serving.registry", "ModelState"),
    "UnknownModelError": ("transmogrifai_tpu.serving.registry",
                          "UnknownModelError"),
    "ScoringServer": ("transmogrifai_tpu.serving.server",
                      "ScoringServer"),
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    if name in _LAZY:
        import importlib
        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
