"""Online serving: micro-batched jit scoring with backpressure, graceful
degradation, and latency metrics.

The TPU-native half of the serving story: where ``local/scoring.py``
reproduces the reference's engine-free row closure
(``OpWorkflowModelLocal``), this package serves the fitted DAG as a
compiled batch program at production request rates. See ``docs/SERVING.md``.

- ``CompiledScorer`` — padding-bucket jit cache over the fused device DAG
- ``CompiledExplainer`` — the scorer plus per-request LOCO attributions
  compiled into the same padded-bucket programs (line-rate "why this
  score"; see ``docs/INSIGHTS.md``)
- ``MicroBatcher`` — dynamic request coalescing, bounded queue, deadlines
- ``ScoringServer`` — the service: admission, retry, row-path degradation
  (+ an opt-in explain lane with its own batcher and metrics)
- ``ServingMetrics`` — p50/p95/p99 latency, throughput, degradation counters
- ``ModelRegistry``/``FleetServer``/``ProgramCache`` — the multi-model
  fleet: fingerprint-keyed registry, per-model routed lanes over one
  HBM-budgeted shared compiled-program cache, zero-downtime hot-swap
"""

from transmogrifai_tpu.serving.batcher import (
    BackpressureError, MicroBatcher, RequestTimeout,
)
from transmogrifai_tpu.serving.compiled import UNKNOWN_TOKEN, CompiledScorer
from transmogrifai_tpu.serving.explain import CompiledExplainer
from transmogrifai_tpu.serving.fleet import (
    FleetServer, ProgramCache, ShadowParityError,
)
from transmogrifai_tpu.serving.metrics import ServingMetrics
from transmogrifai_tpu.serving.registry import (
    ModelRegistry, ModelState, UnknownModelError,
)
from transmogrifai_tpu.serving.server import ScoringServer

__all__ = [
    "BackpressureError", "CompiledExplainer", "CompiledScorer",
    "FleetServer", "MicroBatcher",
    "ModelRegistry", "ModelState", "ProgramCache", "RequestTimeout",
    "ScoringServer", "ServingMetrics", "ShadowParityError",
    "UNKNOWN_TOKEN", "UnknownModelError",
]
