"""Compiled per-request explainability: LOCO attributions on the serving
hot path.

``insights/loco.py`` answers "why this score" offline — a host transformer
over an already-materialized feature vector. Production serving (ROADMAP
item 6) needs the same answer at line rate: explanations must ride the
SAME compiled, padding-bucketed, cache-accounted path as scores, not a
host-side afterthought that re-traces per batch.

:class:`CompiledExplainer` extends :class:`~transmogrifai_tpu.serving.
compiled.CompiledScorer` with one extra compiled program per padding
bucket: the fused program of the PREDICTION layer runs the forward pass
ONCE (producing the same score outputs the plain path extracts) and, in
the same jitted program, batches the G leave-one-group-out masked passes
over the prediction model (``lax.map`` over mask chunks of an inner
``vmap`` — the chunk width caps peak memory at ``[chunk, n, d]`` masked
inputs, and is the resource ladder's rung at fault site
``serving.explain``: OOM halves it and re-serves the same batch).

Cache/fleet semantics carry over unchanged: explain programs live in the
shared :class:`~transmogrifai_tpu.serving.fleet.ProgramCache` keyed
``(model fingerprint, ("explain", layer, chunk), padding bucket)`` with
HBM accounting, so hot-swap eviction, prewarm, and budget pressure treat
them exactly like scoring programs — and the explainer's NON-prediction
layers use the same ``(fingerprint, layer, bucket)`` keys as the scoring
lane, sharing those compiled entries outright.

Feature groups come from the fitted vector's ``VectorMetadata`` through
the SAME ``loco_groups`` the offline stage uses, so served attributions
are parity-checkable (<= 1e-5, asserted by ``benchmarks/
bench_explain_overhead.py``) against ``RecordInsightsLOCO``.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence

import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.insights.loco import group_masks, loco_groups
from transmogrifai_tpu.serving.compiled import CompiledScorer
from transmogrifai_tpu.utils.precision import (
    PRECISION_BYTE_FACTOR, cast_float_leaves, compute_dtype,
    materialize_tree, normalize_precision,
)

__all__ = ["CompiledExplainer", "resolve_prediction_stage",
           "DEFAULT_MASK_CHUNK", "MASK_CHUNK_ENV"]

#: default leave-one-group-out mask-chunk width (masks per inner vmap):
#: peak explain memory is ~[chunk, bucket, d] masked inputs when XLA
#: can't fuse the mask into the score fn
DEFAULT_MASK_CHUNK = 64

#: env override for the initial mask-chunk width
MASK_CHUNK_ENV = "TRANSMOGRIFAI_EXPLAIN_MASK_CHUNK"


def resolve_prediction_stage(model) -> tuple:
    """``(stage, vector input name, prediction output name, layer index)``
    of the fitted prediction stage — the model whose masked re-scores ARE
    the LOCO deltas. Raises ``ValueError`` when the workflow has no
    device prediction stage to explain."""
    pred_f = model._prediction_feature()
    for li, layer in enumerate(model.dag):
        for t in layer:
            if t.get_output() == pred_f:
                if not t.is_device:
                    raise ValueError(
                        f"prediction stage {type(t).__name__} is not a "
                        "device stage; compiled explain needs a device "
                        "prediction model")
                return t, t.runtime_input_names()[-1], pred_f.name, li
    raise ValueError("fitted model has no prediction stage to explain")


class CompiledExplainer(CompiledScorer):
    """Jitted columnar batch scorer that ALSO returns top-K LOCO
    attributions per row.

    ``explain_batch(rows) -> (score_docs, explanations)`` where
    ``score_docs`` matches ``score_batch``'s contract exactly and
    ``explanations[i]`` is an ordered ``[{"name", "delta"}, ...]`` top-K
    list for row i. One instance backs one explain lane (single
    dispatcher thread), typically sharing its ``program_cache`` and
    ``fingerprint`` with the scoring lane's ``CompiledScorer``.
    """

    def __init__(self, model, *, top_k: int = 5,
                 mask_chunk: Optional[int] = None, **kwargs):
        super().__init__(model, **kwargs)
        self.top_k = int(top_k)
        if mask_chunk is None:
            env = os.environ.get(MASK_CHUNK_ENV)
            mask_chunk = int(env) if env else DEFAULT_MASK_CHUNK
        #: masks per inner vmap — the serving.explain ladder rung halves
        #: this on OOM (``shrink_mask_chunk``); floor 1
        self.mask_chunk = max(1, int(mask_chunk))
        (self._pstage, self._vec_name, self._pred_name,
         self._pred_li) = resolve_prediction_stage(model)
        #: resolved on the first explain dispatch from the fitted
        #: vector's metadata (static per fingerprint): [(name, idxs)]
        self._groups: Optional[list] = None
        self._group_names: list[str] = []
        self._masks_np: Optional[np.ndarray] = None     # [G, d]
        #: chunk -> device-resident [n_chunks, chunk, d] masks — static
        #: per chunk width, so steady-state dispatches re-upload nothing
        self._masks_dev: dict = {}
        self._vec_d: Optional[int] = None

    # -- group/mask resolution ----------------------------------------------
    def _resolve_groups(self, vec_col) -> None:
        d = int(vec_col.values.shape[-1])
        self._groups = loco_groups(getattr(vec_col, "metadata", None), d)
        self._group_names = [g for g, _ in self._groups]
        self._masks_np = group_masks(self._groups, d)
        self._masks_dev.clear()
        self._vec_d = d

    @property
    def n_groups(self) -> Optional[int]:
        return len(self._groups) if self._groups is not None else None

    def _chunked_masks(self, chunk: int):
        """``[n_chunks, chunk, d]`` device masks, padded with all-ones
        rows (delta exactly 0: ``x * 1.0`` is bitwise ``x``) dropped
        after the program. Static per chunk width: built and uploaded
        once, reused by every steady-state dispatch."""
        cached = self._masks_dev.get(chunk)
        if cached is not None:
            return cached
        import jax.numpy as jnp
        G, d = self._masks_np.shape
        n_chunks = -(-G // chunk)
        pad = n_chunks * chunk - G
        masks = self._masks_np
        if pad:
            masks = np.concatenate(
                [masks, np.ones((pad, d), np.float32)])
        dev = jnp.asarray(masks.reshape(n_chunks, chunk, d))
        self._masks_dev[chunk] = dev
        return dev

    def effective_mask_chunk(self) -> int:
        """The chunk width programs are actually keyed/traced at:
        ``mask_chunk`` clamped to the group count (a chunk wider than G
        would only pad)."""
        if self._groups is not None:
            return max(1, min(self.mask_chunk, len(self._groups)))
        return max(1, self.mask_chunk)

    def shrink_mask_chunk(self) -> Optional[int]:
        """Resource-ladder rung (site ``serving.explain``): halve the
        mask-chunk width so the next attempt's masked-input peak halves
        too, evicting the old chunk's compiled entries (and cached
        device masks) so their accounted HBM actually releases. Halving
        operates on the EFFECTIVE chunk — the width programs were
        actually traced at — so a ``mask_chunk`` wider than the group
        count still steps down instead of burning no-op rungs. Returns
        the new chunk, or None at the floor (chunk 1 — below it there
        is nothing to shed but the padding buckets, which the serving
        ladder already owns)."""
        old = self.effective_mask_chunk()
        if old <= 1:
            return None
        self.mask_chunk = max(1, old // 2)
        self._masks_dev.pop(old, None)
        if self.program_cache is not None:
            self.program_cache.evict_matching(
                lambda k: isinstance(k, tuple) and len(k) == 3
                and k[0] == self.fingerprint
                and isinstance(k[1], tuple) and k[1][:1] == ("explain",)
                and k[1][2] == old)
        else:
            for key in [k for k in self._programs
                        if isinstance(k, tuple) and k[:1] == ("explain",)
                        and k[2] == old]:
                self._programs.pop(key, None)
        return self.mask_chunk

    # -- compiled explain program -------------------------------------------
    def _explain_program_for(self, dev_ts, bucket: int, chunk: int,
                             precision: str = "f32"):
        factory = lambda: self._build_explain_program(  # noqa: E731
            dev_ts, precision)
        # rung-tagged key, same scheme as the scoring layers: f32 keeps
        # the pre-ladder 3-tuple layer component; variants append the
        # rung LAST so ``k[1][2] == chunk`` (shrink_mask_chunk's
        # predicate) keeps matching every rung's entries
        ek = ("explain", self._pred_li, chunk) if precision == "f32" \
            else ("explain", self._pred_li, chunk, precision)
        if self.program_cache is None:
            program = self._programs.get(ek)
            if program is None:
                program = factory()
                self._programs[ek] = program
            return program
        return self.program_cache.get(
            (self.fingerprint, ek, bucket),
            factory,
            bytes_est=lambda: self.explain_entry_bytes(bucket, chunk,
                                                       precision),
            counters=self.counters, bucket=bucket)

    def explain_entry_bytes(self, bucket: int, chunk: int,
                            precision: str = "f32") -> int:
        """Coarse HBM estimate for one compiled explain entry: the
        scoring layer's estimate plus the masked-input working set
        (``chunk`` masked ``[bucket, d]`` copies when XLA materializes
        them) — an estimate by design, like every HBM guard here. Non-f32
        rungs scale the masked working set by the rung's byte factor
        (masked copies are activations in the rung's compute dtype)."""
        d = self._vec_d if self._vec_d is not None else 0
        factor = PRECISION_BYTE_FACTOR.get(precision, 1.0)
        return self.layer_entry_bytes(self._pred_li, bucket, precision) \
            + max(1, int(int(chunk) * int(bucket) * int(d) * 4 * factor))

    def _build_explain_program(self, dev_ts, precision: str = "f32"):
        """ONE jitted program: the prediction layer's forward pass (same
        outputs the plain path extracts) + the G masked re-scores of the
        prediction model, chunked ``lax.map`` over an inner ``vmap``.
        Non-f32 rungs cast inputs/params/masks to the rung's compute
        dtype in-trace and return f32 outputs/deltas, mirroring
        ``dag.fuse_dag_program``."""
        import jax
        import jax.numpy as jnp

        dev_ts = list(dev_ts)
        pstage, vec_name = self._pstage, self._vec_name
        comp = compute_dtype(precision)
        from transmogrifai_tpu.utils.tracing import device_scope

        def score_of(out):
            prob = out.probability
            if prob is not None and prob.ndim == 2 and prob.shape[1] >= 2:
                return prob[:, 1]
            return out.prediction

        def fused(params, donate_cols, keep_cols, masks):
            env = {**donate_cols, **keep_cols}
            if comp is not None:
                env = cast_float_leaves(env, comp)
                params = materialize_tree(
                    cast_float_leaves(params, comp), comp)
                masks = cast_float_leaves(masks, comp)
            produced = {}
            for t in dev_ts:
                cols = [env[n] for n in t.runtime_input_names()]
                with device_scope(f"{t.operation_name}[{t.uid}]"):
                    produced[t.get_output().name] = t.device_apply(
                        params[t.uid], *cols)
            base = score_of(produced[self._pred_name])       # [n]
            X = env[vec_name].values                         # [n, d]
            pp = params[pstage.uid]

            def one(m):
                return base - score_of(
                    pstage.device_apply(pp, fr.VectorColumn(X * m)))

            with device_scope(f"loco[{pstage.uid}]"):
                deltas = jax.lax.map(jax.vmap(one), masks)
            # [n_chunks, chunk, n] -> [G_pad, n]
            deltas = deltas.reshape(-1, X.shape[0])
            if comp is not None:
                produced = cast_float_leaves(produced, jnp.float32)
                deltas = jnp.asarray(deltas, jnp.float32)
            return produced, deltas

        return jax.jit(fused, donate_argnums=(1,) if self.donate else ())

    # -- explain dispatch ----------------------------------------------------
    def warmup(self, row: dict, buckets: Optional[Sequence[int]] = None,
               precisions: Optional[Sequence[str]] = None) -> list[int]:
        """Pre-compile every padding bucket's EXPLAIN path (which also
        warms/shares the plain layers' programs) before traffic, per
        ladder rung in ``precisions`` (default: the active rung)."""
        from transmogrifai_tpu.utils.devicewatch import compile_telemetry
        warmed = []
        for p in (precisions if precisions is not None
                  else (self.precision,)):
            p = normalize_precision(p)
            suffix = "" if p == "f32" else f"_{p}"
            for b in (buckets if buckets is not None else self.buckets):
                with compile_telemetry.building(
                        f"serving.explain_bucket_{b}{suffix}"):
                    self.explain_batch([dict(row)] * int(b), precision=p)
                if int(b) not in warmed:
                    warmed.append(int(b))
        return warmed

    def explain_batch(self, rows: Sequence[dict], top_k=None,
                      precision: Optional[str] = None
                      ) -> tuple[list[dict], list[list]]:
        """Score + explain one batch. ``top_k``: None (the explainer's
        default), an int for the whole batch, or a per-row list.
        ``precision``: None dispatches at the active rung."""
        rows = list(rows)
        if not rows:
            return [], []
        precision = self.precision if precision is None \
            else normalize_precision(precision)
        ks = self._per_row_ks(rows, top_k)
        if len(rows) > self.max_batch:
            docs: list[dict] = []
            exps: list[list] = []
            for i in range(0, len(rows), self.max_batch):
                d_, e_ = self.explain_batch(
                    rows[i:i + self.max_batch],
                    ks[i:i + self.max_batch], precision=precision)
                docs.extend(d_)
                exps.extend(e_)
            return docs, exps
        n = len(rows)
        bucket = self.bucket_for(n)
        from transmogrifai_tpu.pipeline_data import PipelineData
        padded = rows + [rows[-1]] * (bucket - n)
        cols = {name: fr.HostColumn.from_values(
                    ftype, [r.get(name) for r in padded])
                for name, ftype in self._raw}
        data = PipelineData(fr.HostFrame(cols))
        if self.program_cache is not None:
            data, deltas = self._transform_explain(data, bucket, precision)
            self.counters.count(bucket, dispatches=1)
        else:
            before = self._program_cache_entries()
            data, deltas = self._transform_explain(data, bucket, precision)
            grew = self._program_cache_entries() - before
            self.counters.count(bucket, dispatches=1, compiles=grew)
            if grew:
                from transmogrifai_tpu.utils.events import events
                events.emit("serving.compile", bucket=bucket,
                            programs=grew, lane="explain",
                            precision=precision,
                            fingerprint=self.fingerprint)
        docs = self._extract_rows(data, n)
        exps = self._extract_explanations(deltas, n, ks)
        return docs, exps

    def _per_row_ks(self, rows: Sequence[dict], top_k) -> list[int]:
        if top_k is None:
            return [self.top_k] * len(rows)
        if isinstance(top_k, int):
            return [top_k] * len(rows)
        return [self.top_k if k is None else int(k) for k in top_k]

    def _transform_explain(self, data, bucket: int,
                           precision: str = "f32"):
        """The scorer's ``_transform`` with the prediction layer's
        program swapped for the fused forward+LOCO one. Returns
        ``(data, deltas[G, bucket] np.ndarray)``."""
        deltas = None
        for li, (host_ts, dev_ts) in enumerate(self._layers):
            if host_ts:
                data = data.with_host_cols(
                    {t.get_output().name: t.output_column(data)
                     for t in host_ts})
            if not dev_ts:
                continue
            in_cols = {n: self._device_input(data, n)
                       for t in dev_ts for n in t.runtime_input_names()}
            spent = set(self._free_plan[li]) if self.donate else set()
            donate_cols = {n: c for n, c in in_cols.items() if n in spent}
            keep_cols = {n: c for n, c in in_cols.items() if n not in spent}
            params = self._params_for(dev_ts, precision)
            if li == self._pred_li:
                if self._groups is None:
                    self._resolve_groups(in_cols[self._vec_name])
                chunk = self.effective_mask_chunk()
                program = self._explain_program_for(dev_ts, bucket, chunk,
                                                    precision)
                outs, dd = program(params, donate_cols, keep_cols,
                                   self._chunked_masks(chunk))
                deltas = np.asarray(dd)[:len(self._groups)]
            else:
                program = self._program_for(li, dev_ts, bucket, precision)
                outs = program(params, donate_cols, keep_cols)
            for name in self._free_plan[li]:
                data.device.pop(name, None)
            data = data.with_device_cols(outs)
            for t in dev_ts:
                m = getattr(outs.get(t.get_output().name), "metadata", None)
                if m is not None:
                    t.out_meta = m
        if deltas is None:  # unreachable by construction: _pred_li indexes
            raise RuntimeError("prediction layer never dispatched")
        return data, deltas

    def _extract_explanations(self, deltas: np.ndarray, n: int,
                              ks: Sequence[int]) -> list[list]:
        """``[G, bucket]`` deltas -> per-row ordered top-K attribution
        lists, matching the offline Abs strategy (sort by |delta|, drop
        exact zeros)."""
        names = self._group_names
        per_row = deltas[:, :n].T                             # [n, G]
        out: list[list] = []
        for i in range(n):
            row = per_row[i]
            top = np.argsort(-np.abs(row))[:max(int(ks[i]), 0)]
            out.append([{"name": names[j], "delta": float(row[j])}
                        for j in top if row[j] != 0.0])
        return out
