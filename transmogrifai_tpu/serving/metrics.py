"""Serving observability: latency percentiles, throughput, batch shape,
queue depth, degradation counters — snapshotable as one JSON document.

The analog of the training side's ``AppMetrics``/``SweepCounters`` for the
online path. Latency samples land in a bounded reservoir (the newest
``max_samples`` requests) so percentile queries stay O(reservoir), not
O(lifetime). Compile counts come from the scorer's per-instance
``utils.profiling.ServingCounters`` (a per-padding-bucket
``jax.monitoring`` listener) — the snapshot embeds them so one document
answers "did steady-state serving recompile?" for THIS server alone.
Aggregate serving wall is mirrored into the process profiler under
``OpStep.SCORING`` at snapshot time, keeping ``AppMetrics.pretty()`` the
single place operators read phase time.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Callable, Optional

import numpy as np

__all__ = ["ServingMetrics", "LATENCY_BUCKETS_S"]

#: fixed cumulative latency-histogram bucket bounds (seconds) — Prometheus
#: histogram semantics: bucket[i] counts requests with latency <= bound[i],
#: +Inf is the implicit final bucket (== count). Fixed at class level so
#: every server exports the same series and dashboards can aggregate.
LATENCY_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0)


class ServingMetrics:
    """Thread-safe counters + bounded latency reservoir for one server."""

    def __init__(self, max_samples: int = 8192,
                 queue_depth_fn: Optional[Callable[[], int]] = None,
                 queue_capacity: Optional[int] = None,
                 compile_counters=None,
                 rolling_window_s: float = 30.0):
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._started_at = time.time()
        self.queue_depth_fn = queue_depth_fn
        self.queue_capacity = queue_capacity
        #: this server's ServingCounters (per-scorer; None = no compile
        #: accounting in the snapshot)
        self.compile_counters = compile_counters
        # requests
        self.admitted = 0
        self.rejected_backpressure = 0
        self.rejected_invalid = 0
        self.completed = 0
        self.failed = 0
        self.expired = 0
        # batches
        self.batches = 0
        self.degraded_batches = 0
        self.data_error_batches = 0
        self.batch_rows = 0
        self.batch_wall_s = 0.0
        self.batch_size_hist: collections.Counter = collections.Counter()
        # degradation lifecycle
        self.degraded_entries = 0
        self.recoveries = 0
        self.dispatch_retries = 0
        self.degraded_active = False
        # precision ladder (PR 20): gated promotions, gate rejections,
        # pressure-forced demotions; active rung mirrored as bits so the
        # Prometheus gauge is numeric
        self.precision_promotions = 0
        self.precision_rejections = 0
        self.precision_demotions = 0
        self.precision_bits = 32
        self.precision = "f32"
        # latency reservoir (seconds), newest max_samples
        self._latency: collections.deque = collections.deque(
            maxlen=max_samples)
        # MONOTONIC cumulative latency histogram (Prometheus semantics) —
        # unlike the reservoir it never forgets, so scrapes can rate() it
        self._lat_buckets = [0] * (len(LATENCY_BUCKETS_S) + 1)  # +Inf last
        self._lat_sum = 0.0
        # rolling-window completion counts: the lifetime-average rps
        # under-reports an idle-then-busy server, so steady-state rate is
        # measured over the newest window too. Per-SECOND count buckets
        # (not per-completion timestamps): O(1) to record, bounded by the
        # window length at ANY throughput — a 30s window at 100k rps is
        # ~31 (second, count) pairs, not 3M timestamps
        self.rolling_window_s = float(rolling_window_s)
        self._done_buckets: collections.deque = collections.deque()

    # -- recording -----------------------------------------------------------
    def record_admitted(self, n: int = 1) -> None:
        with self._lock:
            self.admitted += n

    def record_rejected(self, *, invalid: bool = False, n: int = 1) -> None:
        with self._lock:
            if invalid:
                self.rejected_invalid += n
            else:
                self.rejected_backpressure += n

    def record_request_done(self, latency_s: float, ok: bool) -> None:
        self.record_requests_done([(latency_s, ok)])

    def record_requests_done(self, settled) -> None:
        """Bulk per-batch settlement: [(latency_s, ok), ...]."""
        now = time.monotonic()
        sec = int(now)
        with self._lock:
            n_ok = sum(1 for _, ok in settled if ok)
            if n_ok:
                if self._done_buckets and self._done_buckets[-1][0] == sec:
                    self._done_buckets[-1][1] += n_ok
                else:
                    self._done_buckets.append([sec, n_ok])
                cutoff = sec - int(self.rolling_window_s) - 1
                while self._done_buckets and \
                        self._done_buckets[0][0] < cutoff:
                    self._done_buckets.popleft()
            for latency_s, ok in settled:
                if ok:
                    self.completed += 1
                else:
                    self.failed += 1
                self._latency.append(latency_s)
                self._lat_sum += latency_s
                for i, bound in enumerate(LATENCY_BUCKETS_S):
                    if latency_s <= bound:
                        self._lat_buckets[i] += 1
                        break
                else:
                    self._lat_buckets[-1] += 1

    def record_expired(self, n: int = 1) -> None:
        with self._lock:
            self.expired += n
            self.failed += n

    def record_batch(self, size: int, wall_s: float,
                     degraded: bool = False) -> None:
        with self._lock:
            self.batches += 1
            self.batch_rows += size
            self.batch_wall_s += wall_s
            self.batch_size_hist[int(size)] += 1
            if degraded:
                self.degraded_batches += 1

    def record_data_error_batch(self) -> None:
        """A batch re-scored on the row path because of a malformed ROW
        (poison-row isolation), not a device fault — no degraded mode."""
        with self._lock:
            self.data_error_batches += 1

    def record_degraded_entry(self) -> None:
        with self._lock:
            self.degraded_entries += 1
            self.degraded_active = True

    def record_recovery(self) -> None:
        with self._lock:
            self.recoveries += 1
            self.degraded_active = False

    def record_retry(self, n: int = 1) -> None:
        with self._lock:
            self.dispatch_retries += n

    def record_precision(self, precision: str, *, promoted: bool = False,
                         rejected: bool = False,
                         demoted: bool = False) -> None:
        """Precision-ladder lifecycle: ``promoted`` (gate accepted a
        rung), ``rejected`` (gate refused — lane stays on its rung),
        ``demoted`` (pressure forced a rung without the gate). The
        active rung/bits always update to ``precision`` except on a
        rejection, where the lane by definition did not move."""
        from transmogrifai_tpu.utils.precision import PRECISION_BITS
        with self._lock:
            if promoted:
                self.precision_promotions += 1
            if rejected:
                self.precision_rejections += 1
            if demoted:
                self.precision_demotions += 1
            if not rejected:
                self.precision = precision
                self.precision_bits = PRECISION_BITS.get(precision, 32)

    # -- queries -------------------------------------------------------------
    def latency_percentiles_ms(self) -> dict:
        with self._lock:
            samples = np.asarray(self._latency, dtype=np.float64)
        if samples.size == 0:
            return {"count": 0, "p50": None, "p95": None, "p99": None,
                    "mean": None, "max": None}
        p50, p95, p99 = np.percentile(samples, [50.0, 95.0, 99.0])
        return {"count": int(samples.size),
                "p50": round(float(p50) * 1e3, 3),
                "p95": round(float(p95) * 1e3, 3),
                "p99": round(float(p99) * 1e3, 3),
                "mean": round(float(samples.mean()) * 1e3, 3),
                "max": round(float(samples.max()) * 1e3, 3)}

    def throughput_rps(self) -> float:
        """LIFETIME average completions/s — under-reports steady state on
        an idle-then-busy server; see :meth:`rolling_rps`."""
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        with self._lock:
            return self.completed / elapsed

    def rolling_rps(self, window_s: Optional[float] = None) -> float:
        """Completions/s over the newest ``window_s`` (default the
        configured ``rolling_window_s``) — the steady-state rate an
        operator actually wants. A server younger than the window divides
        by its age, not the full window (no warmup under-report)."""
        window = float(window_s if window_s is not None
                       else self.rolling_window_s)
        now = time.monotonic()
        cutoff = now - window
        with self._lock:
            # whole second-buckets within the window (the partial oldest
            # bucket counts fully — a <=1s edge effect on a 30s window)
            n = sum(c for sec, c in self._done_buckets if sec + 1 > cutoff)
        return n / max(min(window, now - self._t0), 1e-9)

    def latency_histogram(self) -> dict:
        """Cumulative Prometheus-style histogram: ``{"buckets": {le:
        cumulative count}, "sum": seconds, "count": n}`` with ``le`` keys
        as strings (``"0.005"`` ... ``"+Inf"``)."""
        with self._lock:
            per_bin = list(self._lat_buckets)
            total_sum = self._lat_sum
        buckets: dict = {}
        running = 0
        for bound, n in zip(LATENCY_BUCKETS_S, per_bin):
            running += n
            buckets[f"{bound:g}"] = running
        running += per_bin[-1]
        buckets["+Inf"] = running
        return {"buckets": buckets, "sum": total_sum, "count": running}

    def snapshot(self, mirror_to_profiler: bool = True) -> dict:
        """One JSON-able document with everything an operator dashboards.

        ``mirror_to_profiler=False`` skips publishing serving wall into
        the process AppMetrics — for callers (runner SERVE) that already
        wrap the replay in a ``profiler.phase(SCORING)`` block and would
        otherwise double-count the dispatch wall."""
        lat = self.latency_percentiles_ms()
        with self._lock:
            mean_size = (self.batch_rows / self.batches) if self.batches \
                else None
            doc = {
                "startedAt": self._started_at,
                "uptimeSeconds": round(time.monotonic() - self._t0, 3),
                "requests": {
                    "admitted": self.admitted,
                    "completed": self.completed,
                    "failed": self.failed,
                    "expired": self.expired,
                    "rejectedBackpressure": self.rejected_backpressure,
                    "rejectedInvalid": self.rejected_invalid,
                },
                "batches": {
                    "count": self.batches,
                    "degraded": self.degraded_batches,
                    "dataErrorFallbacks": self.data_error_batches,
                    "rows": self.batch_rows,
                    "wallSeconds": round(self.batch_wall_s, 6),
                    "meanSize": round(mean_size, 3) if mean_size else None,
                    "sizeHistogram": {str(k): v for k, v in sorted(
                        self.batch_size_hist.items())},
                },
                "degraded": {
                    "active": self.degraded_active,
                    "entries": self.degraded_entries,
                    "recoveries": self.recoveries,
                    "dispatchRetries": self.dispatch_retries,
                },
                "precision": {
                    "active": self.precision,
                    "bits": self.precision_bits,
                    "promotions": self.precision_promotions,
                    "rejections": self.precision_rejections,
                    "demotions": self.precision_demotions,
                },
            }
        doc["latencyMs"] = lat
        doc["latencyHistogram"] = self.latency_histogram()
        # both rates snapshot together: lifetime average AND the rolling
        # steady-state window (an idle-then-busy server's lifetime number
        # is an artifact of its uptime, not its current capacity)
        doc["throughputRps"] = round(self.throughput_rps(), 3)
        doc["throughputRpsRolling"] = round(self.rolling_rps(), 3)
        doc["rollingWindowSeconds"] = self.rolling_window_s
        queue_doc: dict = {"capacity": self.queue_capacity}
        if self.queue_depth_fn is not None:
            try:
                queue_doc["depth"] = int(self.queue_depth_fn())
            except Exception:  # failure-ok: queue-depth probe is optional in snapshots
                queue_doc["depth"] = None
        doc["queue"] = queue_doc
        doc["compileBuckets"] = self.compile_counters.to_json() \
            if self.compile_counters is not None else {}
        if mirror_to_profiler:
            self._mirror_to_profiler()
        return doc

    def _mirror_to_profiler(self) -> None:
        """Publish cumulative serving wall into the process AppMetrics under
        SCORING — delta-recorded so repeated snapshots don't double-count."""
        from transmogrifai_tpu.utils.profiling import OpStep, profiler
        with self._lock:
            delta = self.batch_wall_s - getattr(self, "_mirrored_s", 0.0)
            if delta <= 0:
                return
            self._mirrored_s = self.batch_wall_s
        profiler.metrics.record(OpStep.SCORING, delta)

    def save(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self.snapshot(), fh, indent=2)
        os.replace(tmp, path)
