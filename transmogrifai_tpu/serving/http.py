"""Scrape + scoring endpoint: a stdlib ``http.server`` background thread
serving ``GET /metrics`` (Prometheus text exposition over the server's
live counters), ``GET /healthz`` (liveness + per-model readiness as
JSON), and — when the owner provides a ``score_fn`` (the fleet does) —
``POST /score`` / ``POST /score/<model_id>`` (one JSON request row in,
one JSON score document out; the multi-process load harness's wire).
An ``"explain": true`` (or ``"explain": K``) field on the request row
opts into the fleet's explain lane — the reply gains an ordered
``"explanations"`` top-K LOCO attribution list alongside the score,
under the same trace id + lineage stamp (docs/INSIGHTS.md). The field
is a directive, popped before admission, so strict validation never
sees it; the scale-out router proxies bodies verbatim, so explained
requests ride through unchanged.

Request-scoped tracing starts HERE: every scoring request gets a trace
id — the inbound ``X-Trace-Id`` header when present (sanitized), else a
freshly minted one — that is passed to ``score_fn``, carried through the
batcher into the flight recorder, echoed back as the response's
``X-Trace-Id`` header (success AND error replies), and stamped into the
score document alongside the serving model's lineage.

Deliberately dependency-free and tiny: one daemon thread, a
``ThreadingHTTPServer`` so a slow scraper or a blocking score can't
stall a liveness probe, and no other routes — everything else is a 404.
Port 0 binds an ephemeral port (tests, multi-process fleets racing on
fixed ports); the bound port is ``MetricsServer.port``. Scoring status
mapping: strict-admission / malformed-request errors are 400, an
unknown model id 404, a queue-full ``BackpressureError`` 503 with a
``Retry-After`` hint, an expired request deadline 504 — load shed and
routing mistakes are the CLIENT's signal, never a server crash.

Wire behavior: the handler speaks **HTTP/1.1 with keep-alive** — a
router or load harness reuses one connection per replica instead of
paying a TCP handshake per request (the scale-out hop's hot path).
Request bodies are bounded (``max_body_bytes``, default 1 MiB): an
oversized or length-less body is rejected 413/411 with the connection
closed, never buffered — one request row has no business being
megabytes, and an unbounded read is a trivial DoS surface.

With ``control_fn`` the endpoint also serves ``POST /admin/<action>``
(JSON body in, JSON reply out) — the scale-out control plane a replica
worker exposes to its supervisor (drain, hot-swap, status, quit). A
shadow-gate rejection maps to 409 so a rolling swap can distinguish
"the candidate failed parity" from infrastructure errors.

Access logging: ``BaseHTTPRequestHandler``'s per-request stderr line is
suppressed (a daemon's stderr is not a log pipeline); instead, with
``access_log_sample > 0``, every Nth completed request emits a
structured ``http.access`` event into the flight recorder (method, path,
status, duration, trace id), additionally capped at
``ACCESS_LOG_MAX_PER_S`` events/second so a scrape storm cannot evict
the incident history the ring exists to keep.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from transmogrifai_tpu.utils.events import events
from transmogrifai_tpu.utils.prometheus import CONTENT_TYPE
from transmogrifai_tpu.utils.tracing import new_trace_id, sanitize_trace_id

__all__ = ["MetricsServer", "TRACE_HEADER", "MAX_BODY_BYTES"]

#: the request/response trace-context header (Dapper/B3-style: honor an
#: inbound id so a caller's trace continues through this hop)
TRACE_HEADER = "X-Trace-Id"

#: hard ceiling on sampled access-log events per second
ACCESS_LOG_MAX_PER_S = 100

#: default request-body bound (bytes): one JSON request row, with slack
MAX_BODY_BYTES = 1 << 20


class MetricsServer:
    """Background /metrics + /healthz (+ optional /score) endpoint."""

    def __init__(self, render_fn: Callable[[], str],
                 health_fn: Callable[[], dict],
                 port: int = 0, host: str = "127.0.0.1",
                 score_fn: Optional[Callable[
                     [Optional[str], dict, Optional[str]], dict]] = None,
                 control_fn: Optional[Callable[[str, dict], dict]] = None,
                 access_log_sample: float = 0.0,
                 max_body_bytes: int = MAX_BODY_BYTES):
        self.render_fn = render_fn
        self.health_fn = health_fn
        #: ``score_fn(model_id_or_None, row, trace_id) -> score doc``;
        #: None disables the POST /score routes (scrape-only endpoint)
        self.score_fn = score_fn
        #: ``control_fn(action, payload) -> reply doc`` behind
        #: ``POST /admin/<action>`` — the replica-worker control plane
        #: (None disables the admin routes). The endpoint binds loopback
        #: by default; expose it beyond localhost deliberately.
        self.control_fn = control_fn
        self.max_body_bytes = int(max_body_bytes)
        #: sampled structured access log: 0 (default) = off, else the
        #: fraction of requests evented (1.0 = every request, 0.01 =
        #: every 100th — deterministic stride, not a coin flip)
        self.access_log_sample = float(access_log_sample)
        self._access_n = 0
        self._access_window = [0.0, 0]   # [window second, emits in it]
        self._access_lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._host = host
        self._requested_port = int(port)

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    # -- access log ----------------------------------------------------------
    def _access(self, method: str, path: str, status: int, t0: float,
                trace_id: Optional[str] = None) -> None:
        """Emit a sampled ``http.access`` event (see module docstring)."""
        if self.access_log_sample <= 0 or not events.enabled:
            return
        stride = max(int(round(1.0 / self.access_log_sample)), 1)
        now = time.monotonic()
        with self._access_lock:
            self._access_n += 1
            if (self._access_n - 1) % stride:
                return
            sec = int(now)
            if self._access_window[0] != sec:
                self._access_window = [sec, 0]
            if self._access_window[1] >= ACCESS_LOG_MAX_PER_S:
                suppressed = True
            else:
                suppressed = False
                self._access_window[1] += 1
        if suppressed:
            events.count_suppressed()
            return
        events.emit("http.access", trace_id=trace_id, method=method,
                    path=path, status=int(status),
                    durationMs=round((time.monotonic() - t0) * 1e3, 3))

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1: persistent connections by default — the router->
            # replica hop must not pay a TCP handshake per request. Every
            # reply carries Content-Length (send_error closes on its own)
            protocol_version = "HTTP/1.1"
            # TCP_NODELAY: the reply's status+headers and body flush as
            # separate writes; with Nagle on, the body segment waits for
            # the ACK of the first — a ~40ms delayed-ACK stall PER
            # REQUEST on kernels that delay loopback ACKs. A scoring
            # endpoint's replies are single small documents: latency
            # wins, coalescing buys nothing.
            disable_nagle_algorithm = True

            def _read_body(self) -> Optional[bytes]:
                """Bounded request-body read, or None after an error
                reply. Oversized (413) and length-less-chunked (411)
                bodies are refused WITHOUT reading — send_error marks
                the connection close, so an unread body can't desync
                keep-alive."""
                if self.headers.get("Transfer-Encoding"):
                    self.send_error(
                        411, "chunked bodies unsupported; send "
                             "Content-Length")
                    return None
                try:
                    n = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    self.send_error(400, "malformed Content-Length")
                    return None
                if n < 0:
                    # read(-1) would buffer until EOF — the exact
                    # unbounded read the bound exists to prevent
                    self.send_error(400, "negative Content-Length")
                    return None
                if n > outer.max_body_bytes:
                    self.send_error(
                        413, f"request body {n} bytes exceeds the "
                             f"{outer.max_body_bytes}-byte bound")
                    return None
                return self.rfile.read(n) if n else b""

            def _reply(self, code: int, body: bytes, ctype: str,
                       extra: Optional[dict] = None) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                t0 = time.monotonic()
                path = self.path.split("?")[0]
                try:
                    if path == "/metrics":
                        body = outer.render_fn().encode()
                        ctype = CONTENT_TYPE
                    elif path == "/healthz":
                        body = (json.dumps(outer.health_fn())
                                + "\n").encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404, "only /metrics, /healthz "
                                             "and POST /score")
                        outer._access("GET", path, 404, t0)
                        return
                except Exception as e:  # noqa: BLE001 — a scrape must see the failure, not a hang
                    self.send_error(
                        500, f"{type(e).__name__}: {str(e)[:200]}")
                    outer._access("GET", path, 500, t0)
                    return
                self._reply(200, body, ctype)
                outer._access("GET", path, 200, t0)

            def do_POST(self):  # noqa: N802 — http.server API
                t0 = time.monotonic()
                path = self.path.split("?")[0]
                if outer.control_fn is not None \
                        and path.startswith("/admin/"):
                    self._admin(path, t0)
                    return
                if outer.score_fn is None or not (
                        path == "/score" or path.startswith("/score/")):
                    self.send_error(
                        404, "POST /score requires a scoring server")
                    outer._access("POST", path, 404, t0)
                    return
                model_id = path[len("/score/"):] or None \
                    if path.startswith("/score/") else None
                # trace context: continue the caller's trace or start one
                trace_id = sanitize_trace_id(
                    self.headers.get(TRACE_HEADER)) or new_trace_id()
                traced = {TRACE_HEADER: trace_id}

                def err_json(c, e, extra=None):
                    self._reply(
                        c, (json.dumps(
                            {"error": f"{type(e).__name__}: "
                                      f"{str(e)[:300]}",
                             "traceId": trace_id}) + "\n").encode(),
                        "application/json", {**traced, **(extra or {})})
                    outer._access("POST", path, c, t0, trace_id)
                try:
                    raw = self._read_body()
                    if raw is None:
                        outer._access("POST", path, 413, t0, trace_id)
                        return
                    row = json.loads(raw or b"{}")
                    if not isinstance(row, dict):
                        raise ValueError("request body must be one JSON "
                                         "object (a request row)")
                    doc = outer.score_fn(model_id, row, trace_id)
                except Exception as e:  # noqa: BLE001 — mapped to an HTTP status below
                    from concurrent.futures import (
                        TimeoutError as FutureTimeout,
                    )

                    from transmogrifai_tpu.serving.batcher import (
                        BackpressureError, RequestTimeout,
                    )
                    from transmogrifai_tpu.serving.registry import (
                        UnknownModelError,
                    )
                    if isinstance(e, BackpressureError):
                        err_json(503, e, {"Retry-After":
                                          f"{e.retry_after_s:.3f}"})
                    elif isinstance(e, UnknownModelError):
                        err_json(404, e)
                    elif isinstance(e, (RequestTimeout, TimeoutError,
                                        FutureTimeout)):
                        # RequestTimeout = queue deadline; Future/builtin
                        # TimeoutError = the result-wait bound (NOT the
                        # same class pre-3.11) — all 504, never a 5xx
                        # "server fault"
                        err_json(504, e)
                    elif isinstance(e, (KeyError, ValueError,
                                        json.JSONDecodeError)):
                        err_json(400, e)  # strict admission / bad body
                    else:
                        err_json(500, e)
                    return
                self._reply(200, (json.dumps(doc, default=str)
                                  + "\n").encode(), "application/json",
                            traced)
                outer._access("POST", path, 200, t0, trace_id)

            def _admin(self, path: str, t0: float) -> None:
                """``POST /admin/<action>``: the replica-worker control
                plane. JSON payload -> ``control_fn(action, payload)``
                -> JSON reply. Status mapping mirrors /score, plus 409
                for a shadow-gate rejection (a rolling swap must tell
                "candidate failed parity" from infrastructure faults)."""
                action = path[len("/admin/"):]
                try:
                    raw = self._read_body()
                    if raw is None:
                        outer._access("POST", path, 413, t0)
                        return
                    payload = json.loads(raw or b"{}")
                    if not isinstance(payload, dict):
                        raise ValueError("admin payload must be a JSON "
                                         "object")
                    doc = outer.control_fn(action, payload)
                    code = 200
                except Exception as e:  # noqa: BLE001 — mapped to an HTTP status
                    from transmogrifai_tpu.serving.registry import (
                        UnknownModelError,
                    )
                    if type(e).__name__ == "ShadowParityError":
                        code = 409
                    elif isinstance(e, UnknownModelError):
                        code = 404
                    elif isinstance(e, (KeyError, ValueError,
                                        json.JSONDecodeError)):
                        code = 400
                    else:
                        code = 500
                    doc = {"ok": False, "error":
                           f"{type(e).__name__}: {str(e)[:300]}"}
                self._reply(code, (json.dumps(doc, default=str)
                                   + "\n").encode(), "application/json")
                outer._access("POST", path, code, t0)

            def log_message(self, *args):
                # stderr access lines are suppressed; the structured,
                # sampled http.access event stream replaces them
                pass

        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="transmogrifai-metrics-http", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
