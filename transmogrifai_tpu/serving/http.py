"""Scrape + scoring endpoint: a stdlib ``http.server`` background thread
serving ``GET /metrics`` (Prometheus text exposition over the server's
live counters), ``GET /healthz`` (liveness + per-model readiness as
JSON), and — when the owner provides a ``score_fn`` (the fleet does) —
``POST /score`` / ``POST /score/<model_id>`` (one JSON request row in,
one JSON score document out; the multi-process load harness's wire).

Deliberately dependency-free and tiny: one daemon thread, a
``ThreadingHTTPServer`` so a slow scraper or a blocking score can't
stall a liveness probe, and no other routes — everything else is a 404.
Port 0 binds an ephemeral port (tests); the bound port is
``MetricsServer.port``. Scoring status mapping: strict-admission /
malformed-request errors are 400, an unknown model id 404, a queue-full
``BackpressureError`` 503 with a ``Retry-After`` hint, an expired
request deadline 504 — load shed and routing mistakes are the CLIENT's
signal, never a server crash.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from transmogrifai_tpu.utils.prometheus import CONTENT_TYPE

__all__ = ["MetricsServer"]


class MetricsServer:
    """Background /metrics + /healthz (+ optional /score) endpoint."""

    def __init__(self, render_fn: Callable[[], str],
                 health_fn: Callable[[], dict],
                 port: int = 0, host: str = "127.0.0.1",
                 score_fn: Optional[Callable[[Optional[str], dict],
                                             dict]] = None):
        self.render_fn = render_fn
        self.health_fn = health_fn
        #: ``score_fn(model_id_or_None, row) -> score doc``; None
        #: disables the POST /score routes (scrape-only endpoint)
        self.score_fn = score_fn
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._host = host
        self._requested_port = int(port)

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _reply(self, code: int, body: bytes, ctype: str,
                       extra: Optional[dict] = None) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    if self.path.split("?")[0] == "/metrics":
                        body = outer.render_fn().encode()
                        ctype = CONTENT_TYPE
                    elif self.path.split("?")[0] == "/healthz":
                        body = (json.dumps(outer.health_fn())
                                + "\n").encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404, "only /metrics, /healthz "
                                             "and POST /score")
                        return
                except Exception as e:  # noqa: BLE001 — a scrape must see the failure, not a hang
                    self.send_error(
                        500, f"{type(e).__name__}: {str(e)[:200]}")
                    return
                self._reply(200, body, ctype)

            def do_POST(self):  # noqa: N802 — http.server API
                path = self.path.split("?")[0]
                if outer.score_fn is None or not (
                        path == "/score" or path.startswith("/score/")):
                    self.send_error(
                        404, "POST /score requires a scoring server")
                    return
                model_id = path[len("/score/"):] or None \
                    if path.startswith("/score/") else None
                err_json = lambda c, e, extra=None: self._reply(  # noqa: E731
                    c, (json.dumps({"error": f"{type(e).__name__}: "
                                             f"{str(e)[:300]}"})
                        + "\n").encode(), "application/json", extra)
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    row = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(row, dict):
                        raise ValueError("request body must be one JSON "
                                         "object (a request row)")
                    doc = outer.score_fn(model_id, row)
                except Exception as e:  # noqa: BLE001 — mapped to an HTTP status below
                    from concurrent.futures import (
                        TimeoutError as FutureTimeout,
                    )

                    from transmogrifai_tpu.serving.batcher import (
                        BackpressureError, RequestTimeout,
                    )
                    from transmogrifai_tpu.serving.registry import (
                        UnknownModelError,
                    )
                    if isinstance(e, BackpressureError):
                        err_json(503, e, {"Retry-After":
                                          f"{e.retry_after_s:.3f}"})
                    elif isinstance(e, UnknownModelError):
                        err_json(404, e)
                    elif isinstance(e, (RequestTimeout, TimeoutError,
                                        FutureTimeout)):
                        # RequestTimeout = queue deadline; Future/builtin
                        # TimeoutError = the result-wait bound (NOT the
                        # same class pre-3.11) — all 504, never a 5xx
                        # "server fault"
                        err_json(504, e)
                    elif isinstance(e, (KeyError, ValueError,
                                        json.JSONDecodeError)):
                        err_json(400, e)  # strict admission / bad body
                    else:
                        err_json(500, e)
                    return
                self._reply(200, (json.dumps(doc, default=str)
                                  + "\n").encode(), "application/json")

            def log_message(self, *args):  # requests are not access-logged
                pass

        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="transmogrifai-metrics-http", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
