"""Scrape endpoint: a stdlib ``http.server`` background thread serving
``GET /metrics`` (Prometheus text exposition over the server's live
counters) and ``GET /healthz`` (liveness + degradation state as JSON).

Deliberately dependency-free and tiny: one daemon thread, a
``ThreadingHTTPServer`` so a slow scraper can't block a liveness probe,
and no request body handling at all — everything but the two GET paths
is a 404. Port 0 binds an ephemeral port (tests); the bound port is
``MetricsServer.port``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from transmogrifai_tpu.utils.prometheus import CONTENT_TYPE

__all__ = ["MetricsServer"]


class MetricsServer:
    """Background /metrics + /healthz endpoint for one ScoringServer."""

    def __init__(self, render_fn: Callable[[], str],
                 health_fn: Callable[[], dict],
                 port: int = 0, host: str = "127.0.0.1"):
        self.render_fn = render_fn
        self.health_fn = health_fn
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._host = host
        self._requested_port = int(port)

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    if self.path.split("?")[0] == "/metrics":
                        body = outer.render_fn().encode()
                        ctype = CONTENT_TYPE
                    elif self.path.split("?")[0] == "/healthz":
                        body = (json.dumps(outer.health_fn())
                                + "\n").encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404, "only /metrics and /healthz")
                        return
                except Exception as e:  # noqa: BLE001 — a scrape must see the failure, not a hang
                    self.send_error(
                        500, f"{type(e).__name__}: {str(e)[:200]}")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are not access-logged
                pass

        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="transmogrifai-metrics-http", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
