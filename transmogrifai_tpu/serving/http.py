"""Scrape + scoring endpoint on the shared event-loop HTTP core
(``serving/aiohttp_core.py``): ``GET /metrics`` (Prometheus text
exposition over the server's live counters), ``GET /healthz`` (liveness
+ per-model readiness as JSON), and — when the owner provides a
``score_fn`` (the fleet does) — ``POST /score`` / ``POST
/score/<model_id>``.

The scoring route negotiates on ``Content-Type``:

- ``application/json`` (default): one JSON request row in, one JSON
  score document out — the original wire, unchanged.
- ``application/x-ndjson``: one JSON row per line in, one score
  document per line out (same order). Per-line failures come back as
  inline ``{"error": ..., "traceId": ...}`` documents, so a batch with
  one poison row still scores the rest.
- ``application/x-tmog-frame``: one binary columnar frame in
  (``serving/wireformat.py``), one framed columnar reply out — the
  wire-speed path, served through ``frame_fn`` when the owner provides
  one. Malformed frames are 400s; error replies stay JSON (status
  codes + a readable body beat a binary error frame).

An ``"explain": true`` (or ``"explain": K``) field on a JSON request
row — or ``{"explain": K}`` in a frame's meta — opts into the fleet's
explain lane: the reply gains an ordered ``"explanations"`` top-K LOCO
attribution list alongside the score, under the same trace id +
lineage stamp (docs/INSIGHTS.md).

Request-scoped tracing starts HERE: every scoring request gets a trace
id — the inbound ``X-Trace-Id`` header when present (sanitized), else a
freshly minted one — that is passed to ``score_fn``, carried through the
batcher into the flight recorder, echoed back as the response's
``X-Trace-Id`` header (success AND error replies), and stamped into the
score document alongside the serving model's lineage.

The transport (keep-alive, TCP_NODELAY, bounded bodies: 413 oversize,
411 chunked, 400 malformed lengths — all with the connection closed so
an unread body can't desync a persistent connection) lives in the
shared core; this module only maps applications errors to statuses:
strict-admission / malformed-request errors are 400, an unknown model
id 404, a queue-full ``BackpressureError`` 503 with a ``Retry-After``
hint, an expired request deadline 504 — load shed and routing mistakes
are the CLIENT's signal, never a server crash.

With ``control_fn`` the endpoint also serves ``POST /admin/<action>``
(JSON body in, JSON reply out) — the scale-out control plane a replica
worker exposes to its supervisor (drain, hot-swap, status, quit). A
shadow-gate rejection maps to 409 so a rolling swap can distinguish
"the candidate failed parity" from infrastructure errors.

Access logging: with ``access_log_sample > 0``, every Nth completed
request emits a structured ``http.access`` event into the flight
recorder (method, path, status, duration, trace id), additionally
capped at ``ACCESS_LOG_MAX_PER_S`` events/second so a scrape storm
cannot evict the incident history the ring exists to keep.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Callable, Optional

from transmogrifai_tpu.serving.aiohttp_core import (
    AsyncHTTPServer, DedupeRing, Request, Response,
)
from transmogrifai_tpu.utils.events import events
from transmogrifai_tpu.utils.prometheus import CONTENT_TYPE
from transmogrifai_tpu.utils.tracing import new_trace_id, sanitize_trace_id

__all__ = ["MetricsServer", "TRACE_HEADER", "REQUEST_ID_HEADER",
           "MAX_BODY_BYTES", "CONTENT_TYPE_FRAME", "CONTENT_TYPE_NDJSON"]

#: the request/response trace-context header (Dapper/B3-style: honor an
#: inbound id so a caller's trace continues through this hop)
TRACE_HEADER = "X-Trace-Id"

#: the idempotency-key header (docs/WIRE.md): requests carrying one are
#: deduped by the replica's ring, so a router's mid-request-reset retry
#: is answered from cache instead of scored twice
REQUEST_ID_HEADER = "X-Request-Id"

#: how long a duplicate waits for its in-flight original before giving
#: up with 504 (never 503: a 503 would invite the router to spill the
#: duplicate to another replica WHILE the original still scores here)
DEDUPE_WAIT_S = 30.0


def sanitize_request_id(rid) -> Optional[str]:
    """A usable idempotency key, or None. Bounded printable token —
    the key is echoed into headers and ring memory, so it must not
    carry newlines or unbounded junk."""
    if not isinstance(rid, str):
        return None
    rid = rid.strip()
    if not rid or len(rid) > 128 or not rid.isprintable() \
            or " " in rid:
        return None
    return rid

#: hard ceiling on sampled access-log events per second
ACCESS_LOG_MAX_PER_S = 100

#: default request-body bound (bytes): one JSON request row or one
#: columnar frame, with slack
MAX_BODY_BYTES = 1 << 20

#: negotiated content types on POST /score (see module docstring)
CONTENT_TYPE_FRAME = "application/x-tmog-frame"
CONTENT_TYPE_NDJSON = "application/x-ndjson"


class MetricsServer:
    """Background /metrics + /healthz (+ optional /score) endpoint."""

    def __init__(self, render_fn: Callable[[], str],
                 health_fn: Callable[[], dict],
                 port: int = 0, host: str = "127.0.0.1",
                 score_fn: Optional[Callable[
                     [Optional[str], dict, Optional[str]], dict]] = None,
                 control_fn: Optional[Callable[[str, dict], dict]] = None,
                 access_log_sample: float = 0.0,
                 max_body_bytes: int = MAX_BODY_BYTES,
                 frame_fn: Optional[Callable[
                     [Optional[str], bytes, Optional[str]],
                     bytes]] = None,
                 dedupe_capacity: int = 512,
                 idle_timeout_s: Optional[float] = None,
                 read_timeout_s: Optional[float] = None,
                 write_timeout_s: Optional[float] = None,
                 max_connections: Optional[int] = None):
        self.render_fn = render_fn
        self.health_fn = health_fn
        #: idempotency ring for requests carrying X-Request-Id / frame
        #: meta request_id (0 disables — scrape-only endpoints)
        self.dedupe = DedupeRing(dedupe_capacity) \
            if dedupe_capacity > 0 else None
        #: slow-client / connection-gate overrides (None = the shared
        #: core's defaults; see aiohttp_core.AsyncHTTPServer)
        self._net_overrides = {
            k: v for k, v in (("idle_timeout_s", idle_timeout_s),
                              ("read_timeout_s", read_timeout_s),
                              ("write_timeout_s", write_timeout_s),
                              ("max_connections", max_connections))
            if v is not None}
        #: ``score_fn(model_id_or_None, row, trace_id) -> score doc``;
        #: None disables the POST /score routes (scrape-only endpoint)
        self.score_fn = score_fn
        #: ``frame_fn(model_id_or_None, frame_bytes, trace_id) ->
        #: reply frame bytes`` — the binary columnar scoring wire
        #: (``application/x-tmog-frame``); None disables it
        self.frame_fn = frame_fn
        #: ``control_fn(action, payload) -> reply doc`` behind
        #: ``POST /admin/<action>`` — the replica-worker control plane
        #: (None disables the admin routes). The endpoint binds loopback
        #: by default; expose it beyond localhost deliberately.
        self.control_fn = control_fn
        self.max_body_bytes = int(max_body_bytes)
        #: sampled structured access log: 0 (default) = off, else the
        #: fraction of requests evented (1.0 = every request, 0.01 =
        #: every 100th — deterministic stride, not a coin flip)
        self.access_log_sample = float(access_log_sample)
        self._access_n = 0
        self._access_window = [0.0, 0]   # [window second, emits in it]
        self._access_lock = threading.Lock()
        self._http: Optional[AsyncHTTPServer] = None
        self._host = host
        self._requested_port = int(port)

    @property
    def port(self) -> Optional[int]:
        return self._http.port if self._http else None

    # -- access log ----------------------------------------------------------
    def _access(self, method: str, path: str, status: int, t0: float,
                trace_id: Optional[str] = None) -> None:
        """Emit a sampled ``http.access`` event (see module docstring)."""
        if self.access_log_sample <= 0 or not events.enabled:
            return
        stride = max(int(round(1.0 / self.access_log_sample)), 1)
        now = time.monotonic()
        with self._access_lock:
            self._access_n += 1
            if (self._access_n - 1) % stride:
                return
            sec = int(now)
            if self._access_window[0] != sec:
                self._access_window = [sec, 0]
            if self._access_window[1] >= ACCESS_LOG_MAX_PER_S:
                suppressed = True
            else:
                suppressed = False
                self._access_window[1] += 1
        if suppressed:
            events.count_suppressed()
            return
        events.emit("http.access", trace_id=trace_id, method=method,
                    path=path, status=int(status),
                    durationMs=round((time.monotonic() - t0) * 1e3, 3))

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "MetricsServer":
        if self._http is not None:
            return self
        self._http = AsyncHTTPServer(
            self._handle, port=self._requested_port, host=self._host,
            max_body_bytes=self.max_body_bytes,
            name="transmogrifai-metrics-http",
            **self._net_overrides).start()
        return self

    def stop(self) -> None:
        if self._http is None:
            return
        self._http.stop()
        self._http = None

    # -- request handling (event loop) ---------------------------------------
    async def _handle(self, req: Request) -> Response:
        if req.method == "GET":
            return await self._do_get(req)
        if req.method == "POST":
            return await self._do_post(req)
        return Response.error(404, f"method {req.method} unsupported")

    async def _do_get(self, req: Request) -> Response:
        t0 = time.monotonic()
        path = req.path
        try:
            if path == "/metrics":
                body = (await self._http.run_blocking(
                    self.render_fn)).encode()
                ctype = CONTENT_TYPE
            elif path == "/healthz":
                doc = await self._http.run_blocking(self.health_fn)
                body = (json.dumps(doc) + "\n").encode()
                ctype = "application/json"
            else:
                self._access("GET", path, 404, t0)
                return Response.error(
                    404, "only /metrics, /healthz and POST /score")
        except Exception as e:  # noqa: BLE001 — a scrape must see the failure, not a hang
            self._access("GET", path, 500, t0)
            return Response.error(
                500, f"{type(e).__name__}: {str(e)[:200]}")
        self._access("GET", path, 200, t0)
        return Response(200, body, ctype)

    async def _do_post(self, req: Request) -> Response:
        t0 = time.monotonic()
        path = req.path
        if self.control_fn is not None and path.startswith("/admin/"):
            return await self._admin(req, path, t0)
        servable = self.score_fn is not None \
            or self.frame_fn is not None
        if not servable or not (path == "/score"
                                or path.startswith("/score/")):
            self._access("POST", path, 404, t0)
            return Response.error(
                404, "POST /score requires a scoring server")
        model_id = path[len("/score/"):] or None \
            if path.startswith("/score/") else None
        # trace context: continue the caller's trace or start one
        trace_id = sanitize_trace_id(
            req.header(TRACE_HEADER)) or new_trace_id()
        ctype = (req.header("content-type") or "").split(";")[0].strip()
        if ctype == CONTENT_TYPE_FRAME:
            run = self._score_frame
        elif ctype == CONTENT_TYPE_NDJSON:
            run = self._score_ndjson
        else:
            run = self._score_json
        request_id = sanitize_request_id(req.header(REQUEST_ID_HEADER))
        if request_id is None and ctype == CONTENT_TYPE_FRAME:
            from transmogrifai_tpu.serving.wireformat import (
                peek_request_id,
            )
            request_id = sanitize_request_id(peek_request_id(req.body))
        if self.dedupe is None or request_id is None:
            return await run(req, path, model_id, trace_id, t0)
        return await self._deduped(
            request_id, trace_id,
            lambda: run(req, path, model_id, trace_id, t0))

    async def _deduped(self, request_id: str, trace_id: str,
                       run) -> Response:
        """Execute ``run()`` under the idempotency ring: a repeated key
        is answered from cache ("this exact request was already scored
        — here is that reply"), a key racing its in-flight original
        waits for the original's result. Only 2xx replies are cached;
        failures abandon the key so a legitimate client retry can
        re-execute. Replies always travel as COPIES — the connection
        loop mutates ``Response.close`` on whatever it returns, and a
        cached object must never absorb that."""

        def copy_of(resp: Response, dedupe: str) -> Response:
            return Response(resp.status, resp.body, resp.ctype,
                            {**resp.headers,
                             REQUEST_ID_HEADER: request_id,
                             "X-Dedupe": dedupe})

        for _ in range(2):
            tag, obj = self.dedupe.begin(request_id)
            if tag == "hit":
                return copy_of(obj, "hit")
            if tag == "wait":
                # park OFF the event loop; when the original finishes
                # (or abandons), re-enter begin() for the verdict
                done = await self._http.run_blocking(
                    obj.event.wait, DEDUPE_WAIT_S)
                if not done:
                    break
                continue
            entry = obj
            try:
                resp = await run()
            except BaseException:
                self.dedupe.abandon(request_id, entry)
                raise
            if 200 <= resp.status < 300:
                self.dedupe.complete(request_id, entry, copy_of(
                    resp, "original"))
            else:
                self.dedupe.abandon(request_id, entry)
            return copy_of(resp, "original")
        body = (json.dumps(
            {"error": f"duplicate of in-flight request "
                      f"{request_id} timed out waiting for the "
                      f"original", "traceId": trace_id}) + "\n").encode()
        return Response(504, body, "application/json",
                        {TRACE_HEADER: trace_id,
                         REQUEST_ID_HEADER: request_id})

    def _err_json(self, code: int, e: BaseException, trace_id: str,
                  extra: Optional[dict] = None) -> Response:
        body = (json.dumps(
            {"error": f"{type(e).__name__}: {str(e)[:300]}",
             "traceId": trace_id}) + "\n").encode()
        headers = {TRACE_HEADER: trace_id, **(extra or {})}
        return Response(code, body, "application/json", headers)

    def _map_score_error(self, e: BaseException, path: str,
                         trace_id: str, t0: float) -> Response:
        from concurrent.futures import TimeoutError as FutureTimeout

        from transmogrifai_tpu.serving.batcher import (
            BackpressureError, RequestTimeout,
        )
        from transmogrifai_tpu.serving.registry import UnknownModelError
        if isinstance(e, BackpressureError):
            resp = self._err_json(503, e, trace_id,
                                  {"Retry-After":
                                   f"{e.retry_after_s:.3f}"})
        elif isinstance(e, UnknownModelError):
            resp = self._err_json(404, e, trace_id)
        elif isinstance(e, (RequestTimeout, TimeoutError,
                            FutureTimeout, asyncio.TimeoutError)):
            # RequestTimeout = queue deadline; Future/builtin
            # TimeoutError = the result-wait bound (distinct classes
            # pre-3.11, and run_in_executor re-raises a FutureTimeout
            # as asyncio.TimeoutError) — all 504, never a 5xx
            # "server fault"
            resp = self._err_json(504, e, trace_id)
        elif isinstance(e, (KeyError, ValueError,
                            json.JSONDecodeError)):
            resp = self._err_json(400, e, trace_id)  # strict admission / bad body
        else:
            resp = self._err_json(500, e, trace_id)
        self._access("POST", path, resp.status, t0, trace_id)
        return resp

    async def _score_json(self, req: Request, path: str,
                          model_id: Optional[str], trace_id: str,
                          t0: float) -> Response:
        if self.score_fn is None:
            self._access("POST", path, 404, t0, trace_id)
            return Response.error(
                404, "POST /score requires a scoring server")
        try:
            row = json.loads(req.body or b"{}")
            if not isinstance(row, dict):
                raise ValueError("request body must be one JSON "
                                 "object (a request row)")
            doc = await self._http.run_blocking(
                self.score_fn, model_id, row, trace_id)
        except Exception as e:  # noqa: BLE001 — mapped to an HTTP status
            return self._map_score_error(e, path, trace_id, t0)
        self._access("POST", path, 200, t0, trace_id)
        return Response(200, (json.dumps(doc, default=str)
                              + "\n").encode(), "application/json",
                        {TRACE_HEADER: trace_id})

    async def _score_ndjson(self, req: Request, path: str,
                            model_id: Optional[str], trace_id: str,
                            t0: float) -> Response:
        """One JSON row per line in, one score document per line out.
        Per-line failures reply INLINE (an ``{"error": ...}`` document
        in that line's slot) so one poison row doesn't void the batch;
        a request-level failure on the FIRST line (backpressure, an
        unknown model) maps to its HTTP status like the JSON path, so
        clients keep their retry semantics."""
        if self.score_fn is None:
            self._access("POST", path, 404, t0, trace_id)
            return Response.error(
                404, "POST /score requires a scoring server")
        lines = [ln for ln in req.body.splitlines() if ln.strip()]

        def run():
            docs = []
            for i, ln in enumerate(lines):
                try:
                    row = json.loads(ln)
                    if not isinstance(row, dict):
                        raise ValueError(
                            "each NDJSON line must be one JSON object")
                    docs.append(self.score_fn(model_id, row, trace_id))
                except Exception as e:  # noqa: BLE001 — isolated per line (or mapped whole)
                    if i == 0 and not docs:
                        raise
                    docs.append(
                        {"error": f"{type(e).__name__}: "
                                  f"{str(e)[:300]}",
                         "traceId": trace_id})
            return docs

        try:
            docs = await self._http.run_blocking(run)
        except Exception as e:  # noqa: BLE001 — mapped to an HTTP status
            return self._map_score_error(e, path, trace_id, t0)
        body = "".join(json.dumps(d, default=str) + "\n"
                       for d in docs).encode()
        self._access("POST", path, 200, t0, trace_id)
        return Response(200, body, CONTENT_TYPE_NDJSON,
                        {TRACE_HEADER: trace_id})

    async def _score_frame(self, req: Request, path: str,
                           model_id: Optional[str], trace_id: str,
                           t0: float) -> Response:
        if self.frame_fn is None:
            self._access("POST", path, 400, t0, trace_id)
            return self._err_json(
                400, ValueError(
                    f"{CONTENT_TYPE_FRAME} unsupported on this "
                    "endpoint"), trace_id)
        try:
            reply = await self._http.run_blocking(
                self.frame_fn, model_id, req.body, trace_id)
        except Exception as e:  # noqa: BLE001 — mapped to an HTTP status
            return self._map_score_error(e, path, trace_id, t0)
        self._access("POST", path, 200, t0, trace_id)
        return Response(200, reply, CONTENT_TYPE_FRAME,
                        {TRACE_HEADER: trace_id})

    async def _admin(self, req: Request, path: str,
                     t0: float) -> Response:
        """``POST /admin/<action>``: the replica-worker control plane.
        JSON payload -> ``control_fn(action, payload)`` -> JSON reply.
        Status mapping mirrors /score, plus 409 for a shadow-gate
        rejection (a rolling swap must tell "candidate failed parity"
        from infrastructure faults)."""
        action = path[len("/admin/"):]
        try:
            payload = json.loads(req.body or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("admin payload must be a JSON object")
            doc = await self._http.run_blocking(
                self.control_fn, action, payload)
            code = 200
        except Exception as e:  # noqa: BLE001 — mapped to an HTTP status
            from transmogrifai_tpu.serving.registry import (
                UnknownModelError,
            )
            if type(e).__name__ == "ShadowParityError":
                code = 409
            elif isinstance(e, UnknownModelError):
                code = 404
            elif isinstance(e, (KeyError, ValueError,
                                json.JSONDecodeError)):
                code = 400
            else:
                code = 500
            doc = {"ok": False, "error":
                   f"{type(e).__name__}: {str(e)[:300]}"}
        self._access("POST", path, code, t0)
        return Response(code, (json.dumps(doc, default=str)
                               + "\n").encode(), "application/json")
