"""Text NLP chain: tokenization, language detection, stop words, n-grams,
similarity.

Parity: reference ``core/.../stages/impl/feature/{TextTokenizer,
LangDetector, OpStopWordsRemover, OpNGram, NGramSimilarity,
TextLenTransformer}.scala`` and ``core/.../utils/text/*``. The reference
rides Lucene analyzers + the Optimaize detector; here tokenization is a
unicode word-regex analyzer with a CJK/Thai character-bigram path (the
LuceneTextAnalyzer/CJKAnalyzer analog) and language identification is the
character-n-gram profile detector in ``ops/lang.py`` (~30 languages, the
Optimaize/textcat family). All of these are host stages (string work stays
off the device; SURVEY §7 hard part #2).
"""

from __future__ import annotations

import re
from typing import Optional

import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.ops.lang import detect_language_ngram, language_scores
from transmogrifai_tpu.stages.base import HostTransformer
from transmogrifai_tpu.types import feature_types as ft

__all__ = [
    "TextTokenizer", "LangDetector", "OpStopWordsRemover", "OpNGram",
    "NGramSimilarity", "TextLenTransformer", "STOP_WORDS",
    "simple_tokenize", "detect_language",
    "RegexTokenizer", "TextToMultiPickList", "SetJaccardSimilarity",
]

_WORD_RE = re.compile(r"[^\W_]+", re.UNICODE)

#: scripts written without spaces: tokens segment into character bigrams
#: (the Lucene CJKAnalyzer convention)
_BIGRAM_RANGES = (
    (0x2E80, 0x9FFF),    # CJK radicals .. unified ideographs
    (0x3040, 0x30FF),    # hiragana + katakana (inside the range above)
    (0xF900, 0xFAFF),    # CJK compatibility
    (0x0E00, 0x0E7F),    # Thai
)

#: per-language stopword profiles (removal; detection rides ops/lang.py)
STOP_WORDS: dict[str, frozenset] = {
    "en": frozenset("the a an and or of to in is are was were be been i you "
                    "he she it we they this that with for on at by from as "
                    "not no but if then so what which who whom".split()),
    "fr": frozenset("le la les un une des et ou de du au aux en est sont "
                    "était je tu il elle nous vous ils elles ce cette avec "
                    "pour sur par ne pas mais si que qui".split()),
    "de": frozenset("der die das ein eine und oder von zu in ist sind war "
                    "waren ich du er sie es wir ihr mit für auf bei aus "
                    "nicht kein aber wenn dann was welche wer".split()),
    "es": frozenset("el la los las un una unos unas y o de del al en es son "
                    "era yo tú él ella nosotros vosotros ellos con para "
                    "sobre por no pero si que quien".split()),
    "it": frozenset("il lo la i gli le un uno una e o di del della al in è "
                    "sono era io tu lui lei noi voi loro con per su da non "
                    "ma se che chi".split()),
    "pt": frozenset("o a os as um uma uns umas e ou de do da ao em é são "
                    "era eu tu ele ela nós vós eles com para sobre por não "
                    "mas se que quem".split()),
    "nl": frozenset("de het een en of van naar in is zijn was waren ik jij "
                    "hij zij wij jullie met voor op bij uit niet geen maar "
                    "als dan wat welke wie".split()),
    "sv": frozenset("och det att i en jag hon som han på den med var sig "
                    "för så till är men ett om hade de av icke mig du "
                    "henne då sin nu har inte hans honom".split()),
    "da": frozenset("og i jeg det at en den til er som på de med han af "
                    "for ikke der var mig sig men et har om vi min havde "
                    "ham hun nu over da fra du ud".split()),
    "no": frozenset("og i jeg det at en et den til er som på de med han "
                    "av ikke der så var meg seg men ett har om vi min "
                    "mitt ha hadde hun nå over da ved fra du ut".split()),
    "fi": frozenset("olla olen on ja se ei että en oli hän minä joka mitä "
                    "tämä mutta niin kuin sen sitä tai kun nyt jos mikä "
                    "ole vain minun hänen ovat sinä me he".split()),
    "pl": frozenset("i w nie na się że z do to jak o co tak jest po a ale "
                    "czy za przez od dla przy bez być może ten ta te go "
                    "ich jego jej mnie ciebie".split()),
    "cs": frozenset("a v na se že je s z do o k i ale jako za by pro tak "
                    "po co když už jen při od být ten tato toto jsem jsi "
                    "jsou byl byla bylo nebo ani".split()),
    "ro": frozenset("și în a la cu de pe că nu este sunt un o care mai "
                    "din pentru dar dacă ce așa după cum fără sau fi am "
                    "ai are acest această eu tu el ea noi".split()),
    "hu": frozenset("a az és hogy nem is ez egy van volt de meg csak már "
                    "el mint még ki mi ha vagy lesz lehet más aki amely "
                    "én te ő mert azt ezt nagyon".split()),
    "tr": frozenset("ve bir bu da de için ile ne gibi daha çok ama o ben "
                    "sen biz siz onlar mi mu değil var yok olan olarak "
                    "kadar sonra önce her şey ki en".split()),
    "ru": frozenset("и в не на я что он с как это а то все она так его но "
                    "они к у же вы за бы по ее мне было вот от меня о из "
                    "ему теперь когда даже ну ли если уже или".split()),
    "id": frozenset("yang dan di ini itu dengan untuk tidak dari dalam "
                    "akan pada juga saya kamu dia kami mereka ada bisa "
                    "sudah atau ke oleh karena jika seperti".split()),
}


def _needs_bigrams(ch: str) -> bool:
    cp = ord(ch)
    return any(lo <= cp <= hi for lo, hi in _BIGRAM_RANGES)


def simple_tokenize(text: str, lowercase: bool = True,
                    min_token_length: int = 1) -> list[str]:
    """Unicode word tokens; runs in space-less scripts (CJK, kana, Thai)
    segment into overlapping character bigrams. Mixed-script tokens split
    at script boundaries first (the CJKAnalyzer convention), so 'abc漢字'
    yields 'abc' + the CJK bigrams regardless of which script leads."""
    if lowercase:
        text = text.lower()
    out = []
    for tok in _WORD_RE.findall(text):
        start = 0
        while start < len(tok):
            is_cjk = _needs_bigrams(tok[start])
            end = start + 1
            while end < len(tok) and _needs_bigrams(tok[end]) == is_cjk:
                end += 1
            run = tok[start:end]
            start = end
            if is_cjk:
                if len(run) == 1:
                    out.append(run)
                else:
                    out.extend(run[i:i + 2] for i in range(len(run) - 1))
            elif len(run) >= min_token_length:
                out.append(run)
    return out


def detect_language(text: str) -> Optional[str]:
    """Character-n-gram profile detection over ~30 languages (ops/lang.py);
    None when the text carries no alphabetic signal."""
    return detect_language_ngram(text)


_TAG_RE = re.compile(
    r"<!--.*?-->|<script\b.*?</script\s*>|<style\b.*?</style\s*>|<[^>]*>",
    re.IGNORECASE | re.DOTALL)


def strip_html(text: str) -> str:
    """Lucene HTMLStripCharFilter analog: drop tags/comments/script/style
    bodies, decode entities (stdlib ``html.unescape``: full named/decimal/
    hex table, single-pass so ``&amp;lt;`` stays ``&lt;``, graceful on
    out-of-range numeric references), keep the visible text."""
    import html as _html
    out = _html.unescape(_TAG_RE.sub(" ", text))
    return out.replace("\xa0", " ")  # &nbsp; decodes to NBSP; normalize


class TextTokenizer(HostTransformer):
    """Text -> TextList through the analyzer chain (reference
    ``TextTokenizer.scala:293`` via Lucene): optional HTML stripping,
    tokenization, language-aware stopword filter, Porter stemming for
    English (the EnglishAnalyzer's PorterStemFilter stage)."""

    in_types = (ft.Text,)
    out_type = ft.TextList

    def __init__(self, lowercase: bool = True, min_token_length: int = 1,
                 auto_detect_language: bool = False,
                 filter_stopwords: bool = False,
                 default_language: str = "en",
                 strip_html_tags: bool = False,
                 stem: bool = False,
                 uid: Optional[str] = None):
        self.lowercase = lowercase
        self.min_token_length = min_token_length
        self.auto_detect_language = auto_detect_language
        self.filter_stopwords = filter_stopwords
        self.default_language = default_language
        self.strip_html_tags = strip_html_tags
        self.stem = stem
        super().__init__(uid=uid)

    def transform_row(self, value):
        if value is None:
            return []
        if self.strip_html_tags:
            value = strip_html(value)
        toks = simple_tokenize(value, self.lowercase, self.min_token_length)
        lang = None
        if self.filter_stopwords or self.stem:
            lang = (detect_language(value) if self.auto_detect_language
                    else self.default_language) or self.default_language
        if self.filter_stopwords:
            stop = STOP_WORDS.get(lang, frozenset())
            toks = [t for t in toks if t not in stop]
        if self.stem and lang == "en":
            from transmogrifai_tpu.ops.stemmer import porter_stem
            toks = [porter_stem(t) for t in toks]
        return toks


class LangDetector(HostTransformer):
    """Text -> RealMap of language -> confidence for the top candidates
    (reference LangDetector emits the Optimaize detected-language score
    map)."""

    in_types = (ft.Text,)
    out_type = ft.RealMap

    def __init__(self, top_k: int = 3, uid: Optional[str] = None):
        self.top_k = top_k
        super().__init__(uid=uid)

    def transform_row(self, value):
        if value is None:
            return {}
        scores = language_scores(value)
        if not scores:
            return {}
        top = sorted(scores.items(), key=lambda kv: -kv[1])[:self.top_k]
        return {k: float(v) for k, v in top if v > 0}


class OpStopWordsRemover(HostTransformer):
    in_types = (ft.TextList,)
    out_type = ft.TextList

    def __init__(self, language: str = "en",
                 extra_stop_words: tuple = (),
                 uid: Optional[str] = None):
        self.language = language
        self.extra_stop_words = tuple(extra_stop_words)
        super().__init__(uid=uid)

    def transform_row(self, tokens):
        stop = STOP_WORDS.get(self.language, frozenset()) | set(
            self.extra_stop_words)
        return [t for t in (tokens or []) if t.lower() not in stop]


class OpNGram(HostTransformer):
    in_types = (ft.TextList,)
    out_type = ft.TextList

    def __init__(self, n: int = 2, uid: Optional[str] = None):
        if n < 1:
            raise ValueError("n must be >= 1")
        self.n = n
        super().__init__(uid=uid)

    def transform_row(self, tokens):
        toks = tokens or []
        n = self.n
        return [" ".join(toks[i:i + n]) for i in range(len(toks) - n + 1)]


def _char_ngrams(s: str, n: int) -> set:
    s = s.lower()
    return {s[i:i + n] for i in range(max(len(s) - n + 1, 1))}


class NGramSimilarity(HostTransformer):
    """(Text, Text) -> RealNN Jaccard similarity of character n-grams
    (reference NGramSimilarity/JaccardSimilarity)."""

    in_types = (ft.Text, ft.Text)
    out_type = ft.RealNN

    def __init__(self, n: int = 3, uid: Optional[str] = None):
        self.n = n
        super().__init__(uid=uid)

    def transform_row(self, a, b):
        if not a or not b:
            return 0.0
        ga, gb = _char_ngrams(a, self.n), _char_ngrams(b, self.n)
        union = len(ga | gb)
        return len(ga & gb) / union if union else 0.0


class TextLenTransformer(HostTransformer):
    """Text/TextList -> total text length vector (reference
    TextLenTransformer)."""

    variadic = True
    in_types = (ft.FeatureType,)
    out_type = ft.OPVector

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    def transform_row(self, *values):
        out = []
        for v in values:
            if v is None:
                out.append(0.0)
            elif isinstance(v, str):
                out.append(float(len(v)))
            elif isinstance(v, (list, tuple, set)):
                out.append(float(sum(len(str(x)) for x in v)))
            else:
                out.append(0.0)
        return np.asarray(out, dtype=np.float32)


class RegexTokenizer(HostTransformer):
    """Text -> TextList of regex tokens (reference RichTextFeature
    ``tokenizeRegex`` via LuceneRegexTextAnalyzer -> Lucene PatternTokenizer,
    ``RichTextFeature.scala:378``, ``LuceneTextAnalyzer.scala:139``).

    ``group`` = -1 SPLITS on the pattern (Lucene's "equivalent to split",
    dropping empty tokens — ``tokenizeRegex(pattern="\\s+")`` yields words);
    ``group`` >= 0 takes that capture group of each match (0 = whole match).
    Tokens shorter than ``min_token_length`` drop.
    """

    in_types = (ft.Text,)
    out_type = ft.TextList

    def __init__(self, pattern: str = r"\W+", group: int = -1,
                 min_token_length: int = 1, lowercase: bool = True,
                 uid: Optional[str] = None):
        self.pattern = pattern
        self.group = int(group)
        self.min_token_length = int(min_token_length)
        self.lowercase = bool(lowercase)
        self._re = re.compile(pattern, re.UNICODE)
        super().__init__(uid=uid)

    def transform_row(self, value):
        if value is None:
            return []
        if self.lowercase:
            value = value.lower()
        if self.group < 0:
            toks = [t for t in self._re.split(value) if t]
        else:
            toks = [m.group(self.group) or ""
                    for m in self._re.finditer(value)]
        return [t for t in toks if len(t) >= self.min_token_length]


class TextToMultiPickList(HostTransformer):
    """Text -> single-element MultiPickList (reference RichTextFeature
    ``toMultiPickList``); empty set when missing."""

    in_types = (ft.Text,)
    out_type = ft.MultiPickList

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    def transform_row(self, value):
        return set() if value is None else {value}


class SetJaccardSimilarity(HostTransformer):
    """(MultiPickList, MultiPickList) -> RealNN Jaccard similarity of the
    two sets (reference ``JaccardSimilarity.scala`` / RichSetFeature
    ``jaccardSimilarity``): |a & b| / |a | b|, and 1.0 when BOTH sides are
    empty (the reference's documented convention)."""

    in_types = (ft.MultiPickList, ft.MultiPickList)
    out_type = ft.RealNN

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    def transform_row(self, a, b):
        sa = set(a or ())
        sb = set(b or ())
        if not sa and not sb:
            return 1.0
        union = len(sa | sb)
        return len(sa & sb) / union
