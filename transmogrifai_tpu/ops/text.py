"""Text NLP chain: tokenization, language detection, stop words, n-grams,
similarity.

Parity: reference ``core/.../stages/impl/feature/{TextTokenizer,
LangDetector, OpStopWordsRemover, OpNGram, NGramSimilarity,
TextLenTransformer}.scala`` and ``core/.../utils/text/*``. The reference
rides Lucene analyzers + the Optimaize detector; here tokenization is a
unicode word-regex analyzer and language detection is stopword-profile
scoring — same stage surface and behavior class, no JVM deps. All of these
are host stages (string work stays off the device; SURVEY §7 hard part #2).
"""

from __future__ import annotations

import re
from typing import Optional

import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import HostTransformer
from transmogrifai_tpu.types import feature_types as ft

__all__ = [
    "TextTokenizer", "LangDetector", "OpStopWordsRemover", "OpNGram",
    "NGramSimilarity", "TextLenTransformer", "STOP_WORDS",
]

_WORD_RE = re.compile(r"[^\W_]+", re.UNICODE)

#: minimal per-language stopword profiles (detection + removal)
STOP_WORDS: dict[str, frozenset] = {
    "en": frozenset("the a an and or of to in is are was were be been i you "
                    "he she it we they this that with for on at by from as "
                    "not no but if then so what which who whom".split()),
    "fr": frozenset("le la les un une des et ou de du au aux en est sont "
                    "était je tu il elle nous vous ils elles ce cette avec "
                    "pour sur par ne pas mais si que qui".split()),
    "de": frozenset("der die das ein eine und oder von zu in ist sind war "
                    "waren ich du er sie es wir ihr mit für auf bei aus "
                    "nicht kein aber wenn dann was welche wer".split()),
    "es": frozenset("el la los las un una unos unas y o de del al en es son "
                    "era yo tú él ella nosotros vosotros ellos con para "
                    "sobre por no pero si que quien".split()),
    "it": frozenset("il lo la i gli le un uno una e o di del della al in è "
                    "sono era io tu lui lei noi voi loro con per su da non "
                    "ma se che chi".split()),
    "pt": frozenset("o a os as um uma uns umas e ou de do da ao em é são "
                    "era eu tu ele ela nós vós eles com para sobre por não "
                    "mas se que quem".split()),
    "nl": frozenset("de het een en of van naar in is zijn was waren ik jij "
                    "hij zij wij jullie met voor op bij uit niet geen maar "
                    "als dan wat welke wie".split()),
}


def simple_tokenize(text: str, lowercase: bool = True,
                    min_token_length: int = 1) -> list[str]:
    if lowercase:
        text = text.lower()
    return [t for t in _WORD_RE.findall(text) if len(t) >= min_token_length]


def detect_language(text: str) -> Optional[str]:
    """Stopword-profile scoring; None when no profile matches."""
    toks = set(simple_tokenize(text))
    if not toks:
        return None
    best, best_score = None, 0
    for lang, words in STOP_WORDS.items():
        score = len(toks & words)
        if score > best_score:
            best, best_score = lang, score
    return best


class TextTokenizer(HostTransformer):
    """Text -> TextList of analyzed tokens (language-aware stopword filter
    when ``auto_detect_language``)."""

    in_types = (ft.Text,)
    out_type = ft.TextList

    def __init__(self, lowercase: bool = True, min_token_length: int = 1,
                 auto_detect_language: bool = False,
                 filter_stopwords: bool = False,
                 default_language: str = "en",
                 uid: Optional[str] = None):
        self.lowercase = lowercase
        self.min_token_length = min_token_length
        self.auto_detect_language = auto_detect_language
        self.filter_stopwords = filter_stopwords
        self.default_language = default_language
        super().__init__(uid=uid)

    def transform_row(self, value):
        if value is None:
            return []
        toks = simple_tokenize(value, self.lowercase, self.min_token_length)
        if self.filter_stopwords:
            lang = (detect_language(value) if self.auto_detect_language
                    else self.default_language) or self.default_language
            stop = STOP_WORDS.get(lang, frozenset())
            toks = [t for t in toks if t not in stop]
        return toks


class LangDetector(HostTransformer):
    """Text -> RealMap of language -> confidence (reference LangDetector
    emits the detected-language score map)."""

    in_types = (ft.Text,)
    out_type = ft.RealMap

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    def transform_row(self, value):
        if value is None:
            return {}
        toks = set(simple_tokenize(value))
        if not toks:
            return {}
        scores = {lang: len(toks & words) / len(toks)
                  for lang, words in STOP_WORDS.items()}
        best = max(scores.values())
        if best <= 0:
            return {}
        return {k: v for k, v in scores.items() if v > 0}


class OpStopWordsRemover(HostTransformer):
    in_types = (ft.TextList,)
    out_type = ft.TextList

    def __init__(self, language: str = "en",
                 extra_stop_words: tuple = (),
                 uid: Optional[str] = None):
        self.language = language
        self.extra_stop_words = tuple(extra_stop_words)
        super().__init__(uid=uid)

    def transform_row(self, tokens):
        stop = STOP_WORDS.get(self.language, frozenset()) | set(
            self.extra_stop_words)
        return [t for t in (tokens or []) if t.lower() not in stop]


class OpNGram(HostTransformer):
    in_types = (ft.TextList,)
    out_type = ft.TextList

    def __init__(self, n: int = 2, uid: Optional[str] = None):
        if n < 1:
            raise ValueError("n must be >= 1")
        self.n = n
        super().__init__(uid=uid)

    def transform_row(self, tokens):
        toks = tokens or []
        n = self.n
        return [" ".join(toks[i:i + n]) for i in range(len(toks) - n + 1)]


def _char_ngrams(s: str, n: int) -> set:
    s = s.lower()
    return {s[i:i + n] for i in range(max(len(s) - n + 1, 1))}


class NGramSimilarity(HostTransformer):
    """(Text, Text) -> RealNN Jaccard similarity of character n-grams
    (reference NGramSimilarity/JaccardSimilarity)."""

    in_types = (ft.Text, ft.Text)
    out_type = ft.RealNN

    def __init__(self, n: int = 3, uid: Optional[str] = None):
        self.n = n
        super().__init__(uid=uid)

    def transform_row(self, a, b):
        if not a or not b:
            return 0.0
        ga, gb = _char_ngrams(a, self.n), _char_ngrams(b, self.n)
        union = len(ga | gb)
        return len(ga & gb) / union if union else 0.0


class TextLenTransformer(HostTransformer):
    """Text/TextList -> total text length vector (reference
    TextLenTransformer)."""

    variadic = True
    in_types = (ft.FeatureType,)
    out_type = ft.OPVector

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    def transform_row(self, *values):
        out = []
        for v in values:
            if v is None:
                out.append(0.0)
            elif isinstance(v, str):
                out.append(float(len(v)))
            elif isinstance(v, (list, tuple, set)):
                out.append(float(sum(len(str(x)) for x in v)))
            else:
                out.append(0.0)
        return np.asarray(out, dtype=np.float32)
