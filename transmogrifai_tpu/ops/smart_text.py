"""SmartTextVectorizer: cardinality-adaptive text vectorization.

Parity: reference ``core/.../stages/impl/feature/SmartTextVectorizer.scala:
62-200`` — per-column ``TextStats`` (a value-count monoid capped at
``max_cardinality``) decides the treatment:

- all empty            -> null-indicator only ("ignore")
- low cardinality      -> categorical pivot (topK + OTHER + null)
- high cardinality     -> hashing trick (+ length feature + null indicator)

Optional name/sensitive-data detection (reference NameDetectFun /
HumanNameDetector): columns whose values look like human names beyond a
threshold are dropped and reported, when enabled (off by default, as in the
reference's SensitiveFeatureMode.Off).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.ops.vectorizers.hashing import hash_token, tokenize
from transmogrifai_tpu.ops.vectorizers.onehot import _top_k
from transmogrifai_tpu.stages.base import Estimator, HostTransformer
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (
    NULL_INDICATOR, OTHER, VectorColumnMetadata, VectorMetadata, parent_of,
)

__all__ = ["TextStats", "SmartTextVectorizer", "SmartTextModel",
           "COMMON_FIRST_NAMES", "looks_like_name"]


from transmogrifai_tpu.utils.dict_encode import \
    scan_column as _scan_column  # shared object-column scanner

#: hash treatments fall back to the per-row loop when the per-unique
#: table (uniques x num_hash_features) would exceed this many floats
#: (true free text — no repetition to exploit)
_UNIQUE_TABLE_CAP = 64_000_000


def pivot_slot_fill(out: np.ndarray, off: int, cats, codes: np.ndarray,
                    vocab, null_mask: np.ndarray,
                    track_nulls: bool) -> None:
    """Columnar categorical pivot: per-UNIQUE slot assignment gathered by
    dict-encode code (categories -> own slot, unknown -> OTHER at k,
    null -> k+1 when tracked). Shared by the scalar SmartText path and the
    keyed-map pivot fills so the encode-gate semantics can't drift."""
    k = len(cats)
    cat_idx = {c: j for j, c in enumerate(cats)}
    slots = np.array([cat_idx.get(v, k) for v in vocab], dtype=np.int64)
    rows = np.nonzero(~null_mask)[0]
    out[rows, off + slots[codes[rows]]] = 1.0
    if track_nulls:
        out[null_mask, off + k + 1] = 1.0


def hashed_unique_table(vocab, num_hash_features: int):
    """[uniques, H] token-count table for a vocab, or None when the table
    would blow the memory cap (caller falls back to the per-row loop)."""
    if len(vocab) * num_hash_features > _UNIQUE_TABLE_CAP:
        return None
    uvecs = np.zeros((len(vocab), num_hash_features), np.float32)
    for u, v in enumerate(vocab):
        for tok in tokenize(v):
            uvecs[u, hash_token(tok, num_hash_features)] += 1.0
    return uvecs


@dataclass
class TextStats:
    """Value-count monoid with cardinality cap (reference TextStats)."""

    counts: dict = field(default_factory=dict)
    n: int = 0
    nulls: int = 0
    overflowed: bool = False
    max_cardinality: int = 100

    def add(self, value: Optional[str]) -> None:
        self.n += 1
        if value is None:
            self.nulls += 1
            return
        if self.overflowed:
            return
        self.counts[value] = self.counts.get(value, 0) + 1
        if len(self.counts) > self.max_cardinality:
            self.overflowed = True
            self.counts.clear()

    @property
    def cardinality(self) -> int:
        return (self.max_cardinality + 1 if self.overflowed
                else len(self.counts))


COMMON_FIRST_NAMES = frozenset(
    "james john robert michael william david richard joseph thomas charles "
    "christopher daniel matthew anthony mark donald steven paul andrew "
    "joshua kenneth kevin brian george timothy ronald edward jason jeffrey "
    "ryan jacob gary nicholas eric jonathan stephen larry justin scott "
    "brandon benjamin samuel gregory frank alexander raymond patrick jack "
    "mary patricia jennifer linda elizabeth barbara susan jessica sarah "
    "karen lisa nancy betty margaret sandra ashley kimberly emily donna "
    "michelle carol amanda dorothy melissa deborah stephanie rebecca sharon "
    "laura cynthia kathleen amy angela shirley anna brenda pamela emma "
    "nicole helen samantha katherine christine debra rachel carolyn janet "
    "catherine maria heather diane ruth julie olivia joyce virginia".split())


def looks_like_name(value: str) -> bool:
    toks = tokenize(value)
    return bool(toks) and any(t in COMMON_FIRST_NAMES for t in toks)


class SmartTextVectorizer(Estimator):
    """Variadic estimator over Text inputs with per-column treatment."""

    variadic = True
    in_types = (ft.Text,)
    out_type = ft.OPVector

    def __init__(self, max_cardinality: int = 100, top_k: int = 20,
                 min_support: int = 10, num_hash_features: int = 512,
                 track_nulls: bool = True, track_text_len: bool = True,
                 detect_names: bool = False, name_threshold: float = 0.5,
                 uid: Optional[str] = None):
        self.max_cardinality = max_cardinality
        self.top_k = top_k
        self.min_support = min_support
        self.num_hash_features = num_hash_features
        self.track_nulls = track_nulls
        self.track_text_len = track_text_len
        self.detect_names = detect_names
        self.name_threshold = name_threshold
        super().__init__(uid=uid)

    def fit_model(self, data) -> "SmartTextModel":
        treatments: list[dict] = []
        for name in self.input_names:
            col = data.host_col(name)
            if not self.detect_names:
                # vectorized stats (the Criteo hot path: 26 columns x 10M+
                # rows): one native dict-encode pass + a bincount replaces
                # n per-row TextStats.add() calls. Final-state equivalent:
                # overflow iff total uniques exceed the cap, counts over
                # all values otherwise.
                vals = np.asarray(col.values, dtype=object)
                null_mask, all_str = _scan_column(vals)
                nulls = int(null_mask.sum())
                non_null = len(vals) - nulls
                stats = TextStats(max_cardinality=self.max_cardinality)
                stats.n = len(vals)
                stats.nulls = nulls
                if non_null and not all_str:
                    # non-string objects leaked into the column: the
                    # vectorized encoder would stringify them and the
                    # fitted categories would no longer match raw values
                    # at scoring time — count the slow exact way
                    stats = TextStats(max_cardinality=self.max_cardinality)
                    for v in col.values:
                        stats.add(v)
                elif non_null:
                    from transmogrifai_tpu.utils.dict_encode import \
                        dict_encode
                    codes, vocab = dict_encode(vals)
                    if len(vocab) > self.max_cardinality:
                        stats.overflowed = True
                    else:
                        counts = np.bincount(codes[codes >= 0],
                                             minlength=len(vocab))
                        stats.counts = {v: int(c)
                                        for v, c in zip(vocab, counts)}
                name_hits = 0
            else:
                stats = TextStats(max_cardinality=self.max_cardinality)
                name_hits = 0
                non_null = 0
                for v in col.values:
                    stats.add(v)
                    if v is not None:
                        non_null += 1
                        if looks_like_name(v):
                            name_hits += 1
            if self.detect_names and non_null > 0 \
                    and name_hits / non_null >= self.name_threshold:
                # record WHAT was detected, not just that the column vanished
                # (reference SensitiveFeatureInformation rides into
                # ModelInsights via vector metadata)
                treatments.append({"kind": "sensitive",
                                   "prob_name": name_hits / non_null})
            elif non_null == 0:
                treatments.append({"kind": "ignore"})
            elif not stats.overflowed:
                cats = _top_k(list(stats.counts), list(stats.counts.values()),
                              self.top_k, self.min_support)
                treatments.append({"kind": "pivot", "categories": cats})
            else:
                treatments.append({"kind": "hash"})
        return SmartTextModel(
            treatments=treatments, num_hash_features=self.num_hash_features,
            track_nulls=self.track_nulls, track_text_len=self.track_text_len)


class SmartTextModel(HostTransformer):
    variadic = True
    in_types = (ft.Text,)
    out_type = ft.OPVector

    def __init__(self, treatments: Sequence[dict] = (),
                 num_hash_features: int = 512, track_nulls: bool = True,
                 track_text_len: bool = True, uid: Optional[str] = None):
        self.treatments = [dict(t) for t in treatments]
        self.num_hash_features = num_hash_features
        self.track_nulls = track_nulls
        self.track_text_len = track_text_len
        super().__init__(uid=uid)

    # -- layout --------------------------------------------------------------
    def _width(self, t: dict) -> int:
        kind = t["kind"]
        if kind in ("sensitive",):
            return 0
        if kind == "ignore":
            return 1 if self.track_nulls else 0
        if kind == "pivot":
            return len(t["categories"]) + 1 + (1 if self.track_nulls else 0)
        w = self.num_hash_features
        if self.track_text_len:
            w += 1
        if self.track_nulls:
            w += 1
        return w

    def _fill_row(self, out: np.ndarray, offset: int, t: dict,
                  v: Optional[str]) -> None:
        kind = t["kind"]
        if kind == "sensitive":
            return
        if kind == "ignore":
            if self.track_nulls:
                out[offset] = 1.0 if v is None else 0.0
            return
        if kind == "pivot":
            cats = t["categories"]
            k = len(cats)
            if v is None:
                if self.track_nulls:
                    out[offset + k + 1] = 1.0
            elif v in cats:
                out[offset + cats.index(v)] = 1.0
            else:
                out[offset + k] = 1.0
            return
        # hash
        base = offset
        if v is not None:
            for tok in tokenize(v):
                out[base + hash_token(tok, self.num_hash_features)] += 1.0
        pos = base + self.num_hash_features
        if self.track_text_len:
            out[pos] = 0.0 if v is None else float(len(v))
            pos += 1
        if self.track_nulls:
            out[pos] = 1.0 if v is None else 0.0

    def transform_row(self, *values):
        total = sum(self._width(t) for t in self.treatments)
        out = np.zeros(total, dtype=np.float32)
        offset = 0
        for t, v in zip(self.treatments, values):
            self._fill_row(out, offset, t, v)
            offset += self._width(t)
        return out

    def host_apply(self, *cols: fr.HostColumn) -> fr.HostColumn:
        n = len(cols[0])
        total = sum(self._width(t) for t in self.treatments)
        out = np.zeros((n, total), dtype=np.float32)
        offset = 0
        for t, col in zip(self.treatments, cols):
            self._fill_column(out, offset, t, col.values, n)
            offset += self._width(t)
        return fr.HostColumn(ft.OPVector, out, meta=self._meta())

    def _fill_column(self, out: np.ndarray, offset: int, t: dict,
                     values, n: int) -> None:
        """Columnar treatment fill — exact per-row (_fill_row) semantics,
        vectorized for the Criteo-scale categorical path: one native
        dict-encode pass per column, then per-UNIQUE work (category slot /
        hashed token counts) gathered back by code. Python cost is
        O(uniques), not O(rows)."""
        kind = t["kind"]
        if kind == "sensitive":
            return
        vals = np.asarray(values, dtype=object)
        null_mask, all_str = _scan_column(vals)
        if kind == "ignore":
            if self.track_nulls:
                out[:, offset] = null_mask.astype(np.float32)
            return
        if not all_str:
            # non-string objects: the encoder's vocab is stringified and
            # would mis-route category matching — exact per-row semantics
            for r in range(n):
                self._fill_row(out[r], offset, t, values[r])
            return
        from transmogrifai_tpu.utils.dict_encode import dict_encode
        codes, vocab = dict_encode(vals)
        present = ~null_mask
        if kind == "pivot":
            pivot_slot_fill(out, offset, t["categories"], codes, vocab,
                            null_mask, self.track_nulls)
            return
        # hash
        H = self.num_hash_features
        uvecs = hashed_unique_table(vocab, H)
        if uvecs is None:  # table over the memory cap: exact per-row
            for r in range(n):
                self._fill_row(out[r], offset, t, values[r])
            return
        out[present, offset:offset + H] = uvecs[codes[present]]
        pos = offset + H
        if self.track_text_len:
            vlens = np.array([len(v) for v in vocab], np.float32)
            lens = np.zeros(n, np.float32)
            lens[present] = vlens[codes[present]]
            out[:, pos] = lens
            pos += 1
        if self.track_nulls:
            out[:, pos] = null_mask.astype(np.float32)

    def _meta(self) -> VectorMetadata:
        cols: list[VectorColumnMetadata] = []
        for t, f in zip(self.treatments, self.input_features):
            parent = parent_of(f)
            kind = t["kind"]
            if kind == "sensitive":
                continue
            if kind == "ignore":
                if self.track_nulls:
                    cols.append(VectorColumnMetadata(
                        *parent, grouping=f.name,
                        indicator_value=NULL_INDICATOR))
                continue
            if kind == "pivot":
                for c in t["categories"]:
                    cols.append(VectorColumnMetadata(
                        *parent, grouping=f.name, indicator_value=c))
                cols.append(VectorColumnMetadata(
                    *parent, grouping=f.name, indicator_value=OTHER))
                if self.track_nulls:
                    cols.append(VectorColumnMetadata(
                        *parent, grouping=f.name,
                        indicator_value=NULL_INDICATOR))
                continue
            for j in range(self.num_hash_features):
                cols.append(VectorColumnMetadata(
                    *parent, grouping=f.name, descriptor_value=f"hash_{j}"))
            if self.track_text_len:
                cols.append(VectorColumnMetadata(
                    *parent, grouping=f.name, descriptor_value="textLen"))
            if self.track_nulls:
                cols.append(VectorColumnMetadata(
                    *parent, grouping=f.name, indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.get_output().name, tuple(cols)).reindexed(0)

    def sensitive_features(self) -> list[str]:
        return [f.name for t, f in zip(self.treatments, self.input_features)
                if t["kind"] == "sensitive"]

    def sensitive_info(self) -> dict[str, dict]:
        """SensitiveFeatureInformation analog: name -> detection record for
        every input column the fit dropped as sensitive."""
        return {f.name: {"detected": True,
                         "probName": t.get("prob_name"),
                         "action": "removedFromVector"}
                for t, f in zip(self.treatments, self.input_features)
                if t["kind"] == "sensitive"}

    def fitted_state(self):
        return {"treatments": self.treatments}

    def set_fitted_state(self, state):
        self.treatments = [dict(t) for t in state["treatments"]]
