"""Murmur-style feature hashing: host reference hash + Pallas TPU kernel
for the segment one-hot accumulate.

The hashing-trick vectorizers split into two halves:

- **hashing** a categorical value to a bin — murmur3 x86_32 over the
  value's UTF-8 bytes (:func:`murmur3_str`, the host reference used by the
  row path and by trace-time vocab tables) or the integer finalizer
  (:func:`murmur_mix32`) for already-integer keys. Per-UNIQUE work: the
  device vectorizer hashes each dictionary vocab entry once at trace time
  (O(V), like ``OneHotModel``'s category table), never per row.
- **accumulating** the per-row bins into a dense ``[n, n_bins]`` count
  block — O(n x bins) of pure VPU work, the expensive half the host
  vectorizer used to pay in Python. :func:`segment_onehot` runs it as a
  Pallas kernel (one grid step = one row block; the ``[R, T]`` bin ids and
  the ``[R, n_bins]`` output tile live in VMEM; tokens accumulate by a
  static unroll of iota-compares — "segment accumulate" with the segment
  axis materialized as the row block) with a pure-XLA fallback
  (:func:`segment_onehot_xla`) that computes the identical compare-and-sum,
  so CPU CI asserts BITWISE parity in interpret mode.

Engine selection: ``TRANSMOGRIFAI_HASH_ENGINE`` = ``auto`` (pallas on TPU
backends) | ``pallas`` | ``xla``. The kernel is stateless per grid step —
``vmap`` batching stays legal (same discipline as
``ops/sorted_hist_pallas.py``).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["murmur3_str", "murmur3_bytes", "murmur_mix32",
           "segment_onehot", "segment_onehot_xla", "hash_engine"]

_M32 = 0xFFFFFFFF

#: rows per kernel grid step
_BLOCK_ROWS = 512


def hash_engine() -> str:
    eng = os.environ.get("TRANSMOGRIFAI_HASH_ENGINE", "auto")
    if eng not in ("auto", "pallas", "xla"):
        raise ValueError(
            f"TRANSMOGRIFAI_HASH_ENGINE={eng!r}; one of auto|pallas|xla")
    if eng == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return eng


def murmur3_bytes(data: bytes, seed: int = 0) -> int:
    """Murmur3 x86_32 over raw bytes (reference implementation; matches
    Spark's ``Murmur3_x86_32`` family the reference HashingTF rides)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & _M32
    n = len(data)
    n4 = n - (n % 4)
    for i in range(0, n4, 4):
        k = int.from_bytes(data[i:i + 4], "little")
        k = (k * c1) & _M32
        k = ((k << 15) | (k >> 17)) & _M32
        k = (k * c2) & _M32
        h ^= k
        h = ((h << 13) | (h >> 19)) & _M32
        h = (h * 5 + 0xE6546B64) & _M32
    k = 0
    tail = data[n4:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & _M32
        k = ((k << 15) | (k >> 17)) & _M32
        k = (k * c2) & _M32
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return h


def murmur3_str(value: str, seed: int = 0) -> int:
    """Murmur3 x86_32 of a string's UTF-8 bytes — THE hash shared by the
    device vectorizer's trace-time vocab table and the row-path parity
    contract."""
    return murmur3_bytes(value.encode("utf-8"), seed)


@jax.jit
def murmur_mix32(x):
    """Murmur3 fmix32 finalizer as a jittable uint32 map — device-side
    hashing for integer-keyed features (avalanches sequential ids across
    bins)."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def segment_onehot_xla(bin_ids, n_bins: int):
    """Pure-XLA fallback: ``out[r, b] = #{t : bin_ids[r, t] == b}`` with
    negative ids (missing/padding tokens) contributing nothing. The
    compare-and-sum runs in the same static token order as the kernel, so
    the two are bitwise-identical (0/1 float sums are exact)."""
    n, T = bin_ids.shape
    lanes = jax.lax.broadcasted_iota(jnp.int32, (n, n_bins), 1)
    out = jnp.zeros((n, n_bins), jnp.float32)
    for t in range(T):  # static unroll — T is the (small) token capacity
        col = bin_ids[:, t]
        out = out + ((lanes == col[:, None]) & (col >= 0)[:, None]
                     ).astype(jnp.float32)
    return out


def _kernel(ids_ref, out_ref, *, T: int, n_bins: int):
    """One grid step = one row block: [R, T] bin ids -> [R, n_bins]
    counts, all VMEM-resident, tokens accumulated by static unroll."""
    ids = ids_ref[0]  # [R, T] int32
    R = ids.shape[0]
    lanes = jax.lax.broadcasted_iota(jnp.int32, (R, n_bins), 1)
    acc = jnp.zeros((R, n_bins), jnp.float32)
    for t in range(T):
        col = ids[:, t]
        acc = acc + ((lanes == col[:, None]) & (col >= 0)[:, None]
                     ).astype(jnp.float32)
    out_ref[0] = acc


@functools.partial(jax.jit, static_argnames=("n_bins", "interpret"))
def _segment_onehot_pallas(bin_ids, *, n_bins: int, interpret: bool):
    n, T = bin_ids.shape
    R = min(_BLOCK_ROWS, max(int(n), 1))
    n_pad = int(np.ceil(max(n, 1) / R) * R)
    ids = jnp.pad(bin_ids.astype(jnp.int32), ((0, n_pad - n), (0, 0)),
                  constant_values=-1)  # padding rows count nothing
    nb = n_pad // R
    out = pl.pallas_call(
        functools.partial(_kernel, T=T, n_bins=n_bins),
        grid=(nb,),
        in_specs=[pl.BlockSpec((1, R, T), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, R, n_bins), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb, R, n_bins), jnp.float32),
        interpret=interpret,
    )(ids.reshape(nb, R, T))
    return out.reshape(n_pad, n_bins)[:n]


def segment_onehot(bin_ids, n_bins: int, engine: str | None = None,
                   interpret: bool | None = None):
    """Engine-dispatched segment one-hot accumulate (see module
    docstring). ``bin_ids``: int32 [n, T], -1 = no token."""
    eng = engine or hash_engine()
    if eng != "pallas":
        return segment_onehot_xla(bin_ids, n_bins)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _segment_onehot_pallas(bin_ids, n_bins=int(n_bins),
                                  interpret=bool(interpret))
