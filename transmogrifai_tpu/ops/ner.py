"""Trainable/loadable named-entity sequence tagger (asset-scale NER hook).

Parity: reference ``core/.../utils/text/OpenNLPNameEntityTagger.scala`` +
the binary MaxEnt models under ``models/src/main/resources/OpenNLP`` — the
reference's NER quality comes from *pretrained assets* loaded at runtime.
This module provides the TPU build's equivalent asset pipeline:

- a linear-chain tagger: per-token hashed features (identity, shape,
  affixes, context, dictionary membership) scored by per-tag weight
  vectors + a tag-transition matrix, decoded with Viterbi;
- averaged-perceptron training (``train_tagger``) so models can be built
  from any token/tag corpus;
- an ``.npz`` asset format with save/load and the
  ``TRANSMOGRIFAI_NER_MODEL`` environment hook (mirrors the
  ``TRANSMOGRIFAI_NAME_DICT`` dictionary hook in ops/names.py);
- ``NameEntityRecognizer`` (ops/names.py) consumes a loaded model when one
  is present and falls back to its dictionary/heuristic tagger otherwise.

The decoder is intentionally host-side: NER happens at ingest/feature
extraction on strings, never on the device path (SURVEY §7 hard part (b)).
"""

from __future__ import annotations

import os
import zlib
from typing import Optional, Sequence

import numpy as np

__all__ = ["ViterbiTagger", "train_tagger", "load_tagger", "default_tagger",
           "read_conll", "evaluate_tagger"]

#: tagset (IO scheme — OpenNLP's person/location/organization finders)
TAGS = ("O", "PER", "LOC", "ORG")
_TAG_IDX = {t: i for i, t in enumerate(TAGS)}

#: hashed feature space per tag
DIM = 1 << 17


def _h(s: str) -> int:
    return zlib.crc32(s.encode("utf-8")) % DIM


def _shape(tok: str) -> str:
    out = []
    for ch in tok[:4]:
        out.append("X" if ch.isupper() else
                   "x" if ch.islower() else
                   "d" if ch.isdigit() else ch)
    return "".join(out)


def token_features(tokens: Sequence[str], i: int,
                   dicts: Optional[dict] = None) -> list[int]:
    """Hashed feature ids for position i (identity/shape/affix/context +
    dictionary membership when dictionaries are supplied)."""
    tok = tokens[i]
    low = tok.lower()
    prev = tokens[i - 1].lower() if i > 0 else "<s>"
    nxt = tokens[i + 1].lower() if i + 1 < len(tokens) else "</s>"
    feats = [
        _h("w=" + low), _h("shape=" + _shape(tok)),
        _h("pre3=" + low[:3]), _h("suf3=" + low[-3:]),
        _h("prev=" + prev), _h("next=" + nxt),
        _h("cap=" + str(tok[:1].isupper())),
        _h("pos=" + ("first" if i == 0 else "in")),
    ]
    if dicts:
        for name, vocab in dicts.items():
            if low in vocab:
                feats.append(_h("dict=" + name))
    return feats


class ViterbiTagger:
    """Linear-chain tagger: emissions from hashed-feature weights, first-
    order transitions, exact Viterbi decoding."""

    def __init__(self, weights: Optional[np.ndarray] = None,
                 transitions: Optional[np.ndarray] = None,
                 dicts: Optional[dict] = None,
                 metadata: Optional[dict] = None):
        T = len(TAGS)
        self.weights = (weights if weights is not None
                        else np.zeros((T, DIM), np.float32))
        self.transitions = (transitions if transitions is not None
                            else np.zeros((T, T), np.float32))
        self.dicts = dicts or {}
        #: provenance + measured quality (precision/recall per class on the
        #: committed annotated fixture), recorded by the asset builder
        self.metadata = dict(metadata or {})

    def _emissions(self, tokens: Sequence[str]) -> np.ndarray:
        T = len(TAGS)
        out = np.zeros((len(tokens), T), np.float32)
        for i in range(len(tokens)):
            fs = token_features(tokens, i, self.dicts)
            out[i] = self.weights[:, fs].sum(axis=1)
        return out

    def tag(self, tokens: Sequence[str]) -> list[str]:
        n = len(tokens)
        if n == 0:
            return []
        T = len(TAGS)
        em = self._emissions(tokens)
        score = np.full((n, T), -np.inf, np.float32)
        back = np.zeros((n, T), np.int32)
        score[0] = em[0]
        for i in range(1, n):
            # [prev, cur] candidate scores
            cand = score[i - 1][:, None] + self.transitions + em[i][None, :]
            back[i] = np.argmax(cand, axis=0)
            score[i] = cand[back[i], np.arange(T)]
        path = [int(np.argmax(score[-1]))]
        for i in range(n - 1, 0, -1):
            path.append(int(back[i, path[-1]]))
        return [TAGS[t] for t in reversed(path)]

    # -- asset format --------------------------------------------------------
    def save(self, path: str) -> None:
        import json
        arrs = {"weights": self.weights, "transitions": self.transitions}
        for name, vocab in self.dicts.items():
            arrs[f"dict_{name}"] = np.array(sorted(vocab), dtype="U")
        if self.metadata:
            arrs["meta_json"] = np.array(json.dumps(self.metadata),
                                         dtype="U")
        np.savez_compressed(path, **arrs)

    @staticmethod
    def load(path: str) -> "ViterbiTagger":
        import json
        data = np.load(path, allow_pickle=False)
        dicts = {k[5:]: frozenset(str(v) for v in data[k])
                 for k in data.files if k.startswith("dict_")}
        meta = (json.loads(str(data["meta_json"]))
                if "meta_json" in data.files else {})
        return ViterbiTagger(weights=data["weights"].astype(np.float32),
                             transitions=data["transitions"].astype(
                                 np.float32),
                             dicts=dicts, metadata=meta)


def train_tagger(sentences: Sequence[Sequence[str]],
                 tag_seqs: Sequence[Sequence[str]],
                 dicts: Optional[dict] = None,
                 epochs: int = 5, seed: int = 0) -> ViterbiTagger:
    """Averaged structured perceptron over Viterbi decodes — the classic
    Collins (2002) trainer; small, dependency-free, and good enough to
    build real assets from any token/tag corpus."""
    T = len(TAGS)
    w = np.zeros((T, DIM), np.float32)
    trans = np.zeros((T, T), np.float32)
    w_sum = np.zeros_like(w)
    trans_sum = np.zeros_like(trans)
    tagger = ViterbiTagger(w, trans, dicts)
    rng = np.random.default_rng(seed)
    order = np.arange(len(sentences))
    steps = 0

    def update(toks, gold, pred):
        for i in range(len(toks)):
            g, p = _TAG_IDX[gold[i]], _TAG_IDX[pred[i]]
            if g != p:
                fs = token_features(toks, i, dicts)
                w[g, fs] += 1.0
                w[p, fs] -= 1.0
            if i > 0:
                gp, pp = _TAG_IDX[gold[i - 1]], _TAG_IDX[pred[i - 1]]
                if (gp, g) != (pp, p):
                    trans[gp, g] += 1.0
                    trans[pp, p] -= 1.0

    for _ in range(epochs):
        rng.shuffle(order)
        for si in order:
            toks, gold = sentences[si], tag_seqs[si]
            pred = tagger.tag(toks)
            steps += 1
            if pred != list(gold):
                update(toks, gold, pred)
            # the Collins average is over EVERY step's weights — summing
            # only at mistake steps would bias the average toward early
            # noisy snapshots and underweight the converged weights
            w_sum += w
            trans_sum += trans
    if steps:  # averaged weights generalize far better than the last ones
        tagger.weights = (w_sum / steps).astype(np.float32)
        tagger.transitions = (trans_sum / steps).astype(np.float32)
    return tagger


def read_conll(path: str) -> tuple[list[list[str]], list[list[str]]]:
    """Read a two-column (token<TAB>tag) file with blank-line sentence
    breaks — the format of the committed annotated evaluation fixture."""
    sents: list[list[str]] = []
    tags: list[list[str]] = []
    cur_t: list[str] = []
    cur_g: list[str] = []
    with open(path) as fh:
        for line in fh:
            if not line.strip():
                if cur_t:
                    sents.append(cur_t)
                    tags.append(cur_g)
                cur_t, cur_g = [], []
            else:
                # token is the first column, tag the last: accepts the
                # committed 2-column fixture AND space-separated /
                # multi-column CoNLL-2003 files (token POS chunk NER)
                cols = line.split()
                cur_t.append(cols[0])
                cur_g.append(cols[-1])
    if cur_t:
        sents.append(cur_t)
        tags.append(cur_g)
    return sents, tags


def evaluate_tagger(tagger: "ViterbiTagger",
                    sentences: Sequence[Sequence[str]],
                    tag_seqs: Sequence[Sequence[str]]) -> dict:
    """Token-level precision/recall/F1 per entity class + overall token
    accuracy — the quality record the asset metadata carries (reference
    OpenNLP models ship with published eval numbers; ours travel WITH the
    asset)."""
    tp: dict = {}
    fp: dict = {}
    fn: dict = {}
    correct = total = 0
    for toks, gold in zip(sentences, tag_seqs):
        pred = tagger.tag(list(toks))
        for p, g in zip(pred, gold):
            total += 1
            correct += p == g
            if p == g:
                if g != "O":
                    tp[g] = tp.get(g, 0) + 1
            else:
                if p != "O":
                    fp[p] = fp.get(p, 0) + 1
                if g != "O":
                    fn[g] = fn.get(g, 0) + 1
    out = {"token_accuracy": round(correct / max(total, 1), 4),
           "n_sentences": len(sentences), "n_tokens": total}
    for c in TAGS[1:]:
        p = tp.get(c, 0) / max(tp.get(c, 0) + fp.get(c, 0), 1)
        r = tp.get(c, 0) / max(tp.get(c, 0) + fn.get(c, 0), 1)
        out[c] = {"precision": round(p, 4), "recall": round(r, 4),
                  "f1": round(2 * p * r / max(p + r, 1e-12), 4)}
    return out


_loaded: dict = {"tried": False, "tagger": None}


def load_tagger(path: str) -> ViterbiTagger:
    return ViterbiTagger.load(path)


def default_tagger() -> Optional[ViterbiTagger]:
    """The asset hook: loads $TRANSMOGRIFAI_NER_MODEL (.npz) once, None
    when unset/unloadable (callers fall back to heuristics)."""
    if not _loaded["tried"]:
        _loaded["tried"] = True
        path = os.environ.get("TRANSMOGRIFAI_NER_MODEL")
        if path and not os.path.exists(path):
            import warnings
            warnings.warn(
                f"TRANSMOGRIFAI_NER_MODEL={path!r} does not exist; "
                "falling back to the dictionary/heuristic tagger",
                RuntimeWarning)
        elif path:
            try:
                _loaded["tagger"] = ViterbiTagger.load(path)
            except Exception as e:  # noqa: BLE001
                # an explicitly-requested model must not fail SILENTLY
                # into the heuristic path
                import warnings
                warnings.warn(
                    f"TRANSMOGRIFAI_NER_MODEL={path!r} failed to load "
                    f"({type(e).__name__}: {e}); falling back to the "
                    "dictionary/heuristic tagger", RuntimeWarning)
                _loaded["tagger"] = None
    return _loaded["tagger"]
