"""Label indexing and label/probability joining stages.

Parity: reference ``core/.../stages/impl/feature/{OpStringIndexer,
OpStringIndexerNoFilter, OpIndexToString, OpIndexToStringNoFilter,
MultiLabelJoiner, TextListNullTransformer}.scala`` — string label <-> index
round-trips for multiclass labels, joining class probabilities back to label
strings, and null-tracking for text lists.

These are thin host-side stages (string-shaped, fit once); the heavy
numeric consumers downstream stay on device.
"""

from __future__ import annotations

from collections import Counter
from typing import Optional, Sequence

import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import (
    AllowLabelAsInput, Estimator, HostTransformer,
)
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (
    NULL_INDICATOR, VectorColumnMetadata, VectorMetadata, parent_of,
)

__all__ = [
    "OpStringIndexer", "OpStringIndexerNoFilter", "StringIndexerModel",
    "OpIndexToString", "OpIndexToStringNoFilter",
    "MultiLabelJoiner", "TopNLabelJoiner", "TopNLabelProbMap",
    "TextListNullTransformer", "UNSEEN_LABEL", "UNSEEN_INDEX",
]

UNSEEN_LABEL = "UnseenLabel"
UNSEEN_INDEX = "UnseenIndex"


def _labels_by_count(values, skip_null: bool) -> list[Optional[str]]:
    """Labels most-frequent-first (ties lexicographic, nulls last)."""
    counts = Counter(values)
    return [lb for lb, _ in sorted(
        counts.items(),
        key=lambda kv: (-kv[1], kv[0] is None, kv[0] or ""))
        if not (skip_null and lb is None)]


class OpStringIndexer(Estimator):
    """Text labels -> label indices ordered by descending frequency.

    ``handle_invalid``: "error" raises on unseen values at score time;
    "skip" maps them to missing (the Spark StringIndexer analog of dropping
    the row).
    """

    in_types = (ft.Text,)
    out_type = ft.RealNN

    def __init__(self, handle_invalid: str = "error",
                 uid: Optional[str] = None):
        if handle_invalid not in ("error", "skip"):
            raise ValueError("handle_invalid must be 'error' or 'skip'")
        self.handle_invalid = handle_invalid
        if handle_invalid == "skip":
            self.out_type = ft.Real  # unseen labels become nulls
        super().__init__(uid=uid)

    def fit_model(self, data):
        col = data.host_col(self.input_names[0])
        vals = [col.python_value(i) for i in range(len(col))]
        labels = [lb for lb in _labels_by_count(vals, skip_null=True)]
        return StringIndexerModel(labels=labels,
                                  handle_invalid=self.handle_invalid)


class OpStringIndexerNoFilter(Estimator):
    """Indexer that never fails: unseen/new values map to the extra
    ``unseen_name`` slot at index ``len(labels)`` (reference
    ``OpStringIndexerNoFilter.scala:54-70``); nulls are indexed as "null"."""

    in_types = (ft.Text,)
    out_type = ft.RealNN

    def __init__(self, unseen_name: str = UNSEEN_LABEL,
                 uid: Optional[str] = None):
        self.unseen_name = unseen_name
        super().__init__(uid=uid)

    def fit_model(self, data):
        col = data.host_col(self.input_names[0])
        vals = [col.python_value(i) for i in range(len(col))]
        labels = ["null" if lb is None else lb
                  for lb in _labels_by_count(vals, skip_null=False)]
        return StringIndexerModel(labels=labels, handle_invalid="unseen",
                                  unseen_name=self.unseen_name)


class StringIndexerModel(HostTransformer):
    in_types = (ft.Text,)
    out_type = ft.RealNN

    def __init__(self, labels: Sequence[str] = (),
                 handle_invalid: str = "error",
                 unseen_name: str = UNSEEN_LABEL,
                 uid: Optional[str] = None):
        self.labels = list(labels)
        self.handle_invalid = handle_invalid
        self.unseen_name = unseen_name
        self._index = {lb: i for i, lb in enumerate(self.labels)}
        if handle_invalid == "skip":
            # skip mode emits None for unseen labels (Spark drops the row;
            # here nullability must be declared) — the RealNN never-null
            # contract cannot hold, so the output is nullable Real
            self.out_type = ft.Real
        super().__init__(uid=uid)

    @property
    def all_labels(self) -> list[str]:
        """Labels incl. the unseen slot when present (for joiners)."""
        if self.handle_invalid == "unseen":
            return self.labels + [self.unseen_name]
        return self.labels

    def transform_row(self, value):
        key = "null" if (value is None and self.handle_invalid == "unseen"
                         ) else value
        if key in self._index:
            return float(self._index[key])
        if self.handle_invalid == "unseen":
            return float(len(self.labels))
        if self.handle_invalid == "skip" or value is None:
            return None
        raise ValueError(
            f"{self}: unseen label {value!r} (handle_invalid='error')")

    def fitted_state(self):
        return {"labels": list(self.labels)}  # strings ride the JSON side

    def set_fitted_state(self, state):
        self.labels = [str(x) for x in state["labels"]]
        self._index = {lb: i for i, lb in enumerate(self.labels)}

    def config(self):
        return {"handle_invalid": self.handle_invalid,
                "unseen_name": self.unseen_name}


class OpIndexToString(HostTransformer, AllowLabelAsInput):
    """Label indices -> label strings from a user-supplied labels array.

    Out-of-range indices raise; use ``OpIndexToStringNoFilter`` to map them
    to ``unseen_name`` instead.
    """

    in_types = (ft.RealNN,)
    out_type = ft.Text

    def __init__(self, labels: Sequence[str] = (), uid: Optional[str] = None):
        self.labels = list(labels)
        super().__init__(uid=uid)

    def transform_row(self, value):
        if value is None:
            return None
        i = int(value)
        if 0 <= i < len(self.labels):
            return self.labels[i]
        return self._out_of_range(i)

    def _out_of_range(self, i: int):
        raise ValueError(f"{self}: index {i} outside labels array "
                         f"(size {len(self.labels)})")

    def config(self):
        return {"labels": self.labels}


class OpIndexToStringNoFilter(OpIndexToString):
    def __init__(self, labels: Sequence[str] = (),
                 unseen_name: str = UNSEEN_INDEX, uid: Optional[str] = None):
        self.unseen_name = unseen_name
        super().__init__(labels=labels, uid=uid)

    def _out_of_range(self, i: int):
        return self.unseen_name

    def config(self):
        return {"labels": self.labels, "unseen_name": self.unseen_name}


class MultiLabelJoiner(HostTransformer, AllowLabelAsInput):
    """(indexed label, class-probability vector) -> {label: probability}.

    Parity: reference ``MultiLabelJoiner.scala:44-59`` (labels come from the
    indexer's metadata there; passed explicitly or wired from a
    ``StringIndexerModel`` here).
    """

    in_types = (ft.RealNN, ft.OPVector)
    out_type = ft.RealMap

    def __init__(self, labels: Sequence[str] = (), uid: Optional[str] = None):
        self.labels = list(labels)
        super().__init__(uid=uid)

    @classmethod
    def from_indexer(cls, indexer: StringIndexerModel) -> "MultiLabelJoiner":
        return cls(labels=indexer.all_labels)

    def runtime_input_names(self):
        return (self.input_names[1],)

    def transform_row(self, *values):
        probs = values[-1]
        if probs is None:
            return {}
        arr = np.asarray(probs, np.float64).ravel()
        return {lb: float(p) for lb, p in zip(self.labels, arr)}

    def config(self):
        return {"labels": self.labels}


def top_n_of(label_prob: dict, top_n: int) -> dict:
    pairs = sorted(label_prob.items(), key=lambda kv: (-kv[1], kv[0]))
    return dict(pairs[:top_n])


class TopNLabelJoiner(MultiLabelJoiner):
    """MultiLabelJoiner keeping only the topN classes by probability and
    dropping the UnseenLabel class (reference ``TopNLabelJoiner``)."""

    def __init__(self, labels: Sequence[str] = (), top_n: int = 3,
                 uid: Optional[str] = None):
        self.top_n = top_n
        super().__init__(labels=labels, uid=uid)

    def transform_row(self, *values):
        full = super().transform_row(*values)
        full.pop(UNSEEN_LABEL, None)
        return top_n_of(full, self.top_n)

    def config(self):
        return {"labels": self.labels, "top_n": self.top_n}


class TopNLabelProbMap(HostTransformer):
    """RealMap of label->prob -> its topN entries (reference
    ``TopNLabelProbMap``)."""

    in_types = (ft.RealMap,)
    out_type = ft.RealMap

    def __init__(self, top_n: int = 3, uid: Optional[str] = None):
        self.top_n = top_n
        super().__init__(uid=uid)

    def transform_row(self, value):
        return top_n_of(value or {}, self.top_n)


class TextListNullTransformer(HostTransformer):
    """N TextList inputs -> vector of empty/null indicators (reference
    ``TextListNullTransformer.scala:48-68``)."""

    variadic = True
    in_types = (ft.TextList,)
    out_type = ft.OPVector

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    def transform_row(self, *values):
        return np.asarray([1.0 if not v else 0.0 for v in values],
                          np.float32)

    def host_apply(self, *cols: fr.HostColumn):
        rows = np.stack([self.transform_row(
            *(c.python_value(i) for c in cols))
            for i in range(len(cols[0]))]) if len(cols[0]) else np.zeros(
            (0, len(cols)), np.float32)
        name = self.get_output().name
        meta = VectorMetadata(name, tuple(
            VectorColumnMetadata(*parent_of(f), grouping=f.name,
                                 indicator_value=NULL_INDICATOR)
            for f in self.input_features)).reindexed(0)
        return fr.HostColumn(ft.OPVector, rows.astype(np.float32), meta=meta)
