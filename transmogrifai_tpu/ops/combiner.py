"""VectorsCombiner: concatenate vector blocks into the final feature vector.

Parity: reference ``core/.../stages/impl/feature/VectorsCombiner.scala`` —
N OPVector inputs concatenate in input order; metadata flattens with global
column reindexing (``OpVectorMetadata.flatten``).
"""

from __future__ import annotations

from dataclasses import replace
from typing import Optional

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import AllowLabelAsInput, DeviceTransformer
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (
    VectorColumnMetadata, VectorMetadata,
)

__all__ = ["VectorsCombiner", "PredictionToReal",
           "PredictionProbabilityVector", "PredictionRawVector"]


class VectorsCombiner(DeviceTransformer):
    variadic = True
    in_types = (ft.OPVector,)
    out_type = ft.OPVector

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    def device_apply(self, params, *cols: fr.VectorColumn) -> fr.VectorColumn:
        metas = []
        for i, c in enumerate(cols):
            m = c.metadata
            width = int(c.values.shape[1])
            if m is None or m.size != width:
                # anonymous per-column provenance for metadata-less inputs
                name = self.input_names[i]
                m = VectorMetadata(name, tuple(
                    VectorColumnMetadata((name,), ("OPVector",),
                                         descriptor_value=f"col_{j}")
                    for j in range(width)))
            # tag each block's columns with THEIR producing chain so
            # sibling blocks over the same raw feature (mean-fill vs tree
            # buckets of one Real) don't cross-attribute stages; inner
            # combiners' finer tags win
            block = self.input_names[i]
            m = VectorMetadata(
                m.name,
                tuple(col if col.parent_chain is not None
                      else replace(col, parent_chain=block)
                      for col in m.columns),
                m.history)
            metas.append(m)
        meta = VectorMetadata.flatten(self.get_output().name, metas)
        # vector-level lineage map (OpVectorMetadata.history analog): each
        # input block contributes its raw->derived stage chain, so the
        # combined vector can answer per-column history questions
        own = VectorMetadata.history_of(self.input_features)
        if own:
            merged = {e[0]: e for e in meta.history}
            merged.update({e[0]: e for e in own})
            meta = meta.with_history(tuple(merged.values()))
        vals = jnp.concatenate([c.values for c in cols], axis=1)
        return fr.VectorColumn(vals, meta)

    def transform_row(self, *values):
        return np.concatenate([np.asarray(v, dtype=np.float32).ravel()
                               for v in values])


class PredictionToReal(DeviceTransformer, AllowLabelAsInput):
    """Prediction -> RealNN prediction value (reference RichMapFeature's
    implicit Prediction=>RealNN extractor / ``tupled()``)."""

    in_types = (ft.Prediction,)
    out_type = ft.RealNN

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    def device_apply(self, params, col: fr.PredictionColumn) -> fr.NumericColumn:
        return fr.NumericColumn(col.prediction,
                                jnp.ones_like(col.prediction))

    def transform_row(self, p):
        return None if p is None else float(p["prediction"])


class _PredictionVectorBase(DeviceTransformer, AllowLabelAsInput):
    in_types = (ft.Prediction,)
    out_type = ft.OPVector
    _field = "probability"

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    def device_apply(self, params, col: fr.PredictionColumn) -> fr.VectorColumn:
        return fr.VectorColumn(getattr(col, self._field))

    def transform_row(self, p):
        if p is None:
            return None
        # one key-format contract: the Prediction type's own accessors
        pred = ft.Prediction(p)
        vals = (pred.probability if self._field == "probability"
                else pred.raw_prediction)
        return np.asarray(vals, np.float32)


class PredictionProbabilityVector(_PredictionVectorBase):
    """Prediction -> OPVector of class probabilities (reference
    Prediction=>OPVector probability extractor)."""
    _field = "probability"


class PredictionRawVector(_PredictionVectorBase):
    """Prediction -> OPVector of raw scores."""
    _field = "raw_prediction"
