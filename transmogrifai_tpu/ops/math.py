"""Generic math / value transformers.

Parity: reference ``core/.../stages/impl/feature/MathTransformers.scala``
(+ ``AliasTransformer``, ``ToOccurTransformer``, ``SubstringTransformer``,
``OpScalarStandardScaler``, ``FillMissingWithMean``, ``ScalerTransformer``)
— arithmetic over numeric features with None-propagation semantics matching
the reference's Option algebra, plus scaling estimators.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import (
    AllowLabelAsInput, DeviceTransformer, Estimator, HostTransformer,
)
from transmogrifai_tpu.types import feature_types as ft

__all__ = [
    "BinaryMathTransformer", "UnaryMathTransformer", "ScalarMathTransformer",
    "AliasTransformer", "ToOccurTransformer", "FillMissingWithMean",
    "OpScalarStandardScaler", "ScalerTransformer", "DescalerTransformer",
    "ExistsTransformer", "FilterValueTransformer", "ReplaceTransformer",
    "SubstringTransformer",
]

_BINARY_OPS = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "/": lambda a, b: a / b if b != 0 else None,
}

_UNARY_OPS = {
    "abs": abs,
    "ceil": lambda v: float(np.ceil(v)),
    "floor": lambda v: float(np.floor(v)),
    "round": lambda v: float(np.round(v)),
    "exp": lambda v: float(np.exp(v)),
    "sqrt": lambda v: float(np.sqrt(v)) if v >= 0 else None,
    "log": lambda v: float(np.log(v)) if v > 0 else None,
}


class BinaryMathTransformer(DeviceTransformer):
    """(Real, Real) -> Real elementwise op; missing propagates."""

    in_types = (ft.Real, ft.Real)
    out_type = ft.Real

    def __init__(self, op: str = "+", uid: Optional[str] = None):
        if op not in _BINARY_OPS:
            raise ValueError(f"Unknown op {op!r}")
        self.op = op
        super().__init__(operation_name=f"math_{op}", uid=uid)

    def device_apply(self, params, a: fr.NumericColumn, b: fr.NumericColumn):
        mask = a.mask * b.mask
        if self.op == "+":
            vals = a.values + b.values
        elif self.op == "-":
            vals = a.values - b.values
        elif self.op == "*":
            vals = a.values * b.values
        else:
            safe = jnp.where(b.values != 0, b.values, 1.0)
            vals = a.values / safe
            mask = mask * (b.values != 0)
        return fr.NumericColumn(vals * mask, mask)

    def transform_row(self, a, b):
        if a is None or b is None:
            return None
        return _BINARY_OPS[self.op](float(a), float(b))


class UnaryMathTransformer(DeviceTransformer):
    in_types = (ft.Real,)
    out_type = ft.Real

    def __init__(self, op: str = "abs", uid: Optional[str] = None):
        if op not in _UNARY_OPS:
            raise ValueError(f"Unknown op {op!r}")
        self.op = op
        super().__init__(operation_name=f"math_{op}", uid=uid)

    def device_apply(self, params, a: fr.NumericColumn):
        v = a.values
        mask = a.mask
        if self.op == "abs":
            out = jnp.abs(v)
        elif self.op == "ceil":
            out = jnp.ceil(v)
        elif self.op == "floor":
            out = jnp.floor(v)
        elif self.op == "round":
            out = jnp.round(v)
        elif self.op == "exp":
            out = jnp.exp(v)
        elif self.op == "sqrt":
            mask = mask * (v >= 0)
            out = jnp.sqrt(jnp.maximum(v, 0.0))
        else:  # log
            mask = mask * (v > 0)
            out = jnp.log(jnp.maximum(v, 1e-30))
        return fr.NumericColumn(out * mask, mask)

    def transform_row(self, a):
        return None if a is None else _UNARY_OPS[self.op](float(a))


class ScalarMathTransformer(DeviceTransformer):
    """Real op scalar (e.g. ``f * 2.5``, ``f ** 2``)."""

    in_types = (ft.Real,)
    out_type = ft.Real

    def __init__(self, op: str = "+", scalar: float = 0.0,
                 uid: Optional[str] = None):
        if op not in ("+", "-", "*", "/", "**"):
            raise ValueError(f"Unknown op {op!r}")
        self.op = op
        self.scalar = float(scalar)
        super().__init__(operation_name=f"math_{op}_scalar", uid=uid)

    def device_params(self):
        return jnp.float32(self.scalar)

    def device_apply(self, params, a: fr.NumericColumn):
        v, s = a.values, params
        out = {"+": v + s, "-": v - s, "*": v * s,
               "/": v / jnp.where(s != 0, s, 1.0),
               "**": jnp.sign(v) * jnp.abs(v) ** s}[self.op]
        return fr.NumericColumn(out * a.mask, a.mask)

    def transform_row(self, a):
        if a is None:
            return None
        s = self.scalar
        if self.op == "/" and s == 0:
            return None
        return {"+": a + s, "-": a - s, "*": a * s,
                "/": a / s if s != 0 else None,
                "**": float(np.sign(a) * abs(a) ** s)}[self.op]


class AliasTransformer(HostTransformer):
    """Identity rename (reference AliasTransformer)."""

    in_types = (ft.FeatureType,)
    out_type = ft.FeatureType

    def __init__(self, name: str = "alias", uid: Optional[str] = None):
        self.name = name
        super().__init__(operation_name="alias", uid=uid)

    def set_input(self, *features):
        super().set_input(*features)
        self.out_type = features[0].ftype
        return self

    def make_output_name(self) -> str:
        return self.name

    def transform_row(self, v):
        return v


class ToOccurTransformer(HostTransformer):
    """Any feature -> Binary occurrence (non-empty)."""

    in_types = (ft.FeatureType,)
    out_type = ft.Binary

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    def transform_row(self, v):
        if v is None:
            return False
        if isinstance(v, (list, set, dict, str)):
            return len(v) > 0
        return True


class ExistsTransformer(HostTransformer):
    """Any feature -> Binary via predicate (reference RichFeature ``exists``).

    A module-level importable predicate serializes via the ``mod:qualname``
    scheme (same contract as the external wrappers); a closure/lambda works
    in-memory but raises on save. It sees the plain python value
    (None = missing).
    """

    in_types = (ft.FeatureType,)
    out_type = ft.Binary

    def __init__(self, predicate=None, uid: Optional[str] = None):
        from transmogrifai_tpu.stages.external import _fn_from_path
        self.predicate = (_fn_from_path(predicate)
                          if isinstance(predicate, str) else predicate)
        super().__init__(operation_name="exists", uid=uid)

    def transform_row(self, v):
        return bool(self.predicate(v))

    def config(self) -> dict:
        from transmogrifai_tpu.stages.external import _fn_path
        return {"predicate": _fn_path(self.predicate)}


class FilterValueTransformer(HostTransformer):
    """Keep the value when the predicate holds, else the default (reference
    RichFeature ``filter``). Output type follows the input feature.

    Serializable when the predicate is a module-level importable function
    and the default is JSON-able (``mod:qualname`` scheme, same contract as
    the external wrappers)."""

    in_types = (ft.FeatureType,)
    out_type = ft.FeatureType

    def __init__(self, predicate=None, default=None,
                 uid: Optional[str] = None):
        from transmogrifai_tpu.stages.external import _fn_from_path
        self.predicate = (_fn_from_path(predicate)
                          if isinstance(predicate, str) else predicate)
        self.default = default
        super().__init__(operation_name="filter", uid=uid)

    def set_input(self, *features):
        super().set_input(*features)
        self.out_type = features[0].ftype
        return self

    def transform_row(self, v):
        return v if self.predicate(v) else self.default

    def config(self) -> dict:
        from transmogrifai_tpu.stages.external import _fn_path
        return {"predicate": _fn_path(self.predicate),
                "default": self.default}


class ReplaceTransformer(HostTransformer):
    """Replace matching values (reference RichFeature ``replaceWith``):
    value == old -> new, everything else passes through. None is a legal
    ``old``/``new`` (fill or clear)."""

    in_types = (ft.FeatureType,)
    out_type = ft.FeatureType

    def __init__(self, old=None, new=None, uid: Optional[str] = None):
        self.old = old
        self.new = new
        super().__init__(operation_name="replaceWith", uid=uid)

    def set_input(self, *features):
        super().set_input(*features)
        self.out_type = features[0].ftype
        return self

    def transform_row(self, v):
        return self.new if v == self.old else v


class SubstringTransformer(HostTransformer):
    """(Text sub, Text full) -> Binary: does ``full`` contain ``sub``
    (reference ``SubstringTransformer.scala`` / RichTextFeature
    ``isSubstring``). None if either side is missing."""

    in_types = (ft.Text, ft.Text)
    out_type = ft.Binary

    def __init__(self, to_lowercase: bool = True,
                 uid: Optional[str] = None):
        self.to_lowercase = bool(to_lowercase)
        super().__init__(operation_name="substring", uid=uid)

    def transform_row(self, sub, full):
        if sub is None or full is None:
            return None
        if self.to_lowercase:
            sub, full = sub.lower(), full.lower()
        return sub in full


class FillMissingWithMean(Estimator):
    """Real -> RealNN mean fill (reference FillMissingWithMean)."""

    in_types = (ft.Real,)
    out_type = ft.RealNN

    def __init__(self, default_value: float = 0.0, uid: Optional[str] = None):
        self.default_value = default_value
        super().__init__(uid=uid)

    def fit_model(self, data):
        col = data.device_col(self.input_names[0])
        s = float(jnp.sum(col.values * col.mask))
        c = float(jnp.sum(col.mask))
        return _MeanFillModel(mean=s / c if c > 0 else self.default_value)


class _MeanFillModel(DeviceTransformer):
    in_types = (ft.Real,)
    out_type = ft.RealNN

    def __init__(self, mean: float = 0.0, uid: Optional[str] = None):
        self.mean = mean
        super().__init__(uid=uid)

    def device_params(self):
        return jnp.float32(self.mean)

    def device_apply(self, params, col: fr.NumericColumn):
        vals = col.values * col.mask + params * (1.0 - col.mask)
        return fr.NumericColumn(vals, jnp.ones_like(col.mask))

    def transform_row(self, v):
        return self.mean if v is None else v

    def fitted_state(self):
        return {"mean": np.float64(self.mean)}

    def set_fitted_state(self, state):
        self.mean = float(state["mean"])


class OpScalarStandardScaler(Estimator):
    """Real -> RealNN z-normalization (reference OpScalarStandardScaler)."""

    in_types = (ft.Real,)
    out_type = ft.RealNN

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    def fit_model(self, data):
        col = data.device_col(self.input_names[0])
        c = jnp.maximum(jnp.sum(col.mask), 1.0)
        mean = jnp.sum(col.values * col.mask) / c
        var = jnp.sum(((col.values - mean) ** 2) * col.mask) / c
        sd = float(jnp.sqrt(jnp.maximum(var, 1e-12)))
        return ScalerTransformer(slope=1.0 / sd if sd > 0 else 1.0,
                                 intercept=-float(mean) / sd if sd > 0 else 0.0)


class ScalerTransformer(DeviceTransformer, AllowLabelAsInput):
    """Linear scaling v*slope + intercept, with metadata enabling
    descaling of downstream predictions (reference ScalerTransformer; may
    scale a response label — the scaled output stays a response)."""

    in_types = (ft.Real,)
    out_type = ft.RealNN

    def __init__(self, slope: float = 1.0, intercept: float = 0.0,
                 uid: Optional[str] = None):
        self.slope = float(slope)
        self.intercept = float(intercept)
        super().__init__(uid=uid)

    def device_params(self):
        return (jnp.float32(self.slope), jnp.float32(self.intercept))

    def device_apply(self, params, col: fr.NumericColumn):
        s, b = params
        return fr.NumericColumn((col.values * s + b) * col.mask, col.mask)

    def transform_row(self, v):
        return None if v is None else v * self.slope + self.intercept

    def fitted_state(self):
        return {"slope": np.float64(self.slope),
                "intercept": np.float64(self.intercept)}

    def set_fitted_state(self, state):
        self.slope = float(state["slope"])
        self.intercept = float(state["intercept"])


class DescalerTransformer(DeviceTransformer):
    """Inverse of a ScalerTransformer applied to a prediction feature."""

    in_types = (ft.Prediction,)
    out_type = ft.Prediction

    def __init__(self, slope: float = 1.0, intercept: float = 0.0,
                 uid: Optional[str] = None):
        self.slope = float(slope)
        self.intercept = float(intercept)
        super().__init__(uid=uid)

    def device_params(self):
        return (jnp.float32(self.slope), jnp.float32(self.intercept))

    def device_apply(self, params, col: fr.PredictionColumn):
        s, b = params
        pred = (col.prediction - b) / jnp.where(s != 0, s, 1.0)
        return fr.PredictionColumn(pred, col.raw_prediction, col.probability)

    def transform_row(self, pm):
        out = dict(pm)
        s = self.slope if self.slope != 0 else 1.0
        out["prediction"] = (pm["prediction"] - self.intercept) / s
        return out
