"""Pallas TPU kernel for the sorted-block histogram contraction.

The sorted tree engine (``models/trees._grow_tree_sorted``) computes, per
level, per-block grad/hess histograms

    part[b, s, f*B + k] = sum_c gh[b, s, c] * 1[Xp[b, c, f] == k]

followed by a block-axis cumsum (so per-node totals are two boundary
diffs). The XLA einsum path materializes the [blocks, C, d, B] one-hot in
HBM — host-fenced at ~80 ms/level at 1M x 28 x 64, i.e. ~53 GB/s of pure
one-hot traffic (the op is ~7 GFLOP, nowhere near MXU-bound). This kernel
builds each [C, B] one-hot tile in VMEM only and contracts it on the MXU,
so HBM traffic per level drops to reading Xp (int8 codes) + writing one
[2, d*B] f32 partial row per block.

The kernel is deliberately STATELESS per grid step (no cross-step
scratch): ``vmap`` batching prepends a grid axis, which would silently
break any ``program_id``-keyed accumulator reset — and the multiclass
ensemble always calls the grower under ``vmap``. Round 8's fold x
grid-stacked sweep (``models/trees.train_score_stacked``) nests two
MORE vmaps (fold x lane) on top; the same statelessness is what makes
those legal, and CPU CI asserts interpret-mode parity for the batched
shape against the einsum engine
(``tests/test_tree_stacked_sweep.py::test_stacked_engines_agree``).
The block cumsum stays outside (cheap: [nb, 2, d*B] is ~1/C the
one-hot size).

Parity: identical math to the einsum path (bf16 one-hot, f32
accumulation); CPU CI runs the same kernel in interpret mode.

Replaces (conceptually) the per-level histogram aggregation the reference
delegates to xgboost4j/Spark executors (SURVEY §2.7 P5); here the whole
level is one fused device pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["sorted_block_hist"]


def _kernel(xb_ref, gh_ref, exp_ref, out_ref, *, d: int, n_bins: int):
    """One grid step = one row-block, TWO full-width MXU dots.

    Measured lesson (round 5, host-fenced): the first kernel version did
    d=28 unrolled tiny [2,C]@[C,B] dots per block and lost to the XLA
    einsum by ~18% on per-step overhead. This version broadcasts the bin
    codes across the combined (feature, bin) axis with one constant
    one-hot matmul — xb_at = xb @ E, E[f, f*B+k] = 1, a [C,d]@[C? d,K]
    contraction with full C sublanes — then forms the one-hot by
    comparing against the per-column bin index and contracts with the
    [2, C] grad/hess rows. Bin codes are exact in bf16 up to 256, so the
    broadcast-by-matmul is exact for every supported binning (the
    wrapper rejects n_bins > 256).
    """
    xb = xb_ref[0].astype(jnp.bfloat16)       # [C, d] bin codes
    gh = gh_ref[0].astype(jnp.bfloat16)       # [2, C]
    E = exp_ref[...]                          # [d, K] bf16 expander
    C = xb.shape[0]
    B = n_bins
    K = d * B
    xb_at = jnp.dot(xb, E, preferred_element_type=jnp.float32)  # [C, K]
    k_of_j = (jax.lax.broadcasted_iota(jnp.int32, (C, K), 1)
              % B).astype(jnp.float32)
    eq = (xb_at == k_of_j).astype(jnp.bfloat16)                 # [C, K]
    out_ref[0] = jnp.dot(gh, eq, preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("n_bins", "interpret"))
def sorted_block_hist(Xpb, ghb, *, n_bins: int,
                      interpret: bool | None = None):
    """Per-block histogram partials ``part[b, s, f*B+k]``.

    Xpb: [nb, C, d] int8/int32 bin codes (node-pure blocks from the
    padded sorted layout); ghb: [nb, 2, C] f32 grad/hess rows (zero on
    padding). Returns [nb, 2, d*B] f32 block partials; the caller takes
    the block-axis cumsum + per-node boundary diffs.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if n_bins > 256:
        # the broadcast-by-matmul trick carries bin codes through bf16,
        # which is exact only for integers <= 256 — beyond that the
        # equality compare would silently misfire
        raise ValueError(
            f"sorted_block_hist supports n_bins <= 256 (got {n_bins}); "
            "use the einsum engine for wider binnings")
    nb, C, d = Xpb.shape
    B = n_bins
    K = d * B
    # constant expander: a block-broadcast identity — E[f, f*B+k] = 1
    # spreads each feature's bin code across its B output columns via one
    # exact bf16 matmul
    E = jnp.repeat(jnp.eye(d, dtype=jnp.bfloat16), B, axis=1)
    return pl.pallas_call(
        functools.partial(_kernel, d=d, n_bins=B),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((1, C, d), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2, C), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((d, K), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 2, K), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb, 2, K), jnp.float32),
        interpret=interpret,
    )(Xpb, ghb, E)
