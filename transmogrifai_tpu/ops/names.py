"""Human-name detection and name-entity tagging.

Parity targets:
- ``core/.../stages/impl/feature/HumanNameDetector.scala`` +
  ``core/.../utils/stages/NameDetectUtils.scala``: estimator that decides
  whether a Text column holds person names (dictionary hit-rate averaged
  over rows >= threshold), then per-row emits a NameStats map
  (isName/originalValue/gender) using an ordered list of gender-detection
  strategies (honorific scan, token index, last token).
- ``core/.../stages/impl/feature/NameEntityRecognizer.scala`` + OpenNLP
  tagger: Text -> MultiPickListMap of token -> entity tags.

The reference ships OpenNLP binary models + large census dictionaries; this
build uses compact built-in first-name/gender/honorific dictionaries (the
detection *mechanism* — monoid stats, threshold decision, strategy ordering,
sensitive-feature surfacing — is the parity contract, the dictionary is a
swappable resource). Host stages: string work stays off the device.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Optional, Sequence

from transmogrifai_tpu.stages.base import Estimator, HostTransformer
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["GenderDetectStrategy", "HumanNameDetector",
           "HumanNameDetectorModel", "NameEntityRecognizer",
           "MALE_NAMES", "FEMALE_NAMES", "NAME_DICTIONARY", "SURNAMES",
           "LOCATIONS", "ORG_SUFFIXES", "load_name_dictionaries"]

_TOKEN_RE = re.compile(r"[^\W\d_]+", re.UNICODE)

MALE_NAMES = frozenset(
    "james john robert michael william david richard joseph thomas charles "
    "christopher daniel matthew anthony mark donald steven paul andrew "
    "joshua kenneth kevin brian george timothy ronald edward jason jeffrey "
    "ryan jacob gary nicholas eric jonathan stephen larry justin scott "
    "brandon benjamin samuel gregory frank alexander raymond patrick jack "
    "dennis jerry tyler aaron jose adam nathan henry douglas zachary peter "
    "kyle noah ethan carlos juan luis miguel pedro diego omar ali ahmed "
    "mohammed muhammad mehmet mustafa ibrahim hassan hussein karim tariq "
    "wei jun ming hao lei chen hiroshi kenji takeshi satoshi yuki kazuo "
    "ichiro minho jihoon sung ivan dmitri sergei alexei mikhail nikolai "
    "vladimir boris pavel andrei pierre jean luc marcel francois jacques "
    "michel philippe henri hans klaus jurgen wolfgang dieter fritz stefan "
    "giovanni marco antonio giuseppe luigi paolo francesco alessandro "
    "lorenzo matteo rafael santiago javier fernando alejandro ricardo "
    "eduardo roberto sergio pablo manuel raj amit sanjay vijay arjun rahul "
    "ravi anil sunil deepak krishnan lars erik sven bjorn nils olaf piotr "
    "jakub tomasz marek kofi kwame chidi emeka ade oluwaseun abdul rashid "
    "walter arthur albert harold ernest eugene ralph howard leon oscar "
    "felix hugo leo max victor simon martin".split())

FEMALE_NAMES = frozenset(
    "mary patricia jennifer linda elizabeth barbara susan jessica sarah "
    "karen lisa nancy betty margaret sandra ashley kimberly emily donna "
    "michelle carol amanda dorothy melissa deborah stephanie rebecca sharon "
    "laura cynthia kathleen amy angela shirley anna brenda pamela emma "
    "nicole helen samantha katherine christine debra rachel carolyn janet "
    "catherine maria heather diane ruth julie olivia joyce virginia grace "
    "sofia isabella mia charlotte amelia harper luna camila elena fatima "
    "aisha amina leila zainab yasmin noor mei ling xiu hua yan li yuki "
    "sakura hana akiko yoko keiko naomi jiwoo minji soyeon ingrid "
    "anastasia natasha svetlana olga irina tatiana ekaterina yelena marie "
    "claire chloe camille sophie juliette amelie celine margot giulia "
    "francesca chiara alessia martina valentina lucia carmen rosa pilar "
    "dolores mercedes josefina ana lucia priya ananya divya kavya lakshmi "
    "meera pooja astrid freja sigrid maja ewa agnieszka katarzyna zofia "
    "ngozi chiamaka folake abebi alice clara eva julia laura lena mila "
    "nina rosa sara vera iris ivy jade hazel".split())

SURNAMES = frozenset(
    "smith johnson williams brown jones garcia miller davis rodriguez "
    "martinez hernandez lopez gonzalez wilson anderson thomas taylor moore "
    "jackson martin lee perez thompson white harris sanchez clark ramirez "
    "lewis robinson walker young allen king wright scott torres nguyen "
    "hill flores green adams nelson baker hall rivera campbell mitchell "
    "carter roberts gomez phillips evans turner diaz parker cruz edwards "
    "collins reyes stewart morris morales murphy cook rogers gutierrez "
    "ortiz morgan cooper peterson bailey reed kelly howard ramos kim cho "
    "park choi kang wang li zhang liu chen yang huang zhao wu zhou xu sun "
    "ma zhu hu lin guo he gao luo tanaka suzuki takahashi watanabe ito "
    "yamamoto nakamura kobayashi saito kato singh kumar sharma patel gupta "
    "khan ahmed hussain ali shah ivanov petrov sidorov smirnov kuznetsov "
    "popov volkov muller schmidt schneider fischer weber meyer wagner "
    "becker schulz hoffmann dubois bernard durand moreau laurent lefebvre "
    "rossi russo ferrari esposito bianchi romano colombo ricci silva "
    "santos oliveira souza pereira costa ferreira almeida nowak kowalski "
    "wisniewski andersson johansson karlsson nilsson eriksson larsen "
    "hansen olsen jensen nielsen okafor okonkwo adeyemi mensah osei".split())

LOCATIONS = frozenset(
    "london paris berlin madrid rome amsterdam brussels vienna zurich "
    "geneva dublin lisbon athens warsaw prague budapest bucharest moscow "
    "kyiv istanbul ankara cairo lagos nairobi johannesburg capetown accra "
    "casablanca tokyo osaka kyoto seoul busan beijing shanghai shenzhen "
    "guangzhou hongkong taipei singapore bangkok jakarta manila hanoi "
    "mumbai delhi bangalore chennai kolkata karachi lahore dhaka sydney "
    "melbourne brisbane perth auckland wellington newyork chicago boston "
    "seattle portland denver austin dallas houston phoenix miami atlanta "
    "detroit philadelphia baltimore toronto vancouver montreal ottawa "
    "mexico bogota lima santiago buenosaires saopaulo rio brasilia "
    "america england france germany spain italy portugal netherlands "
    "belgium austria switzerland ireland poland czechia hungary romania "
    "greece russia ukraine turkey egypt nigeria kenya ghana morocco japan "
    "korea china india pakistan bangladesh australia canada brazil "
    "argentina chile peru colombia".split())

#: organization-name suffixes (the OpenNLP organization tag analog)
ORG_SUFFIXES = frozenset(
    "inc corp corporation ltd llc llp plc gmbh ag sa srl bv oy ab co "
    "company group holdings industries technologies solutions systems "
    "labs laboratories partners ventures capital bank university institute "
    "foundation association society".split())

#: full name dictionary for hit-rate detection (the reference's census
#: NameDictionary spans first AND last names; gender stays on the gendered
#: first-name sets)
NAME_DICTIONARY = MALE_NAMES | FEMALE_NAMES | SURNAMES


def load_name_dictionaries(path: str) -> dict[str, int]:
    """Swap in external (census-scale) dictionaries — the pretrained-asset
    hook. The reference ships OpenNLP binaries + census name lists under
    ``models/``; here a directory of plain-text files (one entry per line,
    case-insensitive) replaces the built-ins per file present:
    ``male.txt``, ``female.txt``, ``surnames.txt``, ``locations.txt``.
    A present-but-empty file replaces the built-in with the EMPTY set
    (how you disable a category); missing files keep the built-ins.
    Returns {file stem: entry count}. Also honored at import via
    ``TRANSMOGRIFAI_NAME_DICT``.
    """
    global MALE_NAMES, FEMALE_NAMES, SURNAMES, LOCATIONS, NAME_DICTIONARY
    loaded: dict[str, int] = {}

    def read(stem: str, builtin: frozenset) -> frozenset:
        p = os.path.join(path, f"{stem}.txt")
        if not os.path.isfile(p):
            return builtin
        with open(p, encoding="utf-8") as fh:
            entries = frozenset(
                line.strip().lower() for line in fh if line.strip())
        loaded[stem] = len(entries)
        return entries

    MALE_NAMES = read("male", MALE_NAMES)
    FEMALE_NAMES = read("female", FEMALE_NAMES)
    SURNAMES = read("surnames", SURNAMES)
    LOCATIONS = read("locations", LOCATIONS)
    NAME_DICTIONARY = MALE_NAMES | FEMALE_NAMES | SURNAMES
    return loaded


def _autoload() -> None:
    path = os.environ.get("TRANSMOGRIFAI_NAME_DICT")
    if not path:
        return
    if not os.path.isdir(path):
        import warnings
        warnings.warn(
            f"TRANSMOGRIFAI_NAME_DICT={path!r} is not a directory; keeping "
            "built-in name dictionaries", RuntimeWarning)
        return
    load_name_dictionaries(path)


_autoload()

MALE_HONORIFICS = frozenset({"mr", "mister", "sir"})
FEMALE_HONORIFICS = frozenset({"ms", "mrs", "miss", "madam"})


def _tokens(value: Optional[str]) -> list[str]:
    if not value:
        return []
    return [t.lower() for t in _TOKEN_RE.findall(value)]


@dataclass(frozen=True)
class GenderDetectStrategy:
    """Serializable gender strategy (reference GenderDetectStrategy ADT):
    kind in {FindHonorific, ByIndex, ByLast}; ByIndex carries the token
    index."""

    kind: str = "FindHonorific"
    index: int = 0

    def detect(self, tokens: Sequence[str]) -> str:
        """-> 'Male' | 'Female' | 'GenderNA'."""
        if self.kind == "FindHonorific":
            for t in tokens:
                if t in MALE_HONORIFICS:
                    return "Male"
                if t in FEMALE_HONORIFICS:
                    return "Female"
            return "GenderNA"
        if self.kind == "ByIndex":
            toks = [t for t in tokens if t not in MALE_HONORIFICS
                    and t not in FEMALE_HONORIFICS]
            if self.index < len(toks):
                return _gender_of(toks[self.index])
            return "GenderNA"
        if self.kind == "ByLast":
            return _gender_of(tokens[-1]) if tokens else "GenderNA"
        return "GenderNA"

    def key(self) -> str:
        return (f"ByIndex({self.index})" if self.kind == "ByIndex"
                else f"{self.kind}()")


def _gender_of(token: str) -> str:
    if token in MALE_NAMES:
        return "Male"
    if token in FEMALE_NAMES:
        return "Female"
    return "GenderNA"


DEFAULT_STRATEGIES = (
    GenderDetectStrategy("FindHonorific"),
    GenderDetectStrategy("ByIndex", 0),
    GenderDetectStrategy("ByLast"),
)


@dataclass
class NameDetectStats:
    """Monoid of per-column name evidence (reference NameDetectStats):
    averaged dictionary hit fraction + per-strategy gender tallies."""

    count: int = 0
    dict_hits: float = 0.0
    gender_counts: dict = field(default_factory=dict)  # strategy -> [m, f, na]

    def add(self, value: Optional[str],
            strategies: Sequence[GenderDetectStrategy]) -> None:
        toks = _tokens(value)
        if not toks:
            return
        self.count += 1
        self.dict_hits += sum(
            1 for t in toks if t in NAME_DICTIONARY) / len(toks)
        for s in strategies:
            tally = self.gender_counts.setdefault(s.key(), [0, 0, 0])
            g = s.detect(toks)
            tally[0 if g == "Male" else 1 if g == "Female" else 2] += 1

    def merge(self, other: "NameDetectStats") -> "NameDetectStats":
        self.count += other.count
        self.dict_hits += other.dict_hits
        for k, v in other.gender_counts.items():
            t = self.gender_counts.setdefault(k, [0, 0, 0])
            for i in range(3):
                t[i] += v[i]
        return self

    @property
    def predicted_name_prob(self) -> float:
        return self.dict_hits / self.count if self.count else 0.0


class HumanNameDetector(Estimator):
    """Text -> NameStats. Fit decides treat-as-name and orders gender
    strategies by how often they resolved a gender (fewest GenderNA first,
    mirroring the reference's orderGenderStrategies)."""

    in_types = (ft.Text,)
    out_type = ft.NameStats

    def __init__(self, threshold: float = 0.5, uid: Optional[str] = None):
        self.threshold = float(threshold)
        super().__init__(uid=uid)

    def fit_model(self, data) -> "HumanNameDetectorModel":
        col = data.host_col(self.input_names[0])
        stats = NameDetectStats()
        for v in col.values:
            stats.add(v, DEFAULT_STRATEGIES)
        treat = stats.predicted_name_prob >= self.threshold
        ordered: list[GenderDetectStrategy] = []
        if treat:
            def na_count(s: GenderDetectStrategy) -> int:
                return stats.gender_counts.get(s.key(), [0, 0, 0])[2]
            ordered = sorted(DEFAULT_STRATEGIES, key=na_count)
        model = HumanNameDetectorModel(
            treat_as_name=treat,
            strategies=[{"kind": s.kind, "index": s.index} for s in ordered])
        model.metadata = {
            "treatAsName": treat,
            "predictedNameProb": stats.predicted_name_prob,
            "genderResultsByStrategy": dict(stats.gender_counts),
        }
        return model


class HumanNameDetectorModel(HostTransformer):
    in_types = (ft.Text,)
    out_type = ft.NameStats

    def __init__(self, treat_as_name: bool = False,
                 strategies: Sequence[dict] = (),
                 uid: Optional[str] = None):
        self.treat_as_name = bool(treat_as_name)
        self.strategies = [dict(s) for s in strategies]
        self.metadata: Optional[dict] = None
        super().__init__(uid=uid)

    def transform_row(self, value):
        if not self.treat_as_name:
            return {}
        toks = _tokens(value)
        if not toks:
            return {}  # a missing value is not a detected name
        gender = "GenderNA"
        for s in self.strategies:
            g = GenderDetectStrategy(s["kind"], s.get("index", 0)).detect(toks)
            if g != "GenderNA":
                gender = g
                break
        return {"isName": "true", "originalValue": value or "",
                "gender": gender}


#: ViterbiTagger IO tags -> the reference's entity names
_NER_LABELS = {"PER": "Person", "LOC": "Location", "ORG": "Organization"}


class NameEntityRecognizer(HostTransformer):
    """Text -> MultiPickListMap token -> {entity tags}.

    The reference runs OpenNLP's binary NER models per sentence; the same
    asset pipeline here: when a sequence model is loaded (``ops/ner.py``
    ``TRANSMOGRIFAI_NER_MODEL`` hook, or passed directly) the tagger's
    Viterbi decode drives the tags; otherwise a dictionary/heuristic tagger
    over Person (first names + surnames, with a capitalized-followed-by-
    surname bigram rule), Location, and Organization (capitalized token
    preceding a corporate suffix). Capitalization distinguishes 'Mark
    asked' from 'mark the date' — the same disambiguation role the
    statistical model plays."""

    in_types = (ft.Text,)
    out_type = ft.MultiPickListMap

    def __init__(self, require_capitalized: bool = True,
                 model=None, model_path: Optional[str] = None,
                 uid: Optional[str] = None):
        self.require_capitalized = bool(require_capitalized)
        self.model_path = model_path
        if model is None and model_path:
            from transmogrifai_tpu.ops.ner import load_tagger
            model = load_tagger(model_path)
        self.model = model
        super().__init__(uid=uid)

    def config(self) -> dict:
        # `model` is an in-memory ViterbiTagger (numpy arrays) — persist
        # the PATH, not the object; a directly-injected pathless model
        # cannot round-trip (same contract as unserializable lambdas)
        if self.model is not None and not self.model_path:
            raise NotImplementedError(
                "NameEntityRecognizer with a directly-injected model is "
                "not serializable; pass model_path=... instead")
        return {"require_capitalized": self.require_capitalized,
                "model_path": self.model_path}

    def _tagger(self):
        if self.model is not None:
            return self.model
        from transmogrifai_tpu.ops.ner import default_tagger
        return default_tagger()

    def transform_row(self, value):
        if not value:
            return {}
        raw_toks = _TOKEN_RE.findall(value)
        out: dict[str, set] = {}
        tagger = self._tagger()
        if tagger is not None:
            for tok, io_tag in zip(raw_toks, tagger.tag(raw_toks)):
                label = _NER_LABELS.get(io_tag)
                # the configured capitalization gate applies on the model
                # path too — ambient env state must not change semantics
                if label and (not self.require_capitalized
                              or tok[:1].isupper()):
                    out.setdefault(tok.lower(), set()).add(label)
            return out

        def tag(token: str, label: str) -> None:
            out.setdefault(token.lower(), set()).add(label)

        for i, raw in enumerate(raw_toks):
            low = raw.lower()
            capital_ok = (not self.require_capitalized
                          or raw[:1].isupper())
            nxt = raw_toks[i + 1] if i + 1 < len(raw_toks) else ""
            if capital_ok:
                if low in NAME_DICTIONARY:  # spans first + last names
                    tag(raw, "Person")
                    # "John Smithfield": an unknown capitalized token right
                    # after a first name reads as its surname
                    if low in NAME_DICTIONARY and nxt[:1].isupper() \
                            and nxt.lower() not in LOCATIONS:
                        tag(nxt, "Person")
                if low in LOCATIONS:
                    tag(raw, "Location")
                if nxt.lower() in ORG_SUFFIXES:
                    tag(raw, "Organization")
                    tag(nxt, "Organization")
        return out
